"""Jitted step builders — the TPU hot path.

The reference's per-step work is eager autograd driven from a Python batch
loop (``examples/tinysys/tinysys/classifier.py:29-35``:
zero_grad -> forward -> loss -> backward -> step). Here the whole step is a
single pure function lowered once through ``jax.jit``:

* forward + loss via ``jax.value_and_grad`` (autograd seam),
* optimizer update fused into the same XLA program,
* the :class:`~tpusystem.train.state.TrainState` argument is **donated**, so
  parameters and optimizer slots update in place in HBM (no copy),
* gradient all-reduce over the mesh data axis is inserted by GSPMD when the
  batch is sharded — the step body is identical on 1 chip and on a pod.

Metrics consumed by the event bus must read only the returned loss/outputs
*after* the phase completes (one device->host sync per phase, never per
batch) — the cadence the reference models with ``metrics.compute()``
(``examples/tinysys/tinysys/metrics.py:19-23``).
"""

from __future__ import annotations

from collections.abc import Callable
from inspect import signature
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpusystem.train.optim import masked_update
from tpusystem.train.state import TrainState

# apply_fn contract: (params, inputs, rng, train) -> outputs
ApplyFn = Callable[[Any, Any, jax.Array | None, bool], Any]
# criterion contract: (outputs, targets) -> scalar loss
Criterion = Callable[[Any, Any], jax.Array]


def flax_apply(module) -> ApplyFn:
    """Adapt a flax linen module to the step-builder apply contract.

    Passes ``train=`` and dropout RNGs only when the module's ``__call__``
    accepts them, so simple modules stay simple.
    """
    parameters = signature(module.__call__).parameters
    accepts_train = 'train' in parameters

    def apply(params, inputs, rng=None, train=False):
        kwargs = {'train': train} if accepts_train else {}
        rngs = {'dropout': rng} if rng is not None else None
        return module.apply({'params': params}, inputs, rngs=rngs, **kwargs)

    return apply


def build_train_step(apply_fn: ApplyFn, criterion: Criterion, optimizer,
                     *, accumulate: int = 1, jit: bool = True,
                     guard=None, fault=None):
    """Build ``step(state, inputs, targets) -> (state, (outputs, loss))``.

    ``optimizer`` is a :class:`tpusystem.train.optim.Optimizer` or a raw
    ``optax.GradientTransformation``. The returned step donates ``state``:
    callers must treat the passed-in state as consumed.

    ``guard=`` (a :class:`tpusystem.train.sentinel.Guard`) compiles anomaly
    detection into the same XLA program: loss/global-grad-norm finiteness
    plus an EMA grad-norm spike z-score, with the optimizer update
    suppressed in-graph on a bad step
    (:func:`tpusystem.train.optim.masked_update`) — no extra dispatch, no
    host sync. The statistics ride ``state.health``
    (:class:`~tpusystem.train.state.HealthStats`); arm the state with
    ``guard.arm(state)`` before the first step. The step counter still
    advances on a suppressed step (the batch was consumed — PaLM-style
    skip), while the optimizer's own count does not (schedules see only
    applied updates).

    ``fault=`` is the chaos-drill seam: a traced callable
    ``(step, grads, loss) -> (grads, loss)`` applied right after the
    gradient computation (``step`` is the 1-based index of the step being
    computed). Production code leaves it None; the chaos harness injects
    :class:`tpusystem.parallel.chaos.CorruptGrads` here to drill the
    guard's escalation ladder end-to-end.

    ``accumulate=N`` splits the leading batch dimension into N sequential
    microbatches inside the step (``lax.scan``), averaging gradients
    before the single optimizer update — the activation-memory lever when
    the target global batch does not fit (grads add one params-sized
    buffer; activations shrink by N). When the criterion exposes
    ``weight(targets)`` (the masked LM losses return their unmasked-token
    count), microbatch losses and grads are weighted by it, so the result
    equals the full-batch step even when padding gives microbatches
    different token counts; criteria without ``weight`` are averaged
    equally (exact for per-example-mean losses). With accumulation, the
    returned ``outputs`` are the final microbatch's and ``loss`` is the
    weighted mean over microbatches.

    For activation rematerialisation use per-layer checkpointing at the
    model level (e.g. ``GPT2(remat=True)``) — whole-forward checkpointing
    here would double FLOPs without reducing backward peak memory.
    """
    transform = optimizer.transform() if hasattr(optimizer, 'transform') else optimizer

    def objective(params, inputs, targets, dropout_rng):
        outputs = apply_fn(params, inputs, dropout_rng, True)
        return criterion(outputs, targets), outputs

    def step(state: TrainState, inputs, targets):
        state, dropout_rng = state.next_rng()
        if accumulate == 1:
            (loss, outputs), grads = jax.value_and_grad(
                objective, has_aux=True)(state.params, inputs, targets,
                                         dropout_rng)
        else:
            batch = jax.tree.leaves(inputs)[0].shape[0]
            assert batch % accumulate == 0, (
                f'batch {batch} not divisible by accumulate={accumulate}')
            split = lambda leaf: leaf.reshape(
                (accumulate, batch // accumulate) + leaf.shape[1:])
            micro = (jax.tree.map(split, inputs), jax.tree.map(split, targets),
                     jax.random.split(dropout_rng, accumulate))
            params = state.params

            weight_fn = getattr(criterion, 'weight', None)

            def one(carry, xs):
                grads_acc, loss_acc, weight_acc, _ = carry
                micro_inputs, micro_targets, rng = xs
                (loss, outputs), grads = jax.value_and_grad(
                    objective, has_aux=True)(params, micro_inputs,
                                             micro_targets, rng)
                weight = (jnp.float32(weight_fn(micro_targets)) if weight_fn
                          else jnp.float32(1.0))
                # outputs ride the CARRY (last microbatch wins): stacking
                # them as scan ys would materialize the full-batch outputs
                # buffer this feature exists to avoid
                return (jax.tree.map(
                            lambda acc, g: acc + g.astype(jnp.float32) * weight,
                            grads_acc, grads),
                        loss_acc + loss * weight, weight_acc + weight,
                        outputs), None

            first = jax.tree.map(lambda leaf: leaf[0], micro)
            output_shapes = jax.eval_shape(
                lambda *xs: objective(params, *xs)[1], *first[:2], first[2])
            empty = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), output_shapes)
            # grads accumulate in float32 regardless of param dtype (exact
            # token-count weights + stable sums; standard practice), cast
            # back to the param dtype for the optimizer
            zeros = jax.tree.map(
                lambda leaf: jnp.zeros(leaf.shape, jnp.float32), params)
            (grads, loss_sum, weight_sum, outputs), _ = jax.lax.scan(
                one, (zeros, jnp.float32(0), jnp.float32(0), empty), micro)
            weight_sum = jnp.maximum(weight_sum, 1e-8)  # all-pad batch guard
            grads = jax.tree.map(
                lambda g, p: (g / weight_sum).astype(p.dtype), grads, params)
            loss = loss_sum / weight_sum
        current = state.step + 1
        if fault is not None:
            grads, loss = fault(current, grads, loss)
        if guard is not None:
            assert state.health is not None, (
                'guard= needs health stats on the TrainState: arm it with '
                'Guard.arm(state) before the first step')
            health, ok = guard.judge(state.health, loss, grads)
            params, opt_state = masked_update(
                transform, grads, state.opt_state, state.params, ok,
                scale=health.lr_scale)
            state = state.replace(params=params, opt_state=opt_state,
                                  step=current, health=health)
            return state, (outputs, loss)
        updates, opt_state = transform.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        state = state.replace(params=params, opt_state=opt_state, step=current)
        return state, (outputs, loss)

    return jax.jit(step, donate_argnums=0) if jit else step


def build_multi_step(step, *, jit: bool = True, outputs_fn=None,
                     guard: bool = False):
    """Wrap an (unjitted) train step into N steps per host dispatch.

    ``multi(state, inputs, targets) -> (state, losses)`` where inputs and
    targets carry a leading ``steps`` dimension (``[N, batch, ...]``) and
    ``losses`` is the per-step ``[N]`` float32 vector. One ``lax.scan``
    runs the N steps in a single compiled program, so per-dispatch host
    overhead (~7 ms through a tunneled-TPU relay; one Python round trip
    anywhere) is paid once per N batches instead of per batch — the
    amortization ``bench.py`` applies that the training service otherwise
    never gets. Each distinct ``N`` compiles its own program (a
    :func:`grouped_batches` tail group shorter than ``size`` costs one
    extra compile, cached thereafter). Per-phase metrics stay exact: feed
    the whole loss vector
    to the accumulator (``Mean``/``Perplexity`` accept arrays), and keep
    events at phase cadence as before.

    ``step`` must be built with ``jit=False`` (it is traced into the scan).
    Per-step ``outputs`` are dropped by default — stacking N output pytrees
    would materialize exactly the buffers the fused-loss path avoids. Pass
    ``outputs_fn`` (e.g. ``lambda o: jnp.argmax(o, -1)`` for classifier
    predictions) to stack a *reduced* output per step instead; the return
    becomes ``(state, (stacked_reduced_outputs, losses))``.

    ``guard=True`` (for a ``step`` built with ``guard=``) additionally
    stacks each step's health row (``state.health.last``,
    :data:`tpusystem.train.sentinel.HEALTH_COLUMNS`), so the host-side
    :class:`~tpusystem.train.sentinel.Sentinel` reviews every step of the
    dispatch at the same single phase-cadence sync: the return becomes
    ``(state, (losses, health[N, 4]))`` (health last when ``outputs_fn``
    is also given).
    """
    def multi(state: TrainState, inputs, targets):
        if guard:
            assert state.health is not None, (
                'guard=True needs a guarded step and an armed state '
                '(Guard.arm) — see build_train_step(guard=...)')
        def body(state, xs):
            micro_inputs, micro_targets = xs
            state, (outputs, loss) = step(state, micro_inputs, micro_targets)
            loss = jnp.asarray(loss, jnp.float32)
            ys = (loss,) if outputs_fn is None else (outputs_fn(outputs), loss)
            if guard:
                ys = ys + (state.health.last,)
            return state, ys[0] if len(ys) == 1 else ys
        return jax.lax.scan(body, state, (inputs, targets))
    return jax.jit(multi, donate_argnums=0) if jit else multi


def build_multi_eval_step(step, *, jit: bool = True, outputs_fn=None):
    """Eval counterpart of :func:`build_multi_step`:
    ``multi(state, inputs, targets) -> losses[N]`` (or
    ``(stacked_reduced_outputs, losses)`` with ``outputs_fn``) over stacked
    batches (``step`` from ``build_eval_step(..., jit=False)``)."""
    def multi(state: TrainState, inputs, targets):
        def body(carry, xs):
            outputs, loss = step(state, xs[0], xs[1])
            loss = jnp.asarray(loss, jnp.float32)
            if outputs_fn is None:
                return carry, loss
            return carry, (outputs_fn(outputs), loss)
        _, ys = jax.lax.scan(body, jnp.int32(0), (inputs, targets))
        return ys
    return jax.jit(multi) if jit else multi


def grouped_batches(loader, size: int):
    """Yield tuples of ``[n, batch, ...]`` stacks of up to ``size``
    consecutive batches — the host-side feeder for
    :func:`build_multi_step`. Accepts loaders yielding tuples (``(inputs,
    targets)``) or bare arrays; the tail stack is shorter when the loader
    length doesn't divide ``size``. A shorter tail is a *distinct shape*
    to the jitted scan in ``build_multi_step`` — it compiles once per
    distinct group length, so a non-dividing loader pays one extra
    compile for the remainder group (cached across epochs of the same
    length; pick ``size`` dividing the epoch, or feed the remainder to
    the per-batch step, if that compile matters).

    Device-resident batches stack with ``jnp.stack`` (stays on device —
    ``np.stack`` would round-trip every batch through the host, which on
    a tunneled TPU costs more than the steps it feeds); host arrays stack
    with ``np.stack``."""
    group: list = []

    def flush():
        return tuple(
            jnp.stack(parts) if isinstance(parts[0], jax.Array)
            else np.stack(parts)
            for parts in zip(*group))

    for batch in loader:
        group.append(batch if isinstance(batch, tuple) else (batch,))
        if len(group) == size:
            yield flush()
            group = []
    if group:
        yield flush()


def build_eval_step(apply_fn: ApplyFn, criterion: Criterion, *, jit: bool = True):
    """Build ``step(state, inputs, targets) -> (outputs, loss)`` (no grads,
    deterministic forward) — the ``inference_mode`` analogue."""

    def step(state: TrainState, inputs, targets):
        outputs = apply_fn(state.params, inputs, None, False)
        return outputs, criterion(outputs, targets)

    return jax.jit(step) if jit else step


def build_1f1b_train_step(model, criterion: Criterion, optimizer,
                          *, jit: bool = True):
    """1F1B-scheduled train step for pipelined models (``GPT2Pipelined``).

    Same ``step(state, inputs, targets) -> (state, (outputs, loss))``
    contract as :func:`build_train_step` (``outputs`` is None — microbatch
    outputs never exist whole under 1F1B), but the forward/backward runs
    through :func:`tpusystem.parallel.pipeline.pipeline_train`: backwards
    interleave with forwards so the per-stage activation stash is bounded
    by the stage count instead of the microbatch count. Use when
    activation memory, not step time, binds (see ``pipeline_train``'s
    bubble-FLOPs tradeoff).

    The model supplies the decomposition: ``_embed`` (head), ``_block_fn``
    (stage body), ``_head`` (tail, composed with ``criterion``); its tied
    embedding appears in both head and tail and both gradient
    contributions are summed inside ``pipeline_train``.
    """
    from tpusystem.parallel.pipeline import pipeline_train

    if getattr(model, 'moe_experts', 0):
        raise ValueError(
            'build_1f1b_train_step does not support MoE spans (the router '
            'aux channel rides the GPipe path only) — use build_train_step')

    transform = optimizer.transform() if hasattr(optimizer, 'transform') else optimizer

    def tail_fn(replicated, activations, micro_targets):
        return criterion(model._head(replicated, activations), micro_targets)

    train = pipeline_train(model._embed, model._block_fn(), tail_fn,
                           model.mesh, microbatches=model.microbatches,
                           weight_fn=getattr(criterion, 'weight', None),
                           interleave=getattr(model, 'interleave', 1))

    stacked_key = getattr(model, 'stacked_key', 'h')

    def step(state: TrainState, inputs, targets):
        replicated = {key: value for key, value in state.params.items()
                      if key != stacked_key}
        loss, (d_replicated, d_stacked) = train(
            replicated, state.params[stacked_key], inputs, targets)
        grads = dict(d_replicated, **{stacked_key: d_stacked})
        updates, opt_state = transform.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        state = state.replace(params=params, opt_state=opt_state,
                              step=state.step + 1)
        return state, (None, loss)

    return jax.jit(step, donate_argnums=0) if jit else step


def init_state(module, optimizer, sample_inputs, *, rng: int | jax.Array = 0,
               param_dtype=None) -> TrainState:
    """Initialize a :class:`TrainState` for a flax module.

    Runs ``module.init`` on the sample batch shape, initializes optimizer
    slots, and seeds the carried RNG stream.
    """
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    init_rng, carry_rng = jax.random.split(rng)
    parameters = signature(module.__call__).parameters
    kwargs = {'train': False} if 'train' in parameters else {}
    variables = module.init(init_rng, sample_inputs, **kwargs)
    params = variables['params']
    if param_dtype is not None:
        params = jax.tree.map(lambda leaf: leaf.astype(param_dtype), params)
    transform = optimizer.transform() if hasattr(optimizer, 'transform') else optimizer
    return TrainState.create(params, transform.init(params), carry_rng)
