"""Divergence sentinel: in-graph anomaly detection + host-side escalation.

PR 3 made training survive *external* faults (preemption, worker loss, torn
checkpoints); this module defends against *internal* ones — a NaN/Inf
gradient, a loss/grad-norm spike, a silently corrupted replica — before they
reach the optimizer and poison every checkpoint after them. The design
follows the production playbook: PaLM (Chowdhery et al., 2022) restarted
from the last checkpoint and *skipped the offending batches* on loss spikes;
MegaScale (Jiang et al., 2024) showed that automated in-band health
detection, not human dashboards, is what keeps long runs at high goodput.

Three layers, cheapest first:

1. **In-graph detection** (:class:`Guard`, compiled into the step by
   ``build_train_step(..., guard=Guard())``): loss/global-grad-norm
   finiteness plus an EMA-based grad-norm spike z-score, computed *inside*
   the jitted step. The statistics ride the :class:`~tpusystem.train.state.
   TrainState` pytree (:class:`~tpusystem.train.state.HealthStats`), so they
   checkpoint and donate for free. On a bad step the optimizer update is
   suppressed by ``optax.apply_if_finite``-style masking
   (:func:`tpusystem.train.optim.masked_update`) — one fused program, no
   extra host sync, params and moments untouched.
2. **Host-side policy** (:class:`Sentinel`): consumes the per-step health
   vector at the existing phase-cadence sync point and escalates —
   skip-batch (already done in-graph) → LR backoff via
   ``HealthStats.lr_scale`` (no recompile) → automatic rollback to the last
   committed checkpoint *before* the anomaly with a skip-window over the
   offending cursor range → bounded give-up
   (:class:`~tpusystem.parallel.recovery.DivergenceError`, exit code 44 in
   the restart contract). Every transition is a domain event
   (:mod:`tpusystem.observe.events`), so the ledger and TensorBoard see it.
3. **SDC parity** (:meth:`Sentinel.check_parity`): periodic cross-replica
   hash of DP-replicated params — a cheap all-gather of per-leaf bit
   checksums over the mesh data axis
   (:func:`tpusystem.parallel.collectives.replica_checksums`) — flags a
   diverged replica *before* it contaminates a checkpoint.

Every rung of the ladder is drill-tested by the chaos harness
(``tpusystem.parallel.chaos``: ``CorruptGrads``, ``CorruptBatch``,
``FlipParamBit``) the same way PR 3's kill/resume was.

Typical wiring — everything host-facing stays at phase cadence (per-step
``int(state.step)``/saves would serialize every dispatch against the host,
exactly the sync the in-graph guard exists to avoid)::

    guard = Guard(zmax=6.0)
    step = build_train_step(apply_fn, criterion, optimizer, guard=guard)
    state = guard.arm(init_state(module, optimizer, sample))
    sentinel = Sentinel(checkpointer=ckpt, identity=identity, loader=loader,
                        producer=runtime.producer, model=model)
    for epoch in range(epochs):
        for batch in loader:
            state, (_, loss) = step(state, *batch)   # no host sync here
        state = sentinel.review(state)      # phase cadence: ONE host sync
        sentinel.check_parity(state, mesh)  # before the save can commit
        ckpt.save(identity, int(state.step), state,
                  extras=resume_extras(state, loader))

(With ``build_multi_step(..., guard=True)`` the dispatch returns the
``[N, 4]`` per-step health matrix — pass it to ``review(state, health)``
so every step of the group is judged at the same single sync.)
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpusystem.parallel.recovery import DivergenceError
from tpusystem.registry import register
from tpusystem.train.state import HealthStats, TrainState

logger = logging.getLogger('tpusystem.sentinel')

__all__ = ['Guard', 'Sentinel', 'HEALTH_COLUMNS', 'HEALTH_OK', 'HEALTH_LOSS',
           'HEALTH_GNORM', 'HEALTH_Z', 'DivergenceError']

# layout of HealthStats.last / the per-step health vector rows
HEALTH_COLUMNS = ('ok', 'loss', 'gnorm', 'zscore')
HEALTH_OK, HEALTH_LOSS, HEALTH_GNORM, HEALTH_Z = range(4)


@register
class Guard:
    """In-graph anomaly detector — the ``guard=`` recipe of a train step.

    A registered entity like the optimizers: its hyperparameters capture
    into the experiment identity hash (a run with a different spike
    threshold is a different experiment).

    Args:
        ema: decay of the grad-norm EMAs (bias-corrected at read time).
        zmax: spike threshold in robust standard deviations — a *finite*
            step whose global grad norm sits more than ``zmax`` sigmas above
            the EMA mean is suppressed like a non-finite one.
        warmup: healthy steps folded into the EMAs before the spike detector
            arms (early variance estimates are meaningless; finiteness
            checks are always armed).
        spike_floor: relative sigma floor (fraction of the EMA mean) so a
            very stable grad-norm history cannot turn ordinary jitter into
            phantom spikes.
    """

    def __init__(self, ema: float = 0.98, zmax: float = 6.0,
                 warmup: int = 20, spike_floor: float = 0.05):
        self.ema = ema
        self.zmax = zmax
        self.warmup = warmup
        self.spike_floor = spike_floor

    def arm(self, state: TrainState) -> TrainState:
        """Attach fresh :class:`HealthStats` to the state (idempotent)."""
        if state.health is not None:
            return state
        return state.replace(health=HealthStats.create())

    def judge(self, health: HealthStats, loss, grads):
        """Traced verdict: ``(new_health, ok)`` for one step's (loss, grads).

        Runs inside the jitted step — everything is branch-free ``where``
        arithmetic on scalars plus one ``optax.global_norm`` reduction, so
        the guard adds no dispatch and no host sync. Anomalous steps do not
        fold into the EMAs (the statistic that detects an anomaly must not
        be poisoned by it) and do not advance the warmup count.
        """
        gnorm = optax.global_norm(grads)
        loss = jnp.asarray(loss, jnp.float32)
        finite = jnp.isfinite(loss) & jnp.isfinite(gnorm)
        safe_gnorm = jnp.where(jnp.isfinite(gnorm), gnorm, 0.0).astype(jnp.float32)

        decay = jnp.float32(self.ema)
        # bias-corrected EMA read (Adam-style): EMAs start at zero
        bias = 1.0 - decay ** jnp.maximum(health.count, 1).astype(jnp.float32)
        mean = health.ema_norm / bias
        variance = jnp.maximum(health.ema_sq / bias - mean ** 2, 0.0)
        sigma = jnp.sqrt(variance + (self.spike_floor * mean) ** 2 + 1e-12)
        zscore = (safe_gnorm - mean) / sigma
        armed = health.count >= self.warmup
        spike = armed & finite & (zscore > self.zmax)
        ok = finite & ~spike

        fold = ok  # healthy steps only
        ema_norm = jnp.where(fold, decay * health.ema_norm
                             + (1.0 - decay) * safe_gnorm, health.ema_norm)
        ema_sq = jnp.where(fold, decay * health.ema_sq
                           + (1.0 - decay) * safe_gnorm ** 2, health.ema_sq)
        row = jnp.stack([ok.astype(jnp.float32), loss,
                         gnorm.astype(jnp.float32), zscore])
        new = health.replace(
            ema_norm=ema_norm, ema_sq=ema_sq,
            count=health.count + fold.astype(jnp.int32),
            bad_steps=health.bad_steps + (1 - ok.astype(jnp.int32)),
            last=row)
        return new, ok


class Sentinel:
    """Host-side escalation policy over the guarded step's health vector.

    Consumes per-step health rows at the phase-cadence sync point (the one
    existing device→host transfer per phase — never per step) and walks the
    ladder: count skipped steps → LR backoff → rollback+skip-window →
    bounded give-up. All thresholds are measured over a sliding ``window``
    of the most recently reviewed steps.

    Args:
        checkpointer: :class:`tpusystem.checkpoint.Checkpointer` for the
            rollback rung (None disables rollback — the ladder tops out at
            backoff).
        identity: checkpoint identity (registry hash) of the run.
        loader: the data loader; on rollback its *current* cursor is kept
            (never rewound), which is exactly the PaLM skip-window over the
            offending batches.
        producer: event bus (``runtime.producer`` or any object with
            ``dispatch``) every transition is published on.
        model: host-side aggregate (or identity string) carried in events.
        window: sliding window (in reviewed steps) the thresholds count over.
        backoff_after: bad steps in the window before LR backoff level 1;
            level ``n+1`` needs ``backoff_after * (n+1)``.
        backoff_factor: per-level multiplier applied to
            ``HealthStats.lr_scale``.
        max_backoffs: backoff levels before the ladder stops deepening.
        recover_after: consecutive healthy steps before ``lr_scale`` resets
            to 1.0.
        rollback_after: bad steps in the window that trigger rollback to the
            newest committed checkpoint *preceding* the first bad step.
            Rollback preempts backoff once reached, so rungs above level
            ``rollback_after / backoff_after - 1`` only exist when no
            checkpointer is configured — the defaults (2/6) give backoff
            two rungs (2 and 4 bad steps) before rollback takes over at 6;
            set ``rollback_after <= 2 * backoff_after`` to deliberately
            skip the deeper backoff levels.
        max_rollbacks: rollbacks before the bounded give-up
            (:class:`DivergenceError`).
        on_backoff: optional hook ``(level, scale) -> None`` — the seam for
            loss-scale or schedule adjustments beyond the built-in update
            scaling.
    """

    def __init__(self, *, checkpointer: Any = None, identity: str | None = None,
                 loader: Any = None, producer: Any = None, model: Any = None,
                 window: int = 32, backoff_after: int = 2,
                 backoff_factor: float = 0.5, max_backoffs: int = 4,
                 recover_after: int = 32, rollback_after: int = 6,
                 max_rollbacks: int = 2,
                 on_backoff: Callable[[int, float], None] | None = None):
        self.checkpointer = checkpointer
        self.identity = identity
        self.loader = loader
        self.producer = producer
        self.model = model
        self.window = window
        self.backoff_after = backoff_after
        self.backoff_factor = backoff_factor
        self.max_backoffs = max_backoffs
        self.recover_after = recover_after
        self.rollback_after = rollback_after
        self.max_rollbacks = max_rollbacks
        if checkpointer is not None and identity is None:
            raise ValueError(
                'Sentinel(checkpointer=...) needs identity= too — the '
                'rollback rung restores by identity, and discovering that '
                'at rollback time would crash the recovery path itself')
        self.on_backoff = on_backoff
        self.rollbacks = 0
        self.level = 0
        self._recent: deque[tuple[int, bool]] = deque(maxlen=window)
        self._streak = 0

    # ------------------------------------------------------------------
    # event plumbing

    def _emit(self, event: Any) -> None:
        if self.producer is not None:
            self.producer.dispatch(event)

    def _subject(self) -> Any:
        return self.model if self.model is not None else self.identity

    # ------------------------------------------------------------------
    # the ladder

    def review(self, state: TrainState, health: Any = None) -> TrainState:
        """Digest the health rows since the last review; escalate if needed.

        ``health`` is a ``[n, 4]`` stack of per-step rows (what
        ``build_multi_step(..., guard=True)`` returns) or None to read the
        single most recent row from ``state.health.last`` (per-batch loops
        reviewing at phase cadence should collect rows themselves or accept
        last-row granularity). This call is the one device→host sync of the
        phase. Returns the (possibly rolled-back or rescaled) state; raises
        :class:`DivergenceError` when the ladder is exhausted.
        """
        if health is None:
            if state.health is None:
                raise ValueError('state has no health stats: build the step '
                                 'with guard= and arm the state (Guard.arm)')
            health = state.health.last
        rows = np.atleast_2d(np.asarray(jax.device_get(health), np.float32))
        end = int(state.step)
        first_step = end - len(rows) + 1
        for offset, row in enumerate(rows):
            at = first_step + offset
            ok = bool(row[HEALTH_OK] >= 0.5)
            self._recent.append((at, ok))
            if ok:
                self._streak += 1
                continue
            self._streak = 0
            loss, gnorm = float(row[HEALTH_LOSS]), float(row[HEALTH_GNORM])
            kind = ('nonfinite' if not (np.isfinite(loss) and np.isfinite(gnorm))
                    else 'spike')
            logger.warning('anomalous step %d (%s): loss=%g grad_norm=%g '
                           'z=%.2f — update suppressed', at, kind, loss,
                           gnorm, float(row[HEALTH_Z]))
            from tpusystem.observe.events import AnomalyDetected
            self._emit(AnomalyDetected(model=self._subject(), step=at,
                                       kind=kind, loss=loss, gnorm=gnorm,
                                       zscore=float(row[HEALTH_Z])))
        bad = [at for at, ok in self._recent if not ok]
        if len(bad) >= self.rollback_after and self.checkpointer is not None:
            return self._rollback(state, first_bad=min(bad), step=end)
        if (bad and len(bad) >= self.backoff_after * (self.level + 1)
                and self.level < self.max_backoffs):
            self.level += 1
            return self._apply_scale(state, self.backoff_factor ** self.level,
                                     step=end)
        if self.level and self._streak >= self.recover_after:
            self.level = 0
            # the healthy streak outlived the window's memory of the burst:
            # forget it, or the stale bad steps would re-trigger a backoff
            # on the very next review
            self._recent.clear()
            return self._apply_scale(state, 1.0, step=end)
        return state

    def _apply_scale(self, state: TrainState, scale: float,
                     step: int) -> TrainState:
        from tpusystem.observe.events import BackoffApplied
        health = state.health.replace(lr_scale=jnp.asarray(scale, jnp.float32))
        logger.warning('sentinel backoff level %d: lr_scale=%g at step %d',
                       self.level, scale, step)
        self._emit(BackoffApplied(model=self._subject(), step=step,
                                  level=self.level, scale=scale))
        if self.on_backoff is not None and self.level:
            # the hook sees backoffs only; the recovery reset (level 0,
            # scale 1.0) is built-in and announced by the event alone
            self.on_backoff(self.level, scale)
        return state.replace(health=health)

    def _rollback(self, state: TrainState, first_bad: int,
                  step: int) -> TrainState:
        if self.rollbacks >= self.max_rollbacks:
            raise DivergenceError(
                f'divergence persists after {self.rollbacks} rollback(s): '
                f'{len([1 for _, ok in self._recent if not ok])} bad steps '
                f'in the last {len(self._recent)} at step {step}', step=step)
        committed = self.checkpointer.committed(self.identity)
        candidates = [at for at in committed if at < first_bad]
        if not candidates:
            raise DivergenceError(
                f'no committed checkpoint predates the anomaly at step '
                f'{first_bad} (committed: {committed or "none"})',
                step=step)
        target = max(candidates)
        restored = self.checkpointer.restore(self.identity, state,
                                             epoch=target)
        # rollback resets the backoff ladder: the restored state carries
        # its CHECKPOINTED lr_scale (saved before the burst), and keeping
        # self.level escalated would desynchronize host policy from device
        # state — the window/streak counters restart clean below, so a
        # recurring anomaly re-escalates backoff before the next rollback
        self.level = 0
        # PaLM skip-window: the loader is NOT rewound — training continues
        # from the current cursor, so the batches consumed between the
        # rollback target and now are skipped, never replayed
        extras = self.checkpointer.extras(self.identity, target)
        window = {'from': (extras or {}).get('cursor'),
                  'to': self.loader.state() if self.loader is not None else None}
        # steps after the target are a dead branch now: discard them so
        # post-rollback saves cannot collide with stale step numbers
        self.checkpointer.discard_after(self.identity, target)
        self.rollbacks += 1
        self._recent.clear()
        self._streak = 0
        logger.warning('sentinel rollback #%d: step %d -> %d, skip-window %s',
                       self.rollbacks, step, target, window)
        from tpusystem.observe.events import RolledBack
        self._emit(RolledBack(model=self._subject(), step=step,
                              to_step=target, window=window))
        return restored

    # ------------------------------------------------------------------
    # SDC parity

    def check_parity(self, state: TrainState | Any, mesh, *, axis: str = 'data',
                     raise_on_mismatch: bool = True):
        """Cross-replica parity check of DP-replicated params.

        Hashes every param leaf per data-axis replica (order-independent
        bit checksums, all-gathered over ``axis`` — see
        :func:`tpusystem.parallel.collectives.replica_checksums`) and
        compares rows. Run this at checkpoint cadence, *before* the save:
        a replica corrupted by an SDC (bit flip, bad HBM) is flagged here
        instead of contaminating the checkpoint.

        Returns None when all replicas agree; on a mismatch emits
        :class:`~tpusystem.observe.events.ReplicaDiverged` and raises
        :class:`DivergenceError` (or, with ``raise_on_mismatch=False``,
        returns ``(bad_replicas, bad_leaves)``). A strict majority
        attributes the minority replicas; without one (two replicas, or an
        even split) the culprit is ambiguous and EVERY replica of the
        disagreeing column is reported — never an arbitrary side of a tie.
        """
        from tpusystem.parallel import collectives
        params = state.params if isinstance(state, TrainState) else state
        matrix, paths = collectives.replica_checksums(params, mesh, axis=axis)
        if bool(np.all(matrix == matrix[0])):
            return None
        bad_replicas: set[int] = set()
        bad_leaves: list[str] = []
        for column in range(matrix.shape[1]):
            values, counts = np.unique(matrix[:, column], return_counts=True)
            if len(values) == 1:
                continue
            bad_leaves.append(paths[column])
            if np.sum(counts == counts.max()) > 1:
                # no strict majority (e.g. two replicas, or a 2-2 split):
                # attribution is ambiguous — report every replica rather
                # than arbitrarily blaming one side of the tie
                bad_replicas.update(range(matrix.shape[0]))
                continue
            majority = values[np.argmax(counts)]
            bad_replicas.update(
                int(r) for r in np.nonzero(matrix[:, column] != majority)[0])
        step = int(state.step) if isinstance(state, TrainState) else None
        replicas = sorted(bad_replicas)
        logger.error('cross-replica parity FAILED: replica(s) %s diverge on '
                     '%d leaf/leaves (e.g. %s)', replicas, len(bad_leaves),
                     bad_leaves[:3])
        from tpusystem.observe.events import ReplicaDiverged
        self._emit(ReplicaDiverged(model=self._subject(), step=step,
                                   replicas=replicas, leaves=bad_leaves))
        if raise_on_mismatch:
            raise DivergenceError(
                f'silent data corruption: replica(s) {replicas} on mesh axis '
                f'{axis!r} diverge on {len(bad_leaves)} param leaf/leaves '
                f'(e.g. {bad_leaves[:3]}); do NOT checkpoint — restart from '
                f'the last committed step', step=step)
        return replicas, bad_leaves
