"""Autoregressive text generation with a KV cache.

The inference counterpart of the training stack: ``generate`` clones an LM
module into decode mode (KV caches in the flax ``'cache'`` collection,
absolute positions from the cache cursor), prefills the prompt in one
forward pass, then decodes one token per step under ``lax.scan`` — the
whole sampling loop is a single compiled program, no host round-trip per
token. Works with any module exposing the family conventions
(:class:`tpusystem.models.GPT2` / :class:`~tpusystem.models.Llama`):
a ``decode`` field, logits output, and ``max_seq`` capacity.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from tpusystem.train.cursors import gather_rows as _gather_rows
from tpusystem.train.cursors import rewind as _rewind


def _decoder(module, per_row: bool = False):
    """Clone the module into decode mode: xla attention (flash/ring make no
    sense one token at a time), no dropout, logits output (MoE models drop
    their aux/router term — it only exists for the training loss). The
    mesh field is dropped too — the decode path never reads it, and an
    unhashable live mesh would defeat the compiled-program cache.

    ``per_row=True`` (the speculative path) switches the KV-cache writes
    to per-row scatter so each sequence advances by its own acceptance;
    ordinary generation keeps the faster shared-cursor
    ``dynamic_update_slice`` (see ``cached_attention``)."""
    updates: dict = {'decode': True}
    # decode_pages resets too: the paged layout needs externally managed
    # block tables (tpusystem.serve.Engine sets it on ITS clone after
    # this) — generate()'s own loops always run the contiguous cache
    for field, value in (('attention', 'xla'), ('dropout', 0.0),
                         ('return_features', False), ('remat', False),
                         ('mesh', None), ('per_row_decode', per_row),
                         ('decode_pages', None)):
        if hasattr(module, field):
            updates[field] = value
    return dataclasses.replace(module, **updates)


STREAM_DTYPES = ('auto', 'float32', 'bfloat16', 'int8', 'fp8')


def _stream_params(decoder, params, stream_dtype: str):
    """Transform the streamed param tree per ``generate``'s
    ``stream_dtype``: pre-cast f32 matrix leaves to the compute dtype
    (``'auto'`` — no-op for f32-compute modules — or an explicit
    ``'bfloat16'``), or quantize them to per-channel-scaled narrow
    leaves (``'int8'``/``'fp8'``). ``'float32'`` streams the masters
    untouched."""
    if stream_dtype == 'float32':
        return params
    if stream_dtype not in STREAM_DTYPES:
        raise ValueError(f'unknown stream_dtype {stream_dtype!r}; '
                         f'expected one of {STREAM_DTYPES}')
    if stream_dtype == 'auto':
        compute = jnp.dtype(getattr(decoder, 'dtype', jnp.float32))
        if compute.itemsize >= jnp.dtype(jnp.float32).itemsize:
            return params
        return _caster(compute.name)(params)
    if stream_dtype == 'bfloat16':
        return _caster('bfloat16')(params)
    if stream_dtype == 'fp8':
        from tpusystem.ops.precision import fp8_unsupported_reason
        reason = fp8_unsupported_reason()
        if reason is not None:
            raise ValueError(f"stream_dtype='fp8' is unavailable here: "
                             f'{reason}')
    return _quantizer(stream_dtype)(params)


@functools.cache
def _caster(compute_name: str):
    """One cached jitted cast program per target dtype: per-leaf eager
    casts would pay a host dispatch each (~60 relay round-trips per
    generate() call), and an uncached jit would *retrace and recompile*
    the cast every call (measured 8x slower decode)."""
    compute = jnp.dtype(compute_name)

    def cast(path, leaf):
        # leaves the model consumes at f32 must stay f32: embedding
        # tables (the embed step ADDS wte+wpe rows in f32 before
        # casting; the scan-hoisted head cast keeps the head matmul
        # bf16 anyway) and MoE routers (gate logits are an f32 matmul —
        # a bf16-rounded router could flip near-tie expert choices)
        from tpusystem.parallel.sharding import leaf_path
        path = leaf_path(path)
        if 'embedding' in path or 'router' in path:
            return leaf
        if leaf.ndim >= 2 and leaf.dtype == jnp.float32:
            return leaf.astype(compute)
        return leaf

    return jax.jit(functools.partial(jax.tree_util.tree_map_with_path, cast))


@functools.cache
def _quantizer(mode: str):
    """One cached jitted quantize program per narrow mode — the same
    retrace trap ``_caster`` pins (an uncached jit would retrace the
    whole-tree quantization on every ``generate`` call; measured 8x
    slower decode for the caster's version of this mistake). The leaf
    rule (matrices only, embedding/router excluded) lives in
    :func:`tpusystem.ops.precision.quantize_streamed`."""
    from tpusystem.ops.precision import quantize_streamed
    return jax.jit(functools.partial(quantize_streamed, mode=mode))


def streamed_bytes(module, params, stream_dtype: str) -> int:
    """Per-step streamed bytes of :func:`generate`'s param tree under one
    ``stream_dtype`` — the decode roofline quantity (weight bytes
    crossing HBM per token step; quantized modes count narrow values
    plus their per-channel scales, and embeddings/routers/vectors stay
    f32 per the leaf rule). The one accounting shared by ``bench.py``,
    ``benchmarks/decode_roofline.py``, and the dryrun decode stage."""
    streamed = _stream_params(_decoder(module), params, stream_dtype)
    return sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(streamed))


def _dequant(params, decoder):
    """Dequantized view of a (possibly) quantized streamed tree in the
    module's compute dtype — called INSIDE the compiled decode loop's
    body so the narrow values stay the HBM-resident operand (identity —
    same tree object, zero bits changed — for unquantized trees)."""
    from tpusystem.ops.precision import dequantize_streamed
    compute = jnp.dtype(getattr(decoder, 'dtype', jnp.float32))
    return dequantize_streamed(params, compute)


def _sample(logits, temperature: float, rng):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / temperature
    return jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)


def sampling_key(seed, position):
    """Threefry counter key for one token slot: a pure function of
    ``(seed, position)``. The serving engine derives every sampled
    token's key this way, so the RNG carries NO mutable state — a
    journal that records the emitted prefix (and the request's seed)
    already records everything replay needs, and the same
    ``(seed, position)`` pair reproduces the same key on any engine,
    any process, any host."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), position)


def sample_token(logits, seed, position, temperature, top_k, top_p, mask):
    """Deterministically sample ONE token from one ``[vocab]`` logits row.

    The single sampling primitive shared by the serving engine's jitted
    decode step, its prefill programs, and the speculative verify — so a
    token's identity is a pure function of
    ``(logits, seed, position, temperature, top_k, top_p, mask)`` and
    nothing else. Contract pins:

    * ``temperature == 0`` (or ``top_k == 1``) reproduces greedy argmax
      bitwise — both branches run under ``jnp.where``, so the same
      compiled program serves greedy and sampled rows side by side.
    * ``top_k > 0`` keeps the k highest logits; ``top_p < 1`` keeps the
      smallest prefix of the sorted distribution whose mass *before*
      each token stays under ``top_p`` (the first token always
      survives). Ties break by ``jnp.argsort``'s stable order —
      deterministic across runs and devices.
    * ``mask`` (bool ``[vocab]``) zeroes disallowed tokens before
      everything else — the grammar/JSON structured-output hook. An
      all-``False`` mask is a caller error (validated host-side).

    Scalar args should arrive as jnp-typed values (``jnp.uint32`` seed,
    ``jnp.int32`` position/top_k, ``jnp.float32`` temperature/top_p) so
    jitted callers never retrace on Python scalar weak types. Vmaps
    over rows: every arg but ``logits``/``mask`` is per-row scalar."""
    logits = jnp.where(mask, logits.astype(jnp.float32), -jnp.inf)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # the 1e-6 floor keeps the temperature==0 branch finite (its value
    # is discarded by the final where, but NaNs would poison both sides)
    scaled = logits / jnp.maximum(temperature, jnp.float32(1e-6))
    order = jnp.argsort(-scaled)                     # stable: ties by id
    ranked = jnp.take(scaled, order)
    rank = jnp.arange(logits.shape[-1])
    keep = jnp.where(top_k > 0, rank < top_k, True)
    probs = jax.nn.softmax(ranked)
    mass_before = jnp.cumsum(probs) - probs
    keep = keep & (mass_before < top_p)
    filtered = jnp.zeros_like(scaled).at[order].set(
        jnp.where(keep, ranked, -jnp.inf))
    sampled = jax.random.categorical(
        sampling_key(seed, position), filtered).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def generate(module, params, prompt, *, steps: int,
             temperature: float = 0.0, rng=None,
             stream_dtype: str = 'auto', decode_impl: str = 'auto'):
    """Generate ``steps`` tokens after ``prompt``.

    Args:
        module: the trained LM module (its ``decode=True`` clone is used).
        params: trained parameters.
        prompt: int32 ``[batch, prompt_len]`` token ids.
        steps: tokens to generate per sequence.
        temperature: 0 = greedy argmax; otherwise categorical sampling.
        rng: ``jax.random`` key (required when ``temperature > 0``).
        stream_dtype: what the decode loop streams from HBM each step —
            decode at small batch is weight-STREAMING bound, so this is
            the tokens/sec lever (benchmarks/decode_roofline.py).
            ``'auto'`` (default) pre-casts float32 matrix kernels
            (ndim >= 2) to the module's compute dtype when that dtype is
            narrower: a bf16-compute model casts its f32 kernels to bf16
            at every use anyway, so the cast changes which bytes stay
            resident, not the matmul numerics. ``'bfloat16'`` forces
            that cast regardless of the compute dtype (identical program
            to ``'auto'`` on bf16 modules; bf16-rounds the weights of
            f32 modules). ``'int8'`` / ``'fp8'`` quantize the same
            leaves with per-output-channel symmetric scales
            (:func:`tpusystem.ops.precision.quantize_streamed`) —
            2x/2x fewer weight bytes than bf16, dequantized per use
            inside the loop body (or in-kernel under the fused impl),
            greedy tokens equal up to the bounded quantization error;
            ``'fp8'`` needs the capability probe
            (:func:`~tpusystem.ops.precision.fp8_unsupported_reason`)
            to pass. In every mode, leaves the model consumes at f32
            are untouched: embedding tables (the embed step adds
            wte+wpe rows in f32 — for GPT-2 the tied table is the part
            whose footprint does not shrink), MoE routers (f32 gate
            logits), and vector leaves (biases, layernorm scales).
            ``'float32'`` streams the masters untouched (the training
            layout).
        decode_impl: which token-step runs the decode loop. ``'flax'``
            is the module's own apply (the reference path);
            ``'fused'`` the Pallas fused decode chain
            (:mod:`tpusystem.train.decode_fused`: activation resident
            in VMEM, weights — quantized or not — streamed tile-by-tile,
            fc→gelu→proj in one kernel), raising when the module is
            outside its scope; ``'auto'`` (default) picks ``'fused'``
            on TPU where supported and ``'flax'`` elsewhere.

    Returns:
        int32 ``[batch, prompt_len + steps]`` — prompt plus generation.
    """
    if steps < 1:
        raise ValueError(f'steps must be >= 1, got {steps}')
    if temperature > 0.0 and rng is None:
        raise ValueError('temperature sampling needs an rng key')
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    decoder = _decoder(module)
    params = _stream_params(decoder, params, stream_dtype)
    if prompt.shape[1] + steps > decoder.max_seq:
        raise ValueError(
            f'prompt ({prompt.shape[1]}) + steps ({steps}) exceeds the '
            f'cache capacity max_seq={decoder.max_seq}')
    impl = _resolve_impl(decode_impl, decoder)
    if impl == 'fused':
        from tpusystem.train import decode_fused
        try:
            run = decode_fused.compiled_fused(decoder, steps, temperature)
        except TypeError:   # unhashable module field (e.g. a live mesh)
            run = decode_fused.build_fused(decoder, steps, temperature)
        return run(params, prompt, rng)
    try:
        # jit caches key on function identity: reuse one compiled program
        # per (decoder config, steps, temperature) across generate() calls
        run = _compiled(decoder, steps, temperature)
    except TypeError:       # unhashable module field (e.g. a live mesh)
        run = _build(decoder, steps, temperature)
    return run(params, prompt, rng)


def _resolve_impl(decode_impl: str, decoder) -> str:
    """'flax' | 'fused' for this decode clone. 'auto' is conservative:
    fused only on TPU backends (where the Pallas kernels compile to real
    streaming; elsewhere they would run interpreted) and only for
    modules inside the fused step's scope."""
    if decode_impl not in ('auto', 'flax', 'fused'):
        raise ValueError(f'unknown decode_impl {decode_impl!r}; '
                         "expected 'auto', 'flax' or 'fused'")
    if decode_impl == 'flax':
        return 'flax'
    from tpusystem.train.decode_fused import fused_unsupported_reason
    reason = fused_unsupported_reason(decoder)
    if decode_impl == 'fused':
        if reason is not None:
            raise ValueError(
                f"decode_impl='fused' cannot run this module: {reason}")
        return 'fused'
    if reason is None and jax.default_backend() in ('tpu', 'axon'):
        return 'fused'
    return 'flax'


def speculative_generate(module, params, prompt, *, steps: int,
                         draft_module, draft_params, speculate: int = 4,
                         temperature: float = 0.0, rng=None,
                         stream_dtype: str = 'auto', tree_fanout: int = 1):
    """Generation accelerated by a draft model (speculative decoding).

    The draft proposes ``speculate`` tokens autoregressively (cheap model,
    cheap steps); the target verifies them in ONE forward over the
    proposed window, emitting the accepted prefix plus one corrected
    token — so each target forward yields between 1 and ``speculate + 1``
    tokens, and a bad draft only costs speed, never correctness:

    * ``temperature=0``: acceptance is exact match against the target's
      greedy choices — **output is exactly the target's greedy decode**
      in window-length-invariant arithmetic (CPU float32, or TPU with
      ``jax_default_matmul_precision='highest'``). At the TPU MXU's
      DEFAULT precision, f32 matmul operands are truncated to bfloat16
      with tilings that depend on the query-window length, so the
      verify's K+1-token windows and plain decode's 1-token windows can
      round a near-tie argmax differently (~1e-2 logit scatter measured
      on a v5e) — rare content-dependent token flips, each still a
      greedy choice within platform tolerance.
    * ``temperature>0``: rejection-sampling acceptance (Leviathan et al.):
      draft token ``d`` is accepted with probability ``min(1, p(d)/q(d))``
      and a rejection resamples from ``norm(max(0, p - q))`` — the output
      **distribution** is exactly the target's sampling distribution.

    Both KV caches rewind their cursors to the accepted prefix each round.
    Cache cursors are **per-row** (the caches write and mask at each row's
    own depth), so every sequence advances by its own acceptance count —
    one slow row no longer drags the whole batch to its acceptance, and
    the speedup survives batching: the verify forward runs the WHOLE
    batch's K+1-token windows through one weight pass, so its streaming
    cost amortizes over every row (the batch-1 trajectory is reproduced
    row for row — pinned by tests). Rows that reach ``steps`` idle
    (their cursor and output stop advancing) until the slowest row
    finishes.

    ``stream_dtype`` applies :func:`generate`'s weight-streaming modes to
    the target AND draft param trees (quantized modes dequantize inside
    each round's bodies, so the verify pass streams narrow bytes too).

    ``tree_fanout=F > 1`` switches greedy decoding to **token-tree
    verify**: each sequence drafts ``F`` branches — the draft's top-F
    first tokens, each continued greedily to ``speculate`` tokens — and
    the target verifies all branches as extra batch rows in the SAME
    single forward (one weight pass, ``batch*F`` verify rows). The
    branch with the longest accepted prefix wins the round, so
    acceptance length grows without extra target passes; losing
    branches' cache rows are overwritten from the winner before the
    next round. Greedy only (``temperature=0`` — every branch's
    accepted tokens are target-greedy-verified, so the output is still
    **exactly the target's greedy decode**); capacity accounting is
    unchanged (the tree widens the batch, not the window).

    Returns int32 ``[batch, prompt_len + steps]`` like :func:`generate`.
    """
    if steps < 1:
        raise ValueError(f'steps must be >= 1, got {steps}')
    if speculate < 1:
        raise ValueError(f'speculate must be >= 1, got {speculate}')
    if tree_fanout < 1:
        raise ValueError(f'tree_fanout must be >= 1, got {tree_fanout}')
    if tree_fanout > 1 and temperature > 0.0:
        raise ValueError(
            'tree_fanout > 1 implements greedy token-tree verify only; '
            'rejection-sampling over trees is not implemented — use '
            'tree_fanout=1 for temperature sampling')
    if temperature > 0.0 and rng is None:
        raise ValueError('temperature sampling needs an rng key')
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    decoder = _decoder(module, per_row=True)
    drafter = _decoder(draft_module, per_row=True)
    params = _stream_params(decoder, params, stream_dtype)
    draft_params = _stream_params(drafter, draft_params, stream_dtype)
    needed = prompt.shape[1] + steps + speculate + 1
    capacity = min(decoder.max_seq, drafter.max_seq)
    if needed > capacity:
        raise ValueError(
            f'prompt + steps + speculate + 1 = {needed} exceeds the cache '
            f'capacity max_seq={capacity} (verification overshoots by up to '
            f'speculate tokens before rewinding)')
    if tree_fanout > 1:
        if tree_fanout > drafter.vocab_size:
            raise ValueError(f'tree_fanout ({tree_fanout}) exceeds the '
                             f'draft vocab ({drafter.vocab_size})')
        try:
            run = _compiled_speculative_tree(decoder, drafter, steps,
                                             speculate, tree_fanout)
        except TypeError:   # unhashable module field
            run = _build_speculative_tree(decoder, drafter, steps,
                                          speculate, tree_fanout)
        return run(params, draft_params, prompt, rng)
    try:
        run = _compiled_speculative(decoder, drafter, steps, speculate,
                                    temperature)
    except TypeError:       # unhashable module field
        run = _build_speculative(decoder, drafter, steps, speculate,
                                 temperature)
    return run(params, draft_params, prompt, rng)


@functools.cache
def _compiled_speculative(decoder, drafter, steps: int, speculate: int,
                          temperature: float):
    return _build_speculative(decoder, drafter, steps, speculate, temperature)


def _build_speculative(decoder, drafter, steps: int, speculate: int,
                       temperature: float):
    K = speculate

    @jax.jit
    def run(params, draft_params, prompt, rng):
        batch, prefix = prompt.shape
        tlogits, tstate = decoder.apply(
            {'params': _dequant(params, decoder)}, prompt, mutable=['cache'])
        _, dstate = drafter.apply(
            {'params': _dequant(draft_params, drafter)}, prompt,
            mutable=['cache'])
        rng, key = jax.random.split(rng)
        token = _sample(tlogits[:, -1], temperature, key)
        # padded so a full window write at the last offset stays in bounds
        out = jnp.zeros((batch, steps + K + 1), jnp.int32)
        out = out.at[:, 0].set(token)

        def cond(carry):
            return jnp.min(carry[0]) < steps

        def body(carry):
            produced, cursor, token, out, rng, tcache, dcache = carry
            rng, draft_rng, accept_rng, fix_rng = jax.random.split(rng, 4)
            done = produced >= steps                       # [B] idle rows

            def draft_step(state, key):
                cache, tok = state
                logits, updated = drafter.apply(
                    {'params': _dequant(draft_params, drafter),
                     'cache': cache}, tok[:, None], mutable=['cache'])
                logits = logits[:, -1]
                nxt = _sample(logits, temperature, key)
                return (updated['cache'], nxt), (nxt, logits)

            # K+1 steps: the last consumes d_K so the draft cache holds its
            # KV when every draft is accepted (the extra proposal is unused)
            (dcache, _), (drafts, draft_logits) = jax.lax.scan(
                draft_step, (dcache, token),
                jax.random.split(draft_rng, K + 1))
            drafts = jnp.moveaxis(drafts, 0, 1)[:, :K]            # [B, K]
            draft_logits = jnp.moveaxis(draft_logits, 0, 1)[:, :K]

            # one target forward over the whole proposed window
            window = jnp.concatenate([token[:, None], drafts], axis=1)
            vlogits, tupdated = decoder.apply(
                {'params': _dequant(params, decoder), 'cache': tcache},
                window, mutable=['cache'])

            if temperature == 0.0:
                # acceptance = exact match against the target's greedy
                # choices; correction = the target's own choice there —
                # all per row
                candidates = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
                matches = (drafts == candidates[:, :K]).astype(jnp.int32)
                accepted = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
                correction = jnp.take_along_axis(
                    candidates, accepted[:, None], axis=1)[:, 0]
            else:
                # rejection sampling: accept draft token d with probability
                # min(1, p(d)/q(d)); the correction resamples from
                # norm(max(0, p - q)) at each row's first rejection, or
                # from p itself when every draft was accepted (q masked
                # to 0 at index K)
                p_dist = jax.nn.softmax(
                    vlogits.astype(jnp.float32) / temperature, axis=-1)
                q_dist = jax.nn.softmax(
                    draft_logits.astype(jnp.float32) / temperature, axis=-1)
                p_draft = jnp.take_along_axis(
                    p_dist[:, :K], drafts[..., None], axis=-1)[..., 0]
                q_draft = jnp.take_along_axis(
                    q_dist, drafts[..., None], axis=-1)[..., 0]
                uniforms = jax.random.uniform(accept_rng, (batch, K))
                accepts = (uniforms * q_draft < p_draft).astype(jnp.int32)
                accepted = jnp.sum(jnp.cumprod(accepts, axis=1), axis=1)
                p_at = jnp.take_along_axis(
                    p_dist, accepted[:, None, None],
                    axis=1)[:, 0]                          # [B, V]
                q_padded = jnp.pad(q_dist, ((0, 0), (0, 1), (0, 0)))
                q_at = jnp.take_along_axis(
                    q_padded, accepted[:, None, None], axis=1)[:, 0]
                residual = jnp.maximum(p_at - q_at, 0.0)
                # float rounding can zero the residual; fall back to p
                degenerate = jnp.sum(residual, -1, keepdims=True) < 1e-9
                residual = jnp.where(degenerate, p_at, residual)
                correction = jax.random.categorical(
                    fix_rng, jnp.log(residual + 1e-20), axis=-1
                ).astype(jnp.int32)

            # emit each row's accepted drafts plus its correction token;
            # idle rows write nowhere (their columns land out of bounds)
            positions = jnp.arange(K + 1)[None, :]
            emitted = jnp.where(
                positions < accepted[:, None],
                jnp.pad(drafts, ((0, 0), (0, 1))),
                jnp.where(positions == accepted[:, None],
                          correction[:, None], 0))
            columns = jnp.where(done[:, None], out.shape[1],
                                produced[:, None] + positions)
            out = out.at[jnp.arange(batch)[:, None], columns].set(
                emitted, mode='drop')

            advance = jnp.where(done, 0, accepted + 1)
            produced = produced + advance
            cursor = cursor + advance
            # rows at/past `steps` keep drafting+verifying (a while_loop has
            # no per-row exit) — park their cursor at the prompt end so the
            # dead writes stay inside the audited prompt+steps+speculate+1
            # capacity window instead of relying on scatter-drop /
            # gather-clamp semantics past max_seq; their out/token/produced
            # no longer advance, so outputs are unaffected
            cursor = jnp.where(produced >= steps,
                               jnp.minimum(cursor, prefix), cursor)
            token = jnp.where(
                done, token,
                jnp.take_along_axis(emitted, accepted[:, None], axis=1)[:, 0])
            return (produced, cursor, token, out, rng,
                    _rewind(tupdated['cache'], cursor),
                    _rewind(dcache, cursor))

        carry = (jnp.full((batch,), 1, jnp.int32),
                 jnp.full((batch,), prefix, jnp.int32), token, out, rng,
                 tstate['cache'], dstate['cache'])
        _, _, _, out, _, _, _ = jax.lax.while_loop(cond, body, carry)
        return jnp.concatenate([prompt, out[:, :steps]], axis=1)

    return run


@functools.cache
def _compiled_speculative_tree(decoder, drafter, steps: int, speculate: int,
                               fanout: int):
    return _build_speculative_tree(decoder, drafter, steps, speculate, fanout)


def _build_speculative_tree(decoder, drafter, steps: int, speculate: int,
                            fanout: int):
    """Greedy token-tree verify: each sequence owns ``fanout`` adjacent
    branch rows (row ``b*F + f`` is branch ``f`` of sequence ``b``) whose
    caches hold identical history at every round start. The draft fans
    the tree at its first step (branch ``f`` takes the draft's f-th most
    probable token) and continues each branch greedily; ONE target
    forward verifies all ``batch*F`` windows; the branch with the
    longest target-greedy-accepted prefix wins the round and its cache
    rows are copied over its siblings'. Output invariant: every emitted
    token is the target's own greedy choice given the accepted prefix,
    so the result is exactly :func:`generate`'s greedy decode — the tree
    only changes how many tokens each weight pass yields."""
    K, F = speculate, fanout

    @jax.jit
    def run(params, draft_params, prompt, rng):
        del rng                                  # greedy only
        batch, prefix = prompt.shape
        wide = batch * F
        prompt_wide = jnp.repeat(prompt, F, axis=0)    # branches adjacent
        tlogits, tstate = decoder.apply(
            {'params': _dequant(params, decoder)}, prompt_wide,
            mutable=['cache'])
        _, dstate = drafter.apply(
            {'params': _dequant(draft_params, drafter)}, prompt_wide,
            mutable=['cache'])
        token = jnp.argmax(tlogits[:, -1], axis=-1).astype(jnp.int32)
        out = jnp.zeros((batch, steps + K + 1), jnp.int32)
        out = out.at[:, 0].set(token[::F])
        branch = jnp.arange(wide) % F            # branch id per wide row

        def cond(carry):
            return jnp.min(carry[0]) < steps

        def body(carry):
            produced, cursor, token, out, tcache, dcache = carry
            done = produced >= steps                       # [B] idle rows

            def draft_step(state, step_index):
                cache, tok = state
                logits, updated = drafter.apply(
                    {'params': _dequant(draft_params, drafter),
                     'cache': cache}, tok[:, None], mutable=['cache'])
                logits = logits[:, -1]
                # step 0 fans the tree out: sibling rows see identical
                # logits, branch f takes the f-th most probable token;
                # later steps continue each branch greedily
                _, top = jax.lax.top_k(logits, F)
                fanned = jnp.take_along_axis(
                    top, branch[:, None], axis=1)[:, 0]
                greedy = jnp.argmax(logits, axis=-1)
                nxt = jnp.where(step_index == 0, fanned,
                                greedy).astype(jnp.int32)
                return (updated['cache'], nxt), nxt

            # K+1 steps for the same reason as the linear path: a fully
            # accepted winner's draft cache must hold d_K's KV
            (dcache, _), drafts = jax.lax.scan(
                draft_step, (dcache, token), jnp.arange(K + 1))
            drafts = jnp.moveaxis(drafts, 0, 1)[:, :K]     # [B*F, K]

            # one target forward verifies every branch of every sequence
            window = jnp.concatenate([token[:, None], drafts], axis=1)
            vlogits, tupdated = decoder.apply(
                {'params': _dequant(params, decoder), 'cache': tcache},
                window, mutable=['cache'])
            candidates = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
            matches = (drafts == candidates[:, :K]).astype(jnp.int32)
            accepted = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)

            # the longest accepted prefix wins its group; argmax ties
            # resolve to the lowest branch id = the draft's most
            # probable branch
            per_group = accepted.reshape(batch, F)
            winner = jnp.argmax(per_group, axis=1).astype(jnp.int32)
            accepted_w = jnp.max(per_group, axis=1)        # [B]
            win_rows = jnp.arange(batch) * F + winner
            drafts_w = jnp.take(drafts, win_rows, axis=0)
            correction = jnp.take_along_axis(
                jnp.take(candidates, win_rows, axis=0),
                accepted_w[:, None], axis=1)[:, 0]

            positions = jnp.arange(K + 1)[None, :]
            emitted = jnp.where(
                positions < accepted_w[:, None],
                jnp.pad(drafts_w, ((0, 0), (0, 1))),
                jnp.where(positions == accepted_w[:, None],
                          correction[:, None], 0))
            columns = jnp.where(done[:, None], out.shape[1],
                                produced[:, None] + positions)
            out = out.at[jnp.arange(batch)[:, None], columns].set(
                emitted, mode='drop')

            advance = jnp.where(done, 0, accepted_w + 1)
            produced = produced + advance
            cursor = cursor + jnp.repeat(advance, F)
            # park finished groups' cursors at the prompt end — the
            # linear path's capacity discipline, branch-row flavored
            cursor = jnp.where(jnp.repeat(produced >= steps, F),
                               jnp.minimum(cursor, prefix), cursor)
            next_token = jnp.take_along_axis(
                emitted, accepted_w[:, None], axis=1)[:, 0]
            token = jnp.where(jnp.repeat(done, F), token,
                              jnp.repeat(next_token, F))
            # losing branches inherit the winner's cache rows, then every
            # row rewinds to the group's accepted depth
            rowmap = jnp.repeat(win_rows, F)
            tcache = _rewind(_gather_rows(tupdated['cache'], rowmap),
                             cursor)
            dcache = _rewind(_gather_rows(dcache, rowmap), cursor)
            return (produced, cursor, token, out, tcache, dcache)

        carry = (jnp.full((batch,), 1, jnp.int32),
                 jnp.full((wide,), prefix, jnp.int32), token, out,
                 tstate['cache'], dstate['cache'])
        _, _, _, out, _, _ = jax.lax.while_loop(cond, body, carry)
        return jnp.concatenate([prompt, out[:, :steps]], axis=1)

    return run


@functools.cache
def _compiled(decoder, steps: int, temperature: float):
    return _build(decoder, steps, temperature)


def _build(decoder, steps: int, temperature: float):

    @jax.jit
    def run(params, prompt, rng):
        # prefill: one pass over the prompt builds every layer's cache
        logits, state = decoder.apply({'params': _dequant(params, decoder)},
                                      prompt, mutable=['cache'])
        rng, key = jax.random.split(rng)
        token = _sample(logits[:, -1], temperature, key)

        def step(carry, _):
            cache, token, rng = carry
            # dequantize INSIDE the loop body: the narrow leaves stay
            # the HBM-resident operand, the wide view is per-step
            # transient (identity for unquantized trees)
            logits, updated = decoder.apply(
                {'params': _dequant(params, decoder), 'cache': cache},
                token[:, None], mutable=['cache'])
            rng, key = jax.random.split(rng)
            next_token = _sample(logits[:, -1], temperature, key)
            return (updated['cache'], next_token, rng), token

        (_, last, _), generated = jax.lax.scan(
            step, (state['cache'], token, rng), None, length=steps - 1)
        generated = jnp.moveaxis(generated, 0, 1)       # [B, steps-1]
        return jnp.concatenate([prompt, generated, last[:, None]], axis=1)

    return run
