"""Autoregressive text generation with a KV cache.

The inference counterpart of the training stack: ``generate`` clones an LM
module into decode mode (KV caches in the flax ``'cache'`` collection,
absolute positions from the cache cursor), prefills the prompt in one
forward pass, then decodes one token per step under ``lax.scan`` — the
whole sampling loop is a single compiled program, no host round-trip per
token. Works with any module exposing the family conventions
(:class:`tpusystem.models.GPT2` / :class:`~tpusystem.models.Llama`):
a ``decode`` field, logits output, and ``max_seq`` capacity.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


def _decoder(module):
    """Clone the module into decode mode: xla attention (flash/ring make no
    sense one token at a time), no dropout, logits output (MoE models drop
    their aux/router term — it only exists for the training loss). The
    mesh field is dropped too — the decode path never reads it, and an
    unhashable live mesh would defeat the compiled-program cache."""
    updates: dict = {'decode': True}
    for field, value in (('attention', 'xla'), ('dropout', 0.0),
                         ('return_features', False), ('remat', False),
                         ('mesh', None)):
        if hasattr(module, field):
            updates[field] = value
    return dataclasses.replace(module, **updates)


def _sample(logits, temperature: float, rng):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / temperature
    return jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)


def generate(module, params, prompt, *, steps: int,
             temperature: float = 0.0, rng=None):
    """Generate ``steps`` tokens after ``prompt``.

    Args:
        module: the trained LM module (its ``decode=True`` clone is used).
        params: trained parameters.
        prompt: int32 ``[batch, prompt_len]`` token ids.
        steps: tokens to generate per sequence.
        temperature: 0 = greedy argmax; otherwise categorical sampling.
        rng: ``jax.random`` key (required when ``temperature > 0``).

    Returns:
        int32 ``[batch, prompt_len + steps]`` — prompt plus generation.
    """
    if steps < 1:
        raise ValueError(f'steps must be >= 1, got {steps}')
    if temperature > 0.0 and rng is None:
        raise ValueError('temperature sampling needs an rng key')
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    decoder = _decoder(module)
    if prompt.shape[1] + steps > decoder.max_seq:
        raise ValueError(
            f'prompt ({prompt.shape[1]}) + steps ({steps}) exceeds the '
            f'cache capacity max_seq={decoder.max_seq}')
    try:
        # jit caches key on function identity: reuse one compiled program
        # per (decoder config, steps, temperature) across generate() calls
        run = _compiled(decoder, steps, temperature)
    except TypeError:       # unhashable module field (e.g. a live mesh)
        run = _build(decoder, steps, temperature)
    return run(params, prompt, rng)


@functools.cache
def _compiled(decoder, steps: int, temperature: float):
    return _build(decoder, steps, temperature)


def _build(decoder, steps: int, temperature: float):

    @jax.jit
    def run(params, prompt, rng):
        # prefill: one pass over the prompt builds every layer's cache
        logits, state = decoder.apply({'params': params}, prompt,
                                      mutable=['cache'])
        rng, key = jax.random.split(rng)
        token = _sample(logits[:, -1], temperature, key)

        def step(carry, _):
            cache, token, rng = carry
            logits, updated = decoder.apply(
                {'params': params, 'cache': cache}, token[:, None],
                mutable=['cache'])
            rng, key = jax.random.split(rng)
            next_token = _sample(logits[:, -1], temperature, key)
            return (updated['cache'], next_token, rng), token

        (_, last, _), generated = jax.lax.scan(
            step, (state['cache'], token, rng), None, length=steps - 1)
        generated = jnp.moveaxis(generated, 0, 1)       # [B, steps-1]
        return jnp.concatenate([prompt, generated, last[:, None]], axis=1)

    return run
