"""Autoregressive text generation with a KV cache.

The inference counterpart of the training stack: ``generate`` clones an LM
module into decode mode (KV caches in the flax ``'cache'`` collection,
absolute positions from the cache cursor), prefills the prompt in one
forward pass, then decodes one token per step under ``lax.scan`` — the
whole sampling loop is a single compiled program, no host round-trip per
token. Works with any module exposing the family conventions
(:class:`tpusystem.models.GPT2` / :class:`~tpusystem.models.Llama`):
a ``decode`` field, logits output, and ``max_seq`` capacity.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


def _decoder(module):
    """Clone the module into decode mode: xla attention (flash/ring make no
    sense one token at a time), no dropout, logits output (MoE models drop
    their aux/router term — it only exists for the training loss). The
    mesh field is dropped too — the decode path never reads it, and an
    unhashable live mesh would defeat the compiled-program cache."""
    updates: dict = {'decode': True}
    for field, value in (('attention', 'xla'), ('dropout', 0.0),
                         ('return_features', False), ('remat', False),
                         ('mesh', None)):
        if hasattr(module, field):
            updates[field] = value
    return dataclasses.replace(module, **updates)


def _sample(logits, temperature: float, rng):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / temperature
    return jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)


def generate(module, params, prompt, *, steps: int,
             temperature: float = 0.0, rng=None):
    """Generate ``steps`` tokens after ``prompt``.

    Args:
        module: the trained LM module (its ``decode=True`` clone is used).
        params: trained parameters.
        prompt: int32 ``[batch, prompt_len]`` token ids.
        steps: tokens to generate per sequence.
        temperature: 0 = greedy argmax; otherwise categorical sampling.
        rng: ``jax.random`` key (required when ``temperature > 0``).

    Returns:
        int32 ``[batch, prompt_len + steps]`` — prompt plus generation.
    """
    if steps < 1:
        raise ValueError(f'steps must be >= 1, got {steps}')
    if temperature > 0.0 and rng is None:
        raise ValueError('temperature sampling needs an rng key')
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    decoder = _decoder(module)
    if prompt.shape[1] + steps > decoder.max_seq:
        raise ValueError(
            f'prompt ({prompt.shape[1]}) + steps ({steps}) exceeds the '
            f'cache capacity max_seq={decoder.max_seq}')
    try:
        # jit caches key on function identity: reuse one compiled program
        # per (decoder config, steps, temperature) across generate() calls
        run = _compiled(decoder, steps, temperature)
    except TypeError:       # unhashable module field (e.g. a live mesh)
        run = _build(decoder, steps, temperature)
    return run(params, prompt, rng)


def speculative_generate(module, params, prompt, *, steps: int,
                         draft_module, draft_params, speculate: int = 4):
    """Greedy generation accelerated by a draft model (speculative decoding).

    The draft proposes ``speculate`` tokens autoregressively (cheap model,
    cheap steps); the target verifies them in ONE forward over the
    proposed window and accepts the longest prefix that matches its own
    greedy choices, emitting one extra corrected token — so each target
    forward yields between 1 and ``speculate + 1`` tokens. **Output is
    exactly the target's greedy decode regardless of draft quality** (a
    bad draft only costs speed); both KV caches rewind their cursors to
    the accepted prefix each round.

    Batched prompts advance by the *minimum* acceptance across the batch
    (per-element cursors would need per-row cache writes), so speedup is
    largest at small batch. Greedy only — temperature sampling needs
    rejection-sampling acceptance, not shipped yet.

    Returns int32 ``[batch, prompt_len + steps]`` like :func:`generate`.
    """
    if steps < 1:
        raise ValueError(f'steps must be >= 1, got {steps}')
    if speculate < 1:
        raise ValueError(f'speculate must be >= 1, got {speculate}')
    decoder, drafter = _decoder(module), _decoder(draft_module)
    needed = prompt.shape[1] + steps + speculate + 1
    capacity = min(decoder.max_seq, drafter.max_seq)
    if needed > capacity:
        raise ValueError(
            f'prompt + steps + speculate + 1 = {needed} exceeds the cache '
            f'capacity max_seq={capacity} (verification overshoots by up to '
            f'speculate tokens before rewinding)')
    try:
        run = _compiled_speculative(decoder, drafter, steps, speculate)
    except TypeError:       # unhashable module field
        run = _build_speculative(decoder, drafter, steps, speculate)
    return run(params, draft_params, prompt)


def _rewind(cache, cursor):
    """Set every cache cursor back to ``cursor`` — rows beyond it are
    garbage from rejected speculation, masked out by the cursor-based
    attention mask and overwritten by the next accepted tokens. Covers the
    per-layer KV cursors (``index`` — also what Llama's rotary reads) and
    GPT-2's learned-position offset (``position``)."""
    cursors = (jax.tree_util.DictKey('index'),
               jax.tree_util.DictKey('position'))

    def fix(path, leaf):
        if path[-1] in cursors:
            return jnp.asarray(cursor, leaf.dtype)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.cache
def _compiled_speculative(decoder, drafter, steps: int, speculate: int):
    return _build_speculative(decoder, drafter, steps, speculate)


def _build_speculative(decoder, drafter, steps: int, speculate: int):
    K = speculate

    @jax.jit
    def run(params, draft_params, prompt):
        batch, prefix = prompt.shape
        tlogits, tstate = decoder.apply({'params': params}, prompt,
                                        mutable=['cache'])
        _, dstate = drafter.apply({'params': draft_params}, prompt,
                                  mutable=['cache'])
        token = jnp.argmax(tlogits[:, -1], axis=-1).astype(jnp.int32)
        # padded so a full window write at the last offset stays in bounds
        out = jnp.zeros((batch, steps + K + 1), jnp.int32)
        out = out.at[:, 0].set(token)

        def cond(carry):
            return carry[0] < steps

        def body(carry):
            produced, cursor, token, out, tcache, dcache = carry

            def draft_step(state, _):
                cache, tok = state
                logits, updated = drafter.apply(
                    {'params': draft_params, 'cache': cache}, tok[:, None],
                    mutable=['cache'])
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (updated['cache'], nxt), nxt

            # K+1 steps: the last consumes d_K so the draft cache holds its
            # KV when every draft is accepted (the extra proposal is unused)
            (dcache, _), drafts = jax.lax.scan(
                draft_step, (dcache, token), None, length=K + 1)
            drafts = jnp.moveaxis(drafts, 0, 1)[:, :K]   # [B, K]

            # one target forward over the whole proposed window
            window = jnp.concatenate([token[:, None], drafts], axis=1)
            vlogits, tupdated = decoder.apply(
                {'params': params, 'cache': tcache}, window,
                mutable=['cache'])
            candidates = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)

            # accept the longest draft prefix matching the target's greedy
            # choices; the whole batch advances by the minimum acceptance
            matches = (drafts == candidates[:, :K]).astype(jnp.int32)
            accepted = jnp.min(jnp.sum(jnp.cumprod(matches, axis=1), axis=1))

            # emit accepted drafts plus the target's correction token
            correction = jax.lax.dynamic_index_in_dim(
                candidates, accepted, axis=1, keepdims=False)
            positions = jnp.arange(K + 1)[None, :]
            emitted = jnp.where(
                positions < accepted,
                jnp.pad(drafts, ((0, 0), (0, 1))),
                jnp.where(positions == accepted, correction[:, None], 0))
            out = jax.lax.dynamic_update_slice(out, emitted, (0, produced))

            produced = produced + accepted + 1
            cursor = cursor + accepted + 1
            token = jax.lax.dynamic_index_in_dim(
                emitted, accepted, axis=1, keepdims=False)
            return (produced, cursor,
                    token, out,
                    _rewind(tupdated['cache'], cursor),
                    _rewind(dcache, cursor))

        carry = (jnp.int32(1), jnp.int32(prefix), token, out,
                 tstate['cache'], dstate['cache'])
        _, _, _, out, _, _ = jax.lax.while_loop(cond, body, carry)
        return jnp.concatenate([prompt, out[:, :steps]], axis=1)

    return run


@functools.cache
def _compiled(decoder, steps: int, temperature: float):
    return _build(decoder, steps, temperature)


def _build(decoder, steps: int, temperature: float):

    @jax.jit
    def run(params, prompt, rng):
        # prefill: one pass over the prompt builds every layer's cache
        logits, state = decoder.apply({'params': params}, prompt,
                                      mutable=['cache'])
        rng, key = jax.random.split(rng)
        token = _sample(logits[:, -1], temperature, key)

        def step(carry, _):
            cache, token, rng = carry
            logits, updated = decoder.apply(
                {'params': params, 'cache': cache}, token[:, None],
                mutable=['cache'])
            rng, key = jax.random.split(rng)
            next_token = _sample(logits[:, -1], temperature, key)
            return (updated['cache'], next_token, rng), token

        (_, last, _), generated = jax.lax.scan(
            step, (state['cache'], token, rng), None, length=steps - 1)
        generated = jnp.moveaxis(generated, 0, 1)       # [B, steps-1]
        return jnp.concatenate([prompt, generated, last[:, None]], axis=1)

    return run
