"""Autoregressive text generation with a KV cache.

The inference counterpart of the training stack: ``generate`` clones an LM
module into decode mode (KV caches in the flax ``'cache'`` collection,
absolute positions from the cache cursor), prefills the prompt in one
forward pass, then decodes one token per step under ``lax.scan`` — the
whole sampling loop is a single compiled program, no host round-trip per
token. Works with any module exposing the family conventions
(:class:`tpusystem.models.GPT2` / :class:`~tpusystem.models.Llama`):
a ``decode`` field, logits output, and ``max_seq`` capacity.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


def _decoder(module, per_row: bool = False):
    """Clone the module into decode mode: xla attention (flash/ring make no
    sense one token at a time), no dropout, logits output (MoE models drop
    their aux/router term — it only exists for the training loss). The
    mesh field is dropped too — the decode path never reads it, and an
    unhashable live mesh would defeat the compiled-program cache.

    ``per_row=True`` (the speculative path) switches the KV-cache writes
    to per-row scatter so each sequence advances by its own acceptance;
    ordinary generation keeps the faster shared-cursor
    ``dynamic_update_slice`` (see ``cached_attention``)."""
    updates: dict = {'decode': True}
    for field, value in (('attention', 'xla'), ('dropout', 0.0),
                         ('return_features', False), ('remat', False),
                         ('mesh', None), ('per_row_decode', per_row)):
        if hasattr(module, field):
            updates[field] = value
    return dataclasses.replace(module, **updates)


def _stream_params(decoder, params, stream_dtype: str):
    """Pre-cast f32 matrix leaves to the decode compute dtype (see
    ``generate``'s ``stream_dtype``). No-op for f32-compute modules."""
    if stream_dtype == 'float32':
        return params
    if stream_dtype != 'auto':
        raise ValueError(f'unknown stream_dtype {stream_dtype!r}; '
                         "expected 'auto' or 'float32'")
    compute = jnp.dtype(getattr(decoder, 'dtype', jnp.float32))
    if compute.itemsize >= jnp.dtype(jnp.float32).itemsize:
        return params

    return _caster(compute.name)(params)


@functools.cache
def _caster(compute_name: str):
    """One cached jitted cast program per target dtype: per-leaf eager
    casts would pay a host dispatch each (~60 relay round-trips per
    generate() call), and an uncached jit would *retrace and recompile*
    the cast every call (measured 8x slower decode)."""
    compute = jnp.dtype(compute_name)

    def cast(path, leaf):
        # leaves the model consumes at f32 must stay f32: embedding
        # tables (the embed step ADDS wte+wpe rows in f32 before
        # casting; the scan-hoisted head cast keeps the head matmul
        # bf16 anyway) and MoE routers (gate logits are an f32 matmul —
        # a bf16-rounded router could flip near-tie expert choices)
        from tpusystem.parallel.sharding import leaf_path
        path = leaf_path(path)
        if 'embedding' in path or 'router' in path:
            return leaf
        if leaf.ndim >= 2 and leaf.dtype == jnp.float32:
            return leaf.astype(compute)
        return leaf

    return jax.jit(functools.partial(jax.tree_util.tree_map_with_path, cast))


def _sample(logits, temperature: float, rng):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / temperature
    return jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)


def generate(module, params, prompt, *, steps: int,
             temperature: float = 0.0, rng=None,
             stream_dtype: str = 'auto'):
    """Generate ``steps`` tokens after ``prompt``.

    Args:
        module: the trained LM module (its ``decode=True`` clone is used).
        params: trained parameters.
        prompt: int32 ``[batch, prompt_len]`` token ids.
        steps: tokens to generate per sequence.
        temperature: 0 = greedy argmax; otherwise categorical sampling.
        rng: ``jax.random`` key (required when ``temperature > 0``).
        stream_dtype: ``'auto'`` (default) pre-casts float32 matrix
            kernels (ndim >= 2) to the module's compute dtype when that
            dtype is narrower. Decode at small batch is weight-STREAMING
            bound, and a bf16-compute model casts its f32 kernels to
            bf16 at every use anyway — the cast changes which bytes a
            decode-only process keeps resident, not the matmul numerics.
            Leaves the model consumes at f32 are NOT cast: embedding
            tables (the embed step adds wte+wpe rows in f32 — for GPT-2
            the tied table is the part whose footprint does not halve),
            MoE router weights (routing runs in f32), and vector leaves
            (biases, layernorm scales). ``'float32'`` streams the
            masters untouched (the training layout).

    Returns:
        int32 ``[batch, prompt_len + steps]`` — prompt plus generation.
    """
    if steps < 1:
        raise ValueError(f'steps must be >= 1, got {steps}')
    if temperature > 0.0 and rng is None:
        raise ValueError('temperature sampling needs an rng key')
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    decoder = _decoder(module)
    params = _stream_params(decoder, params, stream_dtype)
    if prompt.shape[1] + steps > decoder.max_seq:
        raise ValueError(
            f'prompt ({prompt.shape[1]}) + steps ({steps}) exceeds the '
            f'cache capacity max_seq={decoder.max_seq}')
    try:
        # jit caches key on function identity: reuse one compiled program
        # per (decoder config, steps, temperature) across generate() calls
        run = _compiled(decoder, steps, temperature)
    except TypeError:       # unhashable module field (e.g. a live mesh)
        run = _build(decoder, steps, temperature)
    return run(params, prompt, rng)


def speculative_generate(module, params, prompt, *, steps: int,
                         draft_module, draft_params, speculate: int = 4,
                         temperature: float = 0.0, rng=None):
    """Generation accelerated by a draft model (speculative decoding).

    The draft proposes ``speculate`` tokens autoregressively (cheap model,
    cheap steps); the target verifies them in ONE forward over the
    proposed window, emitting the accepted prefix plus one corrected
    token — so each target forward yields between 1 and ``speculate + 1``
    tokens, and a bad draft only costs speed, never correctness:

    * ``temperature=0``: acceptance is exact match against the target's
      greedy choices — **output is exactly the target's greedy decode**
      in window-length-invariant arithmetic (CPU float32, or TPU with
      ``jax_default_matmul_precision='highest'``). At the TPU MXU's
      DEFAULT precision, f32 matmul operands are truncated to bfloat16
      with tilings that depend on the query-window length, so the
      verify's K+1-token windows and plain decode's 1-token windows can
      round a near-tie argmax differently (~1e-2 logit scatter measured
      on a v5e) — rare content-dependent token flips, each still a
      greedy choice within platform tolerance.
    * ``temperature>0``: rejection-sampling acceptance (Leviathan et al.):
      draft token ``d`` is accepted with probability ``min(1, p(d)/q(d))``
      and a rejection resamples from ``norm(max(0, p - q))`` — the output
      **distribution** is exactly the target's sampling distribution.

    Both KV caches rewind their cursors to the accepted prefix each round.
    Cache cursors are **per-row** (the caches write and mask at each row's
    own depth), so every sequence advances by its own acceptance count —
    one slow row no longer drags the whole batch to its acceptance, and
    the speedup survives batching. Rows that reach ``steps`` idle (their
    cursor and output stop advancing) until the slowest row finishes.

    Returns int32 ``[batch, prompt_len + steps]`` like :func:`generate`.
    """
    if steps < 1:
        raise ValueError(f'steps must be >= 1, got {steps}')
    if speculate < 1:
        raise ValueError(f'speculate must be >= 1, got {speculate}')
    if temperature > 0.0 and rng is None:
        raise ValueError('temperature sampling needs an rng key')
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    decoder = _decoder(module, per_row=True)
    drafter = _decoder(draft_module, per_row=True)
    needed = prompt.shape[1] + steps + speculate + 1
    capacity = min(decoder.max_seq, drafter.max_seq)
    if needed > capacity:
        raise ValueError(
            f'prompt + steps + speculate + 1 = {needed} exceeds the cache '
            f'capacity max_seq={capacity} (verification overshoots by up to '
            f'speculate tokens before rewinding)')
    try:
        run = _compiled_speculative(decoder, drafter, steps, speculate,
                                    temperature)
    except TypeError:       # unhashable module field
        run = _build_speculative(decoder, drafter, steps, speculate,
                                 temperature)
    return run(params, draft_params, prompt, rng)


def _rewind(cache, cursor):
    """Set every cache cursor back to ``cursor`` — rows beyond it are
    garbage from rejected speculation, masked out by the cursor-based
    attention mask and overwritten by the next accepted tokens. Covers the
    per-layer KV cursors (``index`` — also what Llama's rotary reads) and
    GPT-2's learned-position offset (``position``)."""
    cursors = (jax.tree_util.DictKey('index'),
               jax.tree_util.DictKey('position'))

    def fix(path, leaf):
        if path[-1] in cursors:
            # scanned stacks carry cursors at a leading layer dim —
            # broadcast the [batch] cursor to whatever shape the leaf has
            return jnp.broadcast_to(jnp.asarray(cursor, leaf.dtype),
                                    leaf.shape)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.cache
def _compiled_speculative(decoder, drafter, steps: int, speculate: int,
                          temperature: float):
    return _build_speculative(decoder, drafter, steps, speculate, temperature)


def _build_speculative(decoder, drafter, steps: int, speculate: int,
                       temperature: float):
    K = speculate

    @jax.jit
    def run(params, draft_params, prompt, rng):
        batch, prefix = prompt.shape
        tlogits, tstate = decoder.apply({'params': params}, prompt,
                                        mutable=['cache'])
        _, dstate = drafter.apply({'params': draft_params}, prompt,
                                  mutable=['cache'])
        rng, key = jax.random.split(rng)
        token = _sample(tlogits[:, -1], temperature, key)
        # padded so a full window write at the last offset stays in bounds
        out = jnp.zeros((batch, steps + K + 1), jnp.int32)
        out = out.at[:, 0].set(token)

        def cond(carry):
            return jnp.min(carry[0]) < steps

        def body(carry):
            produced, cursor, token, out, rng, tcache, dcache = carry
            rng, draft_rng, accept_rng, fix_rng = jax.random.split(rng, 4)
            done = produced >= steps                       # [B] idle rows

            def draft_step(state, key):
                cache, tok = state
                logits, updated = drafter.apply(
                    {'params': draft_params, 'cache': cache}, tok[:, None],
                    mutable=['cache'])
                logits = logits[:, -1]
                nxt = _sample(logits, temperature, key)
                return (updated['cache'], nxt), (nxt, logits)

            # K+1 steps: the last consumes d_K so the draft cache holds its
            # KV when every draft is accepted (the extra proposal is unused)
            (dcache, _), (drafts, draft_logits) = jax.lax.scan(
                draft_step, (dcache, token),
                jax.random.split(draft_rng, K + 1))
            drafts = jnp.moveaxis(drafts, 0, 1)[:, :K]            # [B, K]
            draft_logits = jnp.moveaxis(draft_logits, 0, 1)[:, :K]

            # one target forward over the whole proposed window
            window = jnp.concatenate([token[:, None], drafts], axis=1)
            vlogits, tupdated = decoder.apply(
                {'params': params, 'cache': tcache}, window,
                mutable=['cache'])

            if temperature == 0.0:
                # acceptance = exact match against the target's greedy
                # choices; correction = the target's own choice there —
                # all per row
                candidates = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
                matches = (drafts == candidates[:, :K]).astype(jnp.int32)
                accepted = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
                correction = jnp.take_along_axis(
                    candidates, accepted[:, None], axis=1)[:, 0]
            else:
                # rejection sampling: accept draft token d with probability
                # min(1, p(d)/q(d)); the correction resamples from
                # norm(max(0, p - q)) at each row's first rejection, or
                # from p itself when every draft was accepted (q masked
                # to 0 at index K)
                p_dist = jax.nn.softmax(
                    vlogits.astype(jnp.float32) / temperature, axis=-1)
                q_dist = jax.nn.softmax(
                    draft_logits.astype(jnp.float32) / temperature, axis=-1)
                p_draft = jnp.take_along_axis(
                    p_dist[:, :K], drafts[..., None], axis=-1)[..., 0]
                q_draft = jnp.take_along_axis(
                    q_dist, drafts[..., None], axis=-1)[..., 0]
                uniforms = jax.random.uniform(accept_rng, (batch, K))
                accepts = (uniforms * q_draft < p_draft).astype(jnp.int32)
                accepted = jnp.sum(jnp.cumprod(accepts, axis=1), axis=1)
                p_at = jnp.take_along_axis(
                    p_dist, accepted[:, None, None],
                    axis=1)[:, 0]                          # [B, V]
                q_padded = jnp.pad(q_dist, ((0, 0), (0, 1), (0, 0)))
                q_at = jnp.take_along_axis(
                    q_padded, accepted[:, None, None], axis=1)[:, 0]
                residual = jnp.maximum(p_at - q_at, 0.0)
                # float rounding can zero the residual; fall back to p
                degenerate = jnp.sum(residual, -1, keepdims=True) < 1e-9
                residual = jnp.where(degenerate, p_at, residual)
                correction = jax.random.categorical(
                    fix_rng, jnp.log(residual + 1e-20), axis=-1
                ).astype(jnp.int32)

            # emit each row's accepted drafts plus its correction token;
            # idle rows write nowhere (their columns land out of bounds)
            positions = jnp.arange(K + 1)[None, :]
            emitted = jnp.where(
                positions < accepted[:, None],
                jnp.pad(drafts, ((0, 0), (0, 1))),
                jnp.where(positions == accepted[:, None],
                          correction[:, None], 0))
            columns = jnp.where(done[:, None], out.shape[1],
                                produced[:, None] + positions)
            out = out.at[jnp.arange(batch)[:, None], columns].set(
                emitted, mode='drop')

            advance = jnp.where(done, 0, accepted + 1)
            produced = produced + advance
            cursor = cursor + advance
            # rows at/past `steps` keep drafting+verifying (a while_loop has
            # no per-row exit) — park their cursor at the prompt end so the
            # dead writes stay inside the audited prompt+steps+speculate+1
            # capacity window instead of relying on scatter-drop /
            # gather-clamp semantics past max_seq; their out/token/produced
            # no longer advance, so outputs are unaffected
            cursor = jnp.where(produced >= steps,
                               jnp.minimum(cursor, prefix), cursor)
            token = jnp.where(
                done, token,
                jnp.take_along_axis(emitted, accepted[:, None], axis=1)[:, 0])
            return (produced, cursor, token, out, rng,
                    _rewind(tupdated['cache'], cursor),
                    _rewind(dcache, cursor))

        carry = (jnp.full((batch,), 1, jnp.int32),
                 jnp.full((batch,), prefix, jnp.int32), token, out, rng,
                 tstate['cache'], dstate['cache'])
        _, _, _, out, _, _, _ = jax.lax.while_loop(cond, body, carry)
        return jnp.concatenate([prompt, out[:, :steps]], axis=1)

    return run


@functools.cache
def _compiled(decoder, steps: int, temperature: float):
    return _build(decoder, steps, temperature)


def _build(decoder, steps: int, temperature: float):

    @jax.jit
    def run(params, prompt, rng):
        # prefill: one pass over the prompt builds every layer's cache
        logits, state = decoder.apply({'params': params}, prompt,
                                      mutable=['cache'])
        rng, key = jax.random.split(rng)
        token = _sample(logits[:, -1], temperature, key)

        def step(carry, _):
            cache, token, rng = carry
            logits, updated = decoder.apply(
                {'params': params, 'cache': cache}, token[:, None],
                mutable=['cache'])
            rng, key = jax.random.split(rng)
            next_token = _sample(logits[:, -1], temperature, key)
            return (updated['cache'], next_token, rng), token

        (_, last, _), generated = jax.lax.scan(
            step, (state['cache'], token, rng), None, length=steps - 1)
        generated = jnp.moveaxis(generated, 0, 1)       # [B, steps-1]
        return jnp.concatenate([prompt, generated, last[:, None]], axis=1)

    return run
