"""The fused decode loop — ``generate(decode_impl='fused')``.

The flax decode path dispatches ~6 XLA ops per matrix param per token
step and streams every weight at the tree's storage width. This module
is the serving-path alternative: one hand-rolled GPT-2 token-step whose
four per-layer matmuls run through the Pallas decode kernels
(:mod:`tpusystem.ops.pallas.decode_matmul`) — the ``[B, dim]``
activation resident in VMEM, weights streamed tile-by-tile, int8/fp8
tiles dequantized in-kernel against their per-channel scales (so
``stream_dtype='int8'|'fp8'`` keeps its narrow HBM traffic inside the
compiled loop instead of being hoisted into a wide copy), and the
fc→gelu→proj pair fused into ONE kernel whose hidden activation never
exists in HBM.

Contract: **the same tokens as the flax path.** The step math mirrors
``GPT2.__call__`` in decode mode op for op — f32 layernorms (flax
fast-variance form), the bucketed cache read of
:func:`tpusystem.ops.attention.cached_attention` (smallest power-of-2
window covering the filled prefix, ``lax.switch`` over static widths),
f32-accumulated matmuls, the tied f32-logit head — and prefill runs
through the flax module itself, so the cache layout and prompt logits
are the flax path's own. Greedy decode is token-exact against
``decode_impl='flax'`` in window-invariant arithmetic (CPU f32; TPU at
``jax_default_matmul_precision='highest'``) and matches within the
platform's near-tie argmax tolerance at default MXU precision —
the speculative-verify caveat, same cause.

Scope: the unrolled dense GPT-2 family (``fused_unsupported_reason``
names the exact gate). Llama/MoE/scanned stacks fall back to the flax
path under ``decode_impl='auto'`` and raise under an explicit
``'fused'``.

Sampling: the fused step's contract ends at the logits it exposes —
:class:`tpusystem.serve.Engine` applies
:func:`tpusystem.train.generate.sample_token` (seeded counter-based
sampling, temperature/top-k/top-p, grammar masks) to those logits
inside the SAME jitted program, so sampled decode through the fused
chain needs no gate here and stays bitwise-identical to the flax step's
sampled stream wherever greedy is token-exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpusystem.ops.attention import NEG_INF
from tpusystem.ops.pallas.decode_matmul import decode_ffn, decode_matmul
from tpusystem.ops.precision import dequantize_streamed, head_logits


def fused_unsupported_reason(decoder) -> str | None:
    """Why ``decode_impl='fused'`` cannot run this decode clone, or
    ``None`` when it can. The fused step re-implements the GPT-2 dense
    token-step; anything whose step math differs falls back. Scope as of
    the serving-engine integration: unrolled dense GPT-2 runs fused here
    AND inside :class:`tpusystem.serve.Engine` (whose paged per-row step
    is :func:`build_fused_paged_step` — ``fused_paged_reason`` is its
    gate); MoE now serves through the engine's flax paged step (full-
    capacity decode dispatch), just not through this FFN chain."""
    from tpusystem.models.gpt2 import GPT2
    if not isinstance(decoder, GPT2):
        return ("the fused decode step implements the GPT2 family only "
                f"(got {type(decoder).__name__})")
    if decoder.scan_layers:
        return ('scan_layers stacks params under a leading layer dim the '
                'fused per-layer sweep does not walk')
    if decoder.moe_experts:
        return ('MoE blocks route through expert dispatch, not the FFN '
                "chain — the serving engine's flax paged step serves MoE; "
                "this fused chain does not")
    if decoder.per_row_decode:
        return ('per-row cache cursors need the scatter cache write — '
                "generate()'s fused loop is shared-cursor only; the "
                "serving engine's fused PAGED step (build_fused_paged_step) "
                'is the per-row implementation')
    return None


def fused_paged_reason(decoder) -> str | None:
    """Why the serving engine's fused PAGED token-step
    (:func:`build_fused_paged_step`) cannot run this decode clone, or
    ``None`` when it can. Unlike :func:`fused_unsupported_reason`, the
    paged step OWNS per-row cursors and the block-table scatter write —
    the gates left are the step-math ones (GPT-2 dense, unrolled) and
    the TP mesh (no ring arms yet)."""
    from tpusystem.models.gpt2 import GPT2
    mesh = getattr(decoder, 'mesh', None)
    if mesh is not None and dict(getattr(mesh, 'shape', {})).get(
            'model', 1) > 1:
        return ('the fused paged step has no ring arms — its Pallas '
                'matmuls are single-device; under a TP mesh '
                "decode_impl='auto' serves through the sharded flax "
                'paged step (token-exact vs single-device)')
    if not isinstance(decoder, GPT2):
        return ('the fused paged step implements the GPT2 family only '
                f'(got {type(decoder).__name__})')
    if decoder.scan_layers:
        return ('scan_layers stacks params under a leading layer dim the '
                'fused per-layer sweep does not walk')
    if decoder.moe_experts:
        return ('MoE blocks route through expert dispatch, not the FFN '
                "chain — the engine's flax paged step serves MoE (full-"
                'capacity decode dispatch), this fused chain does not')
    if not decoder.decode_pages:
        return ('no decode_pages on this clone — the paged step needs the '
                "serving engine's block-pool cache layout")
    return None


def _layernorm(x, scale, bias):
    """flax ``nn.LayerNorm(dtype=float32)`` numerics: f32, fast variance
    (``E[x^2] - E[x]^2``), epsilon 1e-6."""
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True) - jnp.square(mean)
    inv = jax.lax.rsqrt(var + 1e-6)
    return (x - mean) * inv * scale + bias


def _bucketed_attention(query, key_cache, value_cache, cursor, max_seq: int):
    """One-token bucketed cache attention — ``cached_attention``'s read
    path (same buckets, same mask, same f32 softmax) for ``[B, H, hd]``
    queries against ``[B, S, H, hd]`` caches at per-row depth ``cursor``."""
    compute = query.dtype
    head_dim = query.shape[-1]
    scale = head_dim ** -0.5

    def attend_over(width: int):
        def run():
            keys = jax.lax.slice_in_dim(key_cache, 0, width, axis=1)
            values = jax.lax.slice_in_dim(value_cache, 0, width, axis=1)
            scores = jnp.einsum('bhd,bwhd->bhw', query, keys,
                                preferred_element_type=jnp.float32) * scale
            mask = jnp.arange(width)[None, None, :] <= cursor[:, None, None]
            scores = jnp.where(mask, scores, NEG_INF)
            weights = jax.nn.softmax(scores, axis=-1)
            return jnp.einsum('bhw,bwhd->bhd', weights.astype(compute),
                              values)
        return run

    buckets = [256]
    while buckets[-1] < max_seq:
        buckets.append(min(2 * buckets[-1], max_seq))
    if len(buckets) == 1:
        return attend_over(max_seq)()
    filled = jnp.max(cursor) + 1
    bucket_index = sum((filled > width).astype(jnp.int32)
                       for width in buckets[:-1])
    return jax.lax.switch(bucket_index, [attend_over(w) for w in buckets])


def _paged_attention_fused(query, key_pool, value_pool, table, cursor,
                           max_seq: int, block: int):
    """One-token bucketed attention over the serving engine's PAGED pool
    — :func:`tpusystem.ops.attention.paged_attention`'s read path (same
    block-window buckets, same gather, same mask, same f32 softmax) for
    ``[B, H, hd]`` queries against ``[S, H, hd]`` pools through
    ``[B, max_blocks]`` block tables at per-row depth ``cursor``. The
    current token's KV must already be written at its slot (the write
    happens before the read, exactly as in ``paged_attention``)."""
    compute = query.dtype
    batch = query.shape[0]
    head_dim = query.shape[-1]
    scale = head_dim ** -0.5
    max_blocks = max_seq // block

    def attend_over(width: int):
        def run():
            mapped = jax.lax.slice_in_dim(table, 0, width, axis=1)
            tokens = (mapped[:, :, None] * block
                      + jnp.arange(block)[None, None, :]
                      ).reshape(batch, width * block)
            keys = jnp.take(key_pool, tokens, axis=0)    # [B, W*blk, H, hd]
            values = jnp.take(value_pool, tokens, axis=0)
            scores = jnp.einsum('bhd,bkhd->bhk', query, keys,
                                preferred_element_type=jnp.float32) * scale
            mask = (jnp.arange(width * block)[None, None, :]
                    <= cursor[:, None, None])
            scores = jnp.where(mask, scores, NEG_INF)
            weights = jax.nn.softmax(scores, axis=-1)
            return jnp.einsum('bhk,bkhd->bhd', weights.astype(compute),
                              values)
        return run

    buckets = [min(max_blocks, max(1, 64 // block))]
    while buckets[-1] < max_blocks:
        buckets.append(min(2 * buckets[-1], max_blocks))
    if len(buckets) == 1:
        return attend_over(max_blocks)()
    filled_blocks = (jnp.max(cursor) + block) // block
    bucket_index = sum((filled_blocks > width).astype(jnp.int32)
                       for width in buckets[:-1])
    return jax.lax.switch(bucket_index, [attend_over(w) for w in buckets])


def build_fused_paged_step(decoder):
    """The serving engine's fused ``[rows, 1]`` token-step over the
    paged KV pool: the :func:`build_fused` step math (Pallas
    ``decode_matmul``/``decode_ffn``, in-kernel int8/fp8 dequant, f32
    layernorms, tied f32-logit head) with per-row cursors, the
    block-table scatter write, and ``paged_attention``'s bucketed
    block-window read. Returns ``step(params, cache, tokens) ->
    (logits, new_cache)`` where ``cache`` is the engine's paged cache
    tree (per-layer ``key``/``value`` pools + ``table``/``index``,
    model-level ``position``); cursor leaves in the returned cache are
    the input's — the engine's post-step ``rewind`` owns advancement.
    Token-exact vs the flax paged step in window-length-invariant
    arithmetic (the contiguous fused loop's contract)."""
    reason = fused_paged_reason(decoder)
    if reason is not None:
        raise ValueError(f'fused paged step unsupported: {reason}')
    layers, heads = decoder.layers, decoder.heads
    dim, max_seq = decoder.dim, decoder.max_seq
    head_dim = dim // heads
    compute = jnp.dtype(decoder.dtype)
    num_blocks, block = decoder.decode_pages
    max_blocks = max_seq // block

    def step(params, cache, tokens):
        rows = tokens.shape[0]
        cursor = cache['h_0']['attn']['index']               # [rows]
        wte = params['wte']['embedding']
        wpe = params['wpe']['embedding']
        embedded = (jnp.asarray(wte)[tokens].astype(jnp.float32)
                    + jnp.asarray(wpe)[cache['position']].astype(
                        jnp.float32))
        hidden = embedded.astype(compute)
        # physical token slot of this step's position through each row's
        # table — past-capacity clamps onto the last (trash) column,
        # exactly paged_attention's write discipline
        logical = jnp.minimum(cursor // block, max_blocks - 1)
        pools = {}                       # ('h_i', 'key'|'value') -> pool
        for index in range(layers):
            layer = params[f'h_{index}']
            normed = _layernorm(hidden, layer['ln_1']['scale'],
                                layer['ln_1']['bias']).astype(compute)
            attn = layer['attn']
            qkv = decode_matmul(normed, attn['qkv']['kernel'],
                                attn['qkv']['bias'])
            query, key, value = jnp.split(qkv, 3, axis=-1)
            shape = (rows, heads, head_dim)
            query = query.reshape(shape)
            entry = cache[f'h_{index}']['attn']
            table = entry['table']
            physical = jnp.take_along_axis(table, logical[:, None],
                                           axis=1)[:, 0]
            slots = physical * block + cursor % block        # [rows]
            key_pool = entry['key'].at[slots].set(
                key.reshape(shape).astype(entry['key'].dtype))
            value_pool = entry['value'].at[slots].set(
                value.reshape(shape).astype(entry['value'].dtype))
            pools[(f'h_{index}', 'key')] = key_pool
            pools[(f'h_{index}', 'value')] = value_pool
            context = _paged_attention_fused(query, key_pool, value_pool,
                                             table, cursor, max_seq, block)
            attended = decode_matmul(context.reshape(rows, dim),
                                     attn['out']['kernel'],
                                     attn['out']['bias'])
            hidden = hidden + attended
            normed = _layernorm(hidden, layer['ln_2']['scale'],
                                layer['ln_2']['bias']).astype(compute)
            hidden = hidden + decode_ffn(
                normed, layer['fc']['kernel'], layer['fc']['bias'],
                layer['proj']['kernel'], layer['proj']['bias'],
                activation=jax.nn.gelu)
        final = _layernorm(hidden, params['ln_f']['scale'],
                           params['ln_f']['bias'])
        table = jnp.asarray(wte).astype(compute)
        logits = head_logits(final.astype(compute), table, tied=True)

        def fix(path, leaf):
            if path[-1] in (jax.tree_util.DictKey('key'),
                            jax.tree_util.DictKey('value')):
                return pools[(path[0].key, path[-1].key)]
            return leaf
        return logits, jax.tree_util.tree_map_with_path(fix, cache)

    return step


@functools.cache
def compiled_fused(decoder, steps: int, temperature: float):
    return build_fused(decoder, steps, temperature)


def build_fused(decoder, steps: int, temperature: float):
    """The fused greedy/sampling decode runner: flax prefill, then
    ``steps - 1`` fused token-steps under ``lax.scan``. Accepts plain,
    pre-cast, or quantized param trees (the flax prefill consumes a
    dequantized view; the scan streams the tree as passed)."""
    from tpusystem.train.generate import _sample
    layers, heads = decoder.layers, decoder.heads
    dim, max_seq = decoder.dim, decoder.max_seq
    head_dim = dim // heads
    compute = jnp.dtype(decoder.dtype)

    def token_step(params, k_caches, v_caches, cursor, token):
        wide = token.shape[0]
        start = cursor[0]      # ordinary decode: uniform cursor contract
        wte = params['wte']['embedding']
        wpe = params['wpe']['embedding']
        embedded = (jnp.asarray(wte)[token].astype(jnp.float32)
                    + jnp.asarray(wpe)[cursor].astype(jnp.float32))
        hidden = embedded.astype(compute)
        new_k, new_v = [], []
        for index in range(layers):
            block = params[f'h_{index}']
            normed = _layernorm(hidden, block['ln_1']['scale'],
                                block['ln_1']['bias']).astype(compute)
            attn = block['attn']
            qkv = decode_matmul(normed, attn['qkv']['kernel'],
                                attn['qkv']['bias'])
            query, key, value = jnp.split(qkv, 3, axis=-1)
            shape = (wide, heads, head_dim)
            query = query.reshape(shape)
            key_cache = jax.lax.dynamic_update_slice(
                k_caches[index],
                key.reshape((wide, 1) + shape[1:]).astype(
                    k_caches[index].dtype), (0, start, 0, 0))
            value_cache = jax.lax.dynamic_update_slice(
                v_caches[index],
                value.reshape((wide, 1) + shape[1:]).astype(
                    v_caches[index].dtype), (0, start, 0, 0))
            new_k.append(key_cache)
            new_v.append(value_cache)
            context = _bucketed_attention(query, key_cache, value_cache,
                                          cursor, max_seq)
            attended = decode_matmul(context.reshape(wide, dim),
                                     attn['out']['kernel'],
                                     attn['out']['bias'])
            hidden = hidden + attended
            normed = _layernorm(hidden, block['ln_2']['scale'],
                                block['ln_2']['bias']).astype(compute)
            hidden = hidden + decode_ffn(
                normed, block['fc']['kernel'], block['fc']['bias'],
                block['proj']['kernel'], block['proj']['bias'],
                activation=jax.nn.gelu)
        final = _layernorm(hidden, params['ln_f']['scale'],
                           params['ln_f']['bias'])
        table = jnp.asarray(wte).astype(compute)
        logits = head_logits(final.astype(compute), table, tied=True)
        return logits, tuple(new_k), tuple(new_v)

    @jax.jit
    def run(params, prompt, rng):
        # prefill through the flax module itself: identical prompt
        # logits and cache layout, flash long-prompt routing included
        plain = dequantize_streamed(params, compute)
        logits, state = decoder.apply({'params': plain}, prompt,
                                      mutable=['cache'])
        cache = state['cache']
        k_caches = tuple(cache[f'h_{i}']['attn']['key']
                         for i in range(layers))
        v_caches = tuple(cache[f'h_{i}']['attn']['value']
                         for i in range(layers))
        cursor = cache['position']                       # [B], uniform
        rng, key = jax.random.split(rng)
        token = _sample(logits[:, -1], temperature, key)

        def step(carry, _):
            k_caches, v_caches, cursor, token, rng = carry
            logits, k_caches, v_caches = token_step(
                params, k_caches, v_caches, cursor, token)
            rng, key = jax.random.split(rng)
            next_token = _sample(logits, temperature, key)
            return (k_caches, v_caches, cursor + 1, next_token, rng), token

        (_, _, _, last, _), generated = jax.lax.scan(
            step, (k_caches, v_caches, cursor, token, rng), None,
            length=steps - 1)
        generated = jnp.moveaxis(generated, 0, 1)        # [B, steps-1]
        return jnp.concatenate([prompt, generated, last[:, None]], axis=1)

    return run
