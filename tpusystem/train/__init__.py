from tpusystem.train.state import HealthStats, TrainState, resume_extras
from tpusystem.train.step import (build_1f1b_train_step, build_eval_step,
                                  build_multi_eval_step, build_multi_step,
                                  build_train_step, flax_apply,
                                  grouped_batches, init_state)
from tpusystem.train.optim import SGD, Adam, AdamW, Optimizer, masked_update
from tpusystem.train.losses import (BCEWithLogitsLoss, ChunkedNextTokenLoss,
                                    CrossEntropyLoss, MSELoss, NextTokenLoss,
                                    WithAuxLoss)
from tpusystem.train.metrics import Accuracy, Mean, Metric, Perplexity, TopKAccuracy
from tpusystem.train.generate import generate, speculative_generate
from tpusystem.train.sentinel import (HEALTH_COLUMNS, DivergenceError, Guard,
                                      Sentinel)

__all__ = ['TrainState', 'HealthStats', 'resume_extras', 'build_train_step',
           'build_1f1b_train_step', 'build_eval_step',
           'build_multi_step', 'build_multi_eval_step', 'flax_apply',
           'grouped_batches',
           'init_state', 'Optimizer', 'SGD', 'Adam', 'AdamW', 'masked_update',
           'CrossEntropyLoss', 'MSELoss', 'NextTokenLoss', 'ChunkedNextTokenLoss',
           'WithAuxLoss', 'BCEWithLogitsLoss',
           'Mean', 'Accuracy', 'TopKAccuracy', 'Perplexity', 'Metric',
           'generate', 'speculative_generate',
           'Guard', 'Sentinel', 'HEALTH_COLUMNS', 'DivergenceError']
