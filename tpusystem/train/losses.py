"""Loss criteria as registered entities.

The reference registers ``torch.nn.CrossEntropyLoss`` so the criterion
participates in experiment identity (``examples/tinysys/main.py:27-32``).
These are their pure-functional equivalents: hashable hyperparameter
recipes whose ``__call__`` is jit-traceable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from tpusystem.registry import register


@register
class CrossEntropyLoss:
    """Softmax cross-entropy over integer labels, with optional smoothing."""

    def __init__(self, label_smoothing: float = 0.0):
        self.label_smoothing = label_smoothing

    def __call__(self, logits, targets):
        if self.label_smoothing:
            classes = logits.shape[-1]
            onehot = optax.smooth_labels(
                jnp.eye(classes, dtype=logits.dtype)[targets], self.label_smoothing)
            losses = optax.softmax_cross_entropy(logits, onehot)
        else:
            losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        return jnp.mean(losses)


@register
class MSELoss:
    def __init__(self):
        ...

    def __call__(self, predictions, targets):
        return jnp.mean((predictions - targets) ** 2)


@register
class BCEWithLogitsLoss:
    """Binary cross-entropy on raw logits — the recommender workload's
    click objective (:class:`tpusystem.models.DLRM` emits one logit per
    example). Per-example mean, so gradient accumulation is exact
    without a ``weight`` seam; targets are 0/1 floats (or bools)."""

    def __init__(self):
        ...

    def __call__(self, logits, targets):
        losses = optax.sigmoid_binary_cross_entropy(
            logits.astype(jnp.float32),
            jnp.asarray(targets, jnp.float32))
        return jnp.mean(losses)


@register
class WithAuxLoss:
    """Wrap a criterion for models whose outputs are ``(predictions, aux)``
    — e.g. MoE models returning router load-balance losses
    (:mod:`tpusystem.ops.moe`). The aux term (already scaled by the model's
    coefficients) adds to the base loss; ``coef`` rescales it globally.

    Under gradient accumulation the aux term is approximate either way:
    load balance is nonlinear in batch composition, so per-microbatch aux
    values cannot reproduce the full-batch value exactly. The inner
    criterion's ``weight`` (unmasked-token count) is forwarded because
    routing pressure is per token — the base-loss term stays exact and the
    aux term is token-weighted rather than microbatch-weighted."""

    def __init__(self, criterion, coef: float = 1.0):
        self.criterion = criterion
        self.coef = coef
        if hasattr(criterion, 'weight'):  # forward the accumulation weight
            self.weight = criterion.weight

    def __call__(self, outputs, targets):
        predictions, aux = outputs
        return self.criterion(predictions, targets) + self.coef * aux


@register
class ChunkedNextTokenLoss:
    """Causal LM loss fused with the LM head, chunked over rows.

    Consumes ``(features, table)`` from a model built with
    ``return_features=True`` (:class:`tpusystem.models.GPT2` /
    :class:`~tpusystem.models.Llama`) instead of materialized logits. The
    ``[batch*seq, vocab]`` float32 logits tensor — several GB at LM scale,
    and the usual OOM driver — is never formed: rows are processed in
    ``chunks`` sequential slices, each computing its logits tile at MXU
    rate (bf16 operands, f32 accumulation), reducing to its loss
    contribution, and being rematerialized in the backward pass
    (``jax.checkpoint``), so peak memory drops by ~``chunks``x while FLOPs
    stay within 2x on the head only.

    Same semantics as :class:`NextTokenLoss`: logits[:, :-1] vs
    tokens[:, 1:], pad ids < 0 masked out, optional z-loss. ``table`` may
    be ``[vocab, dim]`` (tied embedding) or ``[dim, vocab]`` (untied head
    kernel).
    """

    def __init__(self, chunks: int = 16, z_loss: float = 0.0,
                 tied: bool | None = None):
        self.chunks = chunks
        self.z_loss = z_loss
        # table orientation; None infers from shapes and refuses the
        # ambiguous square case (vocab == dim) — pass explicitly there
        self.tied = tied

    def __call__(self, outputs, tokens):
        from tpusystem.ops.precision import head_logits

        features, table = outputs
        dim = features.shape[-1]
        rows = features[:, :-1].reshape(-1, dim)
        labels = tokens[:, 1:].reshape(-1)
        padding = -rows.shape[0] % self.chunks
        if padding:
            rows = jnp.pad(rows, ((0, padding), (0, 0)))
            labels = jnp.pad(labels, (0, padding), constant_values=-1)
        rows = rows.reshape(self.chunks, -1, dim)
        labels = labels.reshape(self.chunks, -1)

        @jax.checkpoint
        def chunk(rows_chunk, labels_chunk):
            logits = head_logits(rows_chunk, table, tied=self.tied)
            logsumexp = jax.nn.logsumexp(logits, axis=-1)
            mask = (labels_chunk >= 0).astype(jnp.float32)
            safe = jnp.maximum(labels_chunk, 0)
            true_logit = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
            return (jnp.sum((logsumexp - true_logit) * mask),
                    jnp.sum(jnp.square(logsumexp) * mask), jnp.sum(mask))

        losses, z_terms, counts = jax.lax.map(
            lambda slices: chunk(*slices), (rows, labels))
        total = jnp.maximum(jnp.sum(counts), 1.0)
        loss = jnp.sum(losses) / total
        if self.z_loss:
            loss = loss + self.z_loss * jnp.sum(z_terms) / total
        return loss

    def weight(self, tokens):
        """Unmasked-token count — the accumulation weight that makes
        microbatched means equal the full-batch mean under padding (see
        ``build_train_step(accumulate=...)``)."""
        return jnp.sum((tokens[:, 1:] >= 0).astype(jnp.float32))


@register
class NextTokenLoss:
    """Causal LM loss: cross-entropy of logits[:, :-1] vs tokens[:, 1:],
    with padding mask support (pad id < 0 excluded)."""

    def __init__(self, z_loss: float = 0.0):
        self.z_loss = z_loss

    def __call__(self, logits, tokens):
        shifted_logits = logits[:, :-1]
        shifted_targets = tokens[:, 1:]
        mask = (shifted_targets >= 0).astype(jnp.float32)
        safe_targets = jnp.maximum(shifted_targets, 0)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            shifted_logits.astype(jnp.float32), safe_targets)
        loss = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        if self.z_loss:
            logsumexp = jax.nn.logsumexp(shifted_logits.astype(jnp.float32), axis=-1)
            loss = loss + self.z_loss * jnp.sum((logsumexp ** 2) * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return loss

    def weight(self, tokens):
        """Unmasked-token count — the accumulation weight that makes
        microbatched means equal the full-batch mean under padding (see
        ``build_train_step(accumulate=...)``)."""
        return jnp.sum((tokens[:, 1:] >= 0).astype(jnp.float32))
