"""Loss criteria as registered entities.

The reference registers ``torch.nn.CrossEntropyLoss`` so the criterion
participates in experiment identity (``examples/tinysys/main.py:27-32``).
These are their pure-functional equivalents: hashable hyperparameter
recipes whose ``__call__`` is jit-traceable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from tpusystem.registry import register


@register
class CrossEntropyLoss:
    """Softmax cross-entropy over integer labels, with optional smoothing."""

    def __init__(self, label_smoothing: float = 0.0):
        self.label_smoothing = label_smoothing

    def __call__(self, logits, targets):
        if self.label_smoothing:
            classes = logits.shape[-1]
            onehot = optax.smooth_labels(
                jnp.eye(classes, dtype=logits.dtype)[targets], self.label_smoothing)
            losses = optax.softmax_cross_entropy(logits, onehot)
        else:
            losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        return jnp.mean(losses)


@register
class MSELoss:
    def __init__(self):
        ...

    def __call__(self, predictions, targets):
        return jnp.mean((predictions - targets) ** 2)


@register
class WithAuxLoss:
    """Wrap a criterion for models whose outputs are ``(predictions, aux)``
    — e.g. MoE models returning router load-balance losses
    (:mod:`tpusystem.ops.moe`). The aux term (already scaled by the model's
    coefficients) adds to the base loss; ``coef`` rescales it globally."""

    def __init__(self, criterion, coef: float = 1.0):
        self.criterion = criterion
        self.coef = coef

    def __call__(self, outputs, targets):
        predictions, aux = outputs
        return self.criterion(predictions, targets) + self.coef * aux


@register
class NextTokenLoss:
    """Causal LM loss: cross-entropy of logits[:, :-1] vs tokens[:, 1:],
    with padding mask support (pad id < 0 excluded)."""

    def __init__(self, z_loss: float = 0.0):
        self.z_loss = z_loss

    def __call__(self, logits, tokens):
        shifted_logits = logits[:, :-1]
        shifted_targets = tokens[:, 1:]
        mask = (shifted_targets >= 0).astype(jnp.float32)
        safe_targets = jnp.maximum(shifted_targets, 0)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            shifted_logits.astype(jnp.float32), safe_targets)
        loss = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        if self.z_loss:
            logsumexp = jax.nn.logsumexp(shifted_logits.astype(jnp.float32), axis=-1)
            loss = loss + self.z_loss * jnp.sum((logsumexp ** 2) * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return loss
