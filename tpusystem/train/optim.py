"""Optimizers as registered entities.

The reference registers ``torch.optim.Adam`` with ``excluded_args=[0]`` so
the parameter iterator stays out of the identity hash
(``examples/tinysys/main.py:27-32``). The TPU-native design is cleaner:
optimizers are *pure gradient transforms* (optax) that never hold parameter
references, so the wrapper classes below capture exactly their
hyperparameters — their registry hash identifies the optimization recipe and
participates in checkpoint identity.

Each wrapper exposes ``transform()`` returning the underlying
``optax.GradientTransformation``; slot variables live in
``TrainState.opt_state`` and shard with the same policy as the parameters
(ZeRO-style optimizer-state sharding falls out of GSPMD for free).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from tpusystem.registry import register


def masked_update(transform, grads, opt_state, params, ok, *, scale=None):
    """One optimizer update, suppressed in-graph when ``ok`` is False.

    The ``optax.apply_if_finite`` idea generalized to an arbitrary traced
    health verdict (finiteness AND the guard's spike z-score): the update
    and the new slot variables are computed unconditionally — one fused
    program, no host sync, no control flow — and a per-leaf ``where``
    selects between the advanced and the untouched (params, opt_state).
    A NaN/Inf gradient therefore never reaches the weights *or* the
    optimizer moments, which is what makes a skipped batch free to retry
    or discard (PaLM-style) instead of poisoning every step after it.

    ``scale`` (float32 scalar, typically ``HealthStats.lr_scale``)
    multiplies the updates before application — for optax's SGD/Adam/AdamW
    (where weight decay is folded into the update at the learning rate)
    scaling the update is exactly scaling the learning rate, so a host-side
    backoff needs no recompilation.

    Returns ``(params, opt_state)``.
    """
    updates, new_opt_state = transform.update(grads, opt_state, params)
    if scale is not None:
        updates = jax.tree.map(lambda u: u * scale.astype(u.dtype), updates)
    new_params = optax.apply_updates(params, updates)
    keep = lambda new, old: jnp.where(ok, new, old)
    return (jax.tree.map(keep, new_params, params),
            jax.tree.map(keep, new_opt_state, opt_state))


class Optimizer:
    """Base: a named, hashable recipe producing an optax transform."""

    def transform(self) -> optax.GradientTransformation:
        raise NotImplementedError

    def init(self, params):
        return self.transform().init(params)

    def update(self, grads, opt_state, params=None):
        return self.transform().update(grads, opt_state, params)


@register
class SGD(Optimizer):
    def __init__(self, lr: float = 0.01, momentum: float = 0.0, nesterov: bool = False):
        self.lr, self.momentum, self.nesterov = lr, momentum, nesterov

    def transform(self) -> optax.GradientTransformation:
        return optax.sgd(self.lr, momentum=self.momentum or None, nesterov=self.nesterov)


@register
class Adam(Optimizer):
    def __init__(self, lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
        self.lr, self.b1, self.b2, self.eps = lr, b1, b2, eps

    def transform(self) -> optax.GradientTransformation:
        return optax.adam(self.lr, b1=self.b1, b2=self.b2, eps=self.eps)


@register
class AdamW(Optimizer):
    def __init__(self, lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8, weight_decay: float = 0.01,
                 grad_clip: float = 0.0, warmup_steps: int = 0,
                 decay_steps: int = 0, min_lr_ratio: float = 0.1):
        self.lr, self.b1, self.b2, self.eps = lr, b1, b2, eps
        self.weight_decay = weight_decay
        self.grad_clip = grad_clip
        self.warmup_steps = warmup_steps
        self.decay_steps = decay_steps
        self.min_lr_ratio = min_lr_ratio

    def schedule(self):
        if not self.warmup_steps and not self.decay_steps:
            return self.lr
        if self.warmup_steps and not self.decay_steps:
            # warmup-then-constant: no cosine leg
            return optax.join_schedules(
                [optax.linear_schedule(0.0, self.lr, self.warmup_steps),
                 optax.constant_schedule(self.lr)],
                [self.warmup_steps])
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=self.lr,
            warmup_steps=max(self.warmup_steps, 1),
            decay_steps=max(self.decay_steps, self.warmup_steps + 1),
            end_value=self.lr * self.min_lr_ratio)

    def transform(self) -> optax.GradientTransformation:
        chain = []
        if self.grad_clip:
            chain.append(optax.clip_by_global_norm(self.grad_clip))
        chain.append(optax.adamw(self.schedule(), b1=self.b1, b2=self.b2,
                                 eps=self.eps, weight_decay=self.weight_decay))
        return optax.chain(*chain)
