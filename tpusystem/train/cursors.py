"""Per-row KV-cache cursor authority.

Every path that manages decode-cache rows independently — speculative
decoding's rewind-to-accepted-prefix (:mod:`tpusystem.train.generate`),
token-tree verify's winner-row copy, and the serving engine's
admit/evict row recycling (:mod:`tpusystem.serve.engine`) — edits the
same two kinds of cache leaves: the per-layer ``index`` cursor that
:func:`tpusystem.ops.attention.cached_attention` writes and masks at
(and that Llama's rotary reads), and GPT-2's model-level ``position``
offset. This module is the single implementation of those edits, so the
speculative path and the engine cannot drift on which leaves count as
cursors or how scanned stacks broadcast.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# The cache-collection leaf names that hold per-row cursor state: the
# per-layer KV cursor (``index`` — also what Llama's rotary reads) and
# GPT-2's learned-position offset (``position``).
CURSOR_KEYS = (jax.tree_util.DictKey('index'),
               jax.tree_util.DictKey('position'))


def is_cursor(path) -> bool:
    """True when a cache tree path addresses a cursor leaf."""
    return path[-1] in CURSOR_KEYS


def rewind(cache, cursor):
    """Set every cache cursor to ``cursor`` (``[batch]`` int, or a
    scalar broadcast over rows) — rows beyond it are garbage from
    rejected speculation or a retired serving row, masked out by the
    cursor-based attention mask and overwritten by the next accepted
    tokens. Scanned stacks carry cursors at a leading layer dim; the
    ``[batch]`` cursor broadcasts into whatever shape the leaf has."""
    def fix(path, leaf):
        if is_cursor(path):
            return jnp.broadcast_to(jnp.asarray(cursor, leaf.dtype),
                                    leaf.shape)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


def read_cursor(cache):
    """The per-row ``[batch]`` cursor of a decode cache — the first
    ``index`` leaf found (every layer's agrees under the :func:`rewind`
    discipline; scanned stacks return layer 0's slice)."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        if path[-1] == jax.tree_util.DictKey('index'):
            return leaf.reshape(-1, leaf.shape[-1])[0] if leaf.ndim > 1 \
                else leaf
    raise ValueError('no index cursor leaf in this cache tree — was the '
                     'cache created by a decode-mode apply?')


def gather_rows(cache, rows):
    """Overwrite every row's cache with row ``rows[i]``'s (token-tree
    verify's winner-copy): KV leaves gather on their batch axis — always
    ``ndim - 4`` for the contiguous ``[..., batch, max_seq, heads,
    head_dim]`` cache layout, which also covers scanned stacks' leading
    layer dim — and cursor leaves (``index``/``position``) on their last
    axis. Contiguous caches only: a paged cache's pool has no batch axis
    (rows alias blocks through the table), so row copies there are block
    copies, owned by :class:`tpusystem.serve.PagedKVCache`."""
    def fix(path, leaf):
        axis = leaf.ndim - 1 if is_cursor(path) else leaf.ndim - 4
        return jnp.take(leaf, rows, axis=axis)
    return jax.tree_util.tree_map_with_path(fix, cache)
