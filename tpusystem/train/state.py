"""Device-side aggregate state.

The reference's aggregate is a mutable ``nn.Module`` whose weights, optimizer
slots and step counter change in place (``torchsystem/domain/aggregate.py``).
On TPU that state must be an immutable pytree advanced by pure, jitted
functions — :class:`TrainState` is that pytree. Host-side concerns (phase,
epoch, events) stay on :class:`tpusystem.domain.Aggregate`; everything the
compiled step needs threads through here.

``TrainState`` is a registered JAX pytree dataclass: it can be donated into
a jitted step (buffer reuse in HBM), sharded over a mesh with
``NamedSharding``, and checkpointed as a single tree.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

__all__ = ['TrainState', 'HealthStats', 'resume_extras']


class HealthStats(struct.PyTreeNode):
    """Device-side training-health statistics (the ``guard=`` companion).

    Rides :attr:`TrainState.health` as ordinary pytree leaves, so the stats
    checkpoint, donate, and shard with the rest of the state for free — the
    guarded step (:func:`tpusystem.train.build_train_step` with ``guard=``)
    updates them in the same fused XLA program as the optimizer, with no
    extra host sync.

    Attributes:
        ema_norm: biased EMA of the global gradient norm (healthy steps only
            — an anomaly must not poison the statistic that detects it).
        ema_sq: biased EMA of the squared gradient norm (variance source for
            the spike z-score).
        count: number of healthy steps folded into the EMAs (bias correction
            and the spike detector's warmup gate).
        bad_steps: cumulative count of steps whose update was suppressed.
        lr_scale: multiplier applied to the optimizer's updates — the
            host-side backoff lever (:class:`tpusystem.train.Sentinel`
            halves it without recompiling; for optax's AdamW/SGD scaling the
            update is exactly scaling the learning rate).
        last: the most recent step's health row
            ``[ok, loss, grad_norm, zscore]`` (float32[4], columns
            :data:`tpusystem.train.sentinel.HEALTH_COLUMNS`) — what the
            host-side Sentinel reads at phase cadence.
    """

    ema_norm: jax.Array
    ema_sq: jax.Array
    count: jax.Array
    bad_steps: jax.Array
    lr_scale: jax.Array
    last: jax.Array

    @classmethod
    def create(cls) -> 'HealthStats':
        return cls(ema_norm=jnp.zeros((), jnp.float32),
                   ema_sq=jnp.zeros((), jnp.float32),
                   count=jnp.zeros((), jnp.int32),
                   bad_steps=jnp.zeros((), jnp.int32),
                   lr_scale=jnp.ones((), jnp.float32),
                   last=jnp.asarray([1.0, 0.0, 0.0, 0.0], jnp.float32))


class TrainState(struct.PyTreeNode):
    """Immutable training-state pytree.

    Attributes:
        params: model parameter pytree (typically bfloat16/float32 leaves).
        opt_state: optimizer slot variables (moments etc.).
        rng: PRNG key folded each step for dropout and other stochastic ops.
        step: scalar int32 step counter, lives on device so incrementing it
            never forces a host sync.
        health: :class:`HealthStats` when the state is armed for a guarded
            step (``Guard.arm(state)``), else None (an empty pytree
            subtree — unguarded jitted steps see the same donated tree as
            before). Checkpoints written before this field existed restore
            through the Checkpointer's legacy-shape fallback (the leafless
            field is pruned from the restore target and ``None`` grafted
            back); restoring such a checkpoint into an *armed* target
            fails loudly — restore unarmed, then ``arm``.
    """

    params: Any
    opt_state: Any
    rng: jax.Array
    step: jax.Array
    health: Any = None

    @classmethod
    def create(cls, params: Any, opt_state: Any, rng: jax.Array | int = 0,
               health: Any = None) -> 'TrainState':
        if isinstance(rng, int):
            rng = jax.random.PRNGKey(rng)
        return cls(params=params, opt_state=opt_state, rng=rng,
                   step=jnp.zeros((), dtype=jnp.int32), health=health)

    def next_rng(self) -> tuple['TrainState', jax.Array]:
        """Split the carried key; returns (state-with-new-key, subkey)."""
        rng, sub = jax.random.split(self.rng)
        return self.replace(rng=rng), sub

    @property
    def global_step(self) -> int:
        """Host-side view of the step counter (forces one device sync —
        checkpoint/logging cadence only, never per step)."""
        return int(self.step)


def resume_extras(state: TrainState, loader: Any = None, **extra: Any) -> dict:
    """Host-side resume metadata to ride a checkpoint's ``extras``.

    The device-side resumable state (params, optimizer slots, RNG key, step
    counter) already lives *inside* the :class:`TrainState` pytree and is
    checkpointed with it; what the restart cannot recompute is the host-side
    position — which batches the data loader already consumed. This bundles
    both halves' bookkeeping into one JSON-able dict::

        ckpt.save(identity, state.global_step, state,
                  extras=resume_extras(state, loader))
        ...
        state, step, extras = ckpt.resume(identity, state)
        loader.seek(extras['cursor'])          # skip consumed batches

    ``loader`` is anything with a ``state()`` cursor method
    (:class:`tpusystem.data.Loader`); extra keyword pairs are stored
    verbatim.
    """
    return {'step': int(state.step),
            'cursor': None if loader is None else loader.state(),
            **extra}
