"""Device-side aggregate state.

The reference's aggregate is a mutable ``nn.Module`` whose weights, optimizer
slots and step counter change in place (``torchsystem/domain/aggregate.py``).
On TPU that state must be an immutable pytree advanced by pure, jitted
functions — :class:`TrainState` is that pytree. Host-side concerns (phase,
epoch, events) stay on :class:`tpusystem.domain.Aggregate`; everything the
compiled step needs threads through here.

``TrainState`` is a registered JAX pytree dataclass: it can be donated into
a jitted step (buffer reuse in HBM), sharded over a mesh with
``NamedSharding``, and checkpointed as a single tree.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

__all__ = ['TrainState', 'resume_extras']


class TrainState(struct.PyTreeNode):
    """Immutable training-state pytree.

    Attributes:
        params: model parameter pytree (typically bfloat16/float32 leaves).
        opt_state: optimizer slot variables (moments etc.).
        rng: PRNG key folded each step for dropout and other stochastic ops.
        step: scalar int32 step counter, lives on device so incrementing it
            never forces a host sync.
    """

    params: Any
    opt_state: Any
    rng: jax.Array
    step: jax.Array

    @classmethod
    def create(cls, params: Any, opt_state: Any, rng: jax.Array | int = 0) -> 'TrainState':
        if isinstance(rng, int):
            rng = jax.random.PRNGKey(rng)
        return cls(params=params, opt_state=opt_state, rng=rng,
                   step=jnp.zeros((), dtype=jnp.int32))

    def next_rng(self) -> tuple['TrainState', jax.Array]:
        """Split the carried key; returns (state-with-new-key, subkey)."""
        rng, sub = jax.random.split(self.rng)
        return self.replace(rng=rng), sub

    @property
    def global_step(self) -> int:
        """Host-side view of the step counter (forces one device sync —
        checkpoint/logging cadence only, never per step)."""
        return int(self.step)


def resume_extras(state: TrainState, loader: Any = None, **extra: Any) -> dict:
    """Host-side resume metadata to ride a checkpoint's ``extras``.

    The device-side resumable state (params, optimizer slots, RNG key, step
    counter) already lives *inside* the :class:`TrainState` pytree and is
    checkpointed with it; what the restart cannot recompute is the host-side
    position — which batches the data loader already consumed. This bundles
    both halves' bookkeeping into one JSON-able dict::

        ckpt.save(identity, state.global_step, state,
                  extras=resume_extras(state, loader))
        ...
        state, step, extras = ckpt.resume(identity, state)
        loader.seek(extras['cursor'])          # skip consumed batches

    ``loader`` is anything with a ``state()`` cursor method
    (:class:`tpusystem.data.Loader`); extra keyword pairs are stored
    verbatim.
    """
    return {'step': int(state.step),
            'cursor': None if loader is None else loader.state(),
            **extra}
