"""Sharded embedding tables — the recommender workload's parameter tier.

A recommender model inverts every assumption the LLM workloads trained
into this codebase: parameters are dominated by embedding tables far too
large for one device (params >> activations), compute per token is tiny,
and the hot path is *row movement* — sparse gathers forward, scatter-adds
backward. This module supplies that tier:

* :class:`ShardedEmbedding` — a flax embedding whose table row-shards
  its vocab dimension over the combined ``expert``/``model`` mesh axes
  (:func:`tpusystem.parallel.sharding.table_row_spec`, the
  ``constrain_expert_major`` seam's sibling). The apply path runs inside
  ``shard_map`` with **device-side id→shard routing**: each shard
  translates global ids into its local row space, masks the ids it does
  not own, looks up its slice, and a ``psum`` over the table axes
  assembles the result — every id's row comes wholly from one shard, so
  the sum adds exact zeros and the sharded forward is **bitwise equal**
  to the unsharded one.

* a **unique-id dedup pass** (:func:`dedup_ids`) before the gather: a
  Zipfian id distribution makes duplicate ids the common case, so the
  table gather reads each distinct row once and the batch-side expansion
  is a cheap dense gather. The dedup also makes the backward's
  device-side scatter collision-free — duplicate cotangents fold into
  unique slots via XLA's segment-sum *before* the table scatter-add
  (the kernel still handles collisions for direct callers).

* the row movement itself rides the hoisted Pallas pair
  (:func:`tpusystem.ops.pallas.embedding_lookup.embedding_lookup` —
  gather + f32 scatter-add ``custom_vjp``), with the pure
  :func:`~tpusystem.ops.pallas.embedding_lookup.lookup_plan` pinning
  the ``jnp.take``/segment-sum fallback off-TPU or on untileable
  shapes.

Init is NEVER routed through ``shard_map`` (the single-init-authority
discipline from the overlap scheduler): the table param is drawn by a
plain initializer, so param trees and checkpoints are bitwise invariant
to the mesh and every knob here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.ops.pallas.embedding_lookup import embedding_lookup
from tpusystem.parallel.mesh import DATA, FSDP, shard_map
from tpusystem.parallel.sharding import (TABLE_AXES, constrain_table_rows,
                                         table_row_spec)
from tpusystem.registry import register


def dedup_ids(ids, sentinel: int):
    """Static-shape unique-id pass: ``(reps, inverse)`` with
    ``reps[inverse[j]] == ids[j]``.

    ``reps`` is ``[n]`` — the distinct ids packed at the front, the rest
    padded with ``sentinel`` (an out-of-range id the lookup masks to a
    zero row, which ``inverse`` never points at). Pure sort/cumsum/
    scatter, so it jits with static shapes; callers map invalid ids to
    ``sentinel`` *before* deduping so all padding collapses into one
    rep. The values after expansion are identical with or without the
    pass — dedup is a traffic optimization, not a semantic knob."""
    n = ids.shape[0]
    order = jnp.argsort(ids)
    sorted_ids = jnp.take(ids, order)
    first = jnp.concatenate([jnp.ones((1,), bool),
                             sorted_ids[1:] != sorted_ids[:-1]])
    slot = jnp.cumsum(first) - 1                    # slot per sorted element
    reps = jnp.full((n,), sentinel, jnp.int32).at[slot].set(sorted_ids)
    inverse = jnp.zeros((n,), jnp.int32).at[order].set(slot)
    return reps, inverse


def lookup(table, ids, weights=None, *, impl: str = 'auto',
           dedup: bool = True, block_rows: int = 256,
           interpret: bool | None = None):
    """Weighted lookup ``out[j] = w[j] * table[ids[j]]`` with the
    unique-id dedup pass in front of the gather.

    Ids outside ``[0, rows)`` (``-1`` multi-hot padding) produce zero
    rows and no gradient. With ``dedup=True`` the gather touches each
    distinct id once and the backward's batch-side scatter is
    collision-free; the output is bitwise identical either way."""
    rows = table.shape[0]
    ids = jnp.asarray(ids, jnp.int32)
    valid = (ids >= 0) & (ids < rows)
    sent = jnp.where(valid, ids, rows)
    if not dedup:
        return embedding_lookup(table, sent, weights, impl=impl,
                                block_rows=block_rows, interpret=interpret)
    reps, inverse = dedup_ids(sent, rows)
    unique_rows = embedding_lookup(table, reps, None, impl=impl,
                                   block_rows=block_rows,
                                   interpret=interpret)
    # batch-side expansion: a dense gather whose transpose (the
    # duplicate-folding segment-sum) runs before the table scatter-add
    gathered = jnp.take(unique_rows, inverse, axis=0)
    if weights is None:
        return gathered
    scaled = gathered.astype(jnp.float32) * jnp.asarray(
        weights, jnp.float32)[:, None]
    return scaled.astype(table.dtype)


def route_plan(vocab: int, count: int, mesh,
               axes=TABLE_AXES) -> str | None:
    """Pure shardability decision for one lookup: ``None`` when the
    device-side routed path applies, else the blocking reason (the
    caller falls back to the local lookup — GSPMD still places the
    table, it just routes the gather itself). Pinned by tests so mesh
    or shape drift cannot silently change which path runs."""
    if mesh is None:
        return 'no mesh'
    present = tuple(axis for axis in axes if axis in mesh.axis_names)
    shards = 1
    for axis in present:
        shards *= mesh.shape[axis]
    if shards == 1:
        return f'table axes {axes} all have size 1'
    if vocab % shards:
        return f'vocab {vocab} not divisible by {shards} table shards'
    row_shards = 1
    for axis in (DATA, FSDP):
        if axis in mesh.axis_names:
            row_shards *= mesh.shape[axis]
    if count % row_shards:
        return (f'{count} ids not divisible by the {row_shards}-way '
                f'batch sharding')
    return None


@register('ShardedEmbedding', excluded_kwargs={'mesh', 'parent', 'name'})
class ShardedEmbedding(nn.Module):
    """Embedding table row-sharded over the ``expert``/``model`` axes.

    ``__call__(ids, weights=None)`` accepts any id shape (``[B]``
    one-hot, ``[B, K]`` multi-hot with ``-1`` padding, ...) and returns
    ``ids.shape + (features,)`` rows; padded ids yield zero rows, so a
    multi-hot pool is a plain ``sum`` over the hot axis.

    On a mesh where :func:`route_plan` passes, the lookup runs inside
    ``shard_map``: ids (replicated across the table axes, row-sharded
    over data/fsdp with the batch) are routed device-side — global id →
    local row, non-owned ids masked — each shard gathers only its slice,
    and a ``psum`` over the table axes assembles rows. Exactly one shard
    contributes a given row and the rest add zeros, so the sharded
    forward is bitwise equal to the unsharded one. Otherwise (no mesh,
    size-1 table axes, indivisible shapes, init) the local path runs —
    same math, GSPMD left to its own placement.

    Attributes:
        vocab: table rows (must divide by the table-shard count).
        features: embedding dimension.
        mesh: mesh whose ``expert``/``model`` axes shard the rows.
        impl: row-movement impl — ``'auto'`` | ``'fused'`` | ``'take'``
            (:func:`~tpusystem.ops.pallas.embedding_lookup.embedding_lookup`).
        dedup: unique-id pass before the gather (:func:`dedup_ids`).
        init_scale: stddev of the normal table init.
    """

    vocab: int
    features: int
    mesh: object = None
    impl: str = 'auto'
    dedup: bool = True
    init_scale: float = 0.02

    @nn.compact
    def __call__(self, ids, weights=None):
        table = self.param('embedding',
                           nn.initializers.normal(self.init_scale),
                           (self.vocab, self.features), jnp.float32)
        shape = tuple(ids.shape)
        flat = jnp.asarray(ids, jnp.int32).reshape(-1)
        flat_w = (None if weights is None
                  else jnp.asarray(weights, jnp.float32).reshape(-1))
        blocked = (route_plan(self.vocab, flat.shape[0], self.mesh)
                   if not self.is_initializing() else 'initializing')
        if blocked is None:
            out = self._sharded(table, flat, flat_w)
        else:
            out = lookup(table, flat, flat_w, impl=self.impl,
                         dedup=self.dedup)
        return out.reshape(shape + (self.features,))

    def _sharded(self, table, flat, flat_w):
        """Device-side id→shard routing inside ``shard_map``."""
        mesh = self.mesh
        # the annotation point: pin the table row-sharded right up to
        # the manual boundary so GSPMD never reshards it on the way in
        table = constrain_table_rows(table, mesh)
        table_axes = tuple(axis for axis in TABLE_AXES
                           if axis in mesh.axis_names)
        sizes = [mesh.shape[axis] for axis in table_axes]
        shards = 1
        for size in sizes:
            shards *= size
        local_rows = self.vocab // shards
        row_axes = tuple(axis for axis in (DATA, FSDP)
                         if axis in mesh.axis_names)
        row_spec = P(row_axes) if row_axes else P()
        out_spec = P(row_axes, None) if row_axes else P(None, None)
        impl, dedup = self.impl, self.dedup
        # the weights operand exists only when the caller passed weights:
        # the unweighted hot path keeps lookup()'s fast branch (no ones
        # array sharded through the region, no extra multiply/round)
        weighted = flat_w is not None
        in_specs = (P(table_axes, None), row_spec) + (
            (row_spec,) if weighted else ())

        @functools.partial(shard_map, mesh=mesh, check_vma=False,
                           in_specs=in_specs, out_specs=out_spec)
        def run(local_table, ids, *maybe_w):
            # shard index in table_row_spec's expert-major order
            index = lax.axis_index(table_axes[0])
            for axis, size in zip(table_axes[1:], sizes[1:]):
                index = index * size + lax.axis_index(axis)
            local = ids - index * local_rows
            owned = (ids >= 0) & (local >= 0) & (local < local_rows)
            routed = jnp.where(owned, local, -1)     # -1 = masked out
            partial = lookup(local_table, routed,
                             maybe_w[0] if weighted else None,
                             impl=impl, dedup=dedup)
            # each id's row lives on exactly one shard; the psum adds
            # exact zeros from the others (bitwise-transparent)
            return lax.psum(partial, table_axes)

        return run(table, flat, *((flat_w,) if weighted else ()))
