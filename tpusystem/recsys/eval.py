"""Streaming recommender evaluation at phase cadence from the event bus.

Recommender metrics are rank statistics — AUC needs every (positive,
negative) score pair, recall@k a per-query ranking — which the LM
workloads' per-phase ``Mean``/``Perplexity`` accumulators cannot
express. These evaluators keep the repo's cadence discipline anyway: a
tiny jitted ``update`` per batch against device values (no host sync,
no data-dependent Python), ONE ``device_get`` per phase in ``compute``.

* :class:`StreamingAUC` — histogram-bucketed AUC over sigmoid scores:
  ``update`` bins each batch's scores into fixed positive/negative
  histograms on device; ``compute`` applies the rank-sum formula
  (P(random positive > random negative), half credit for same-bucket
  ties). Memory is O(buckets), resolution is 1/buckets — exact when
  scores land on bucket centers, within ~1/buckets otherwise.

* :class:`RecallAtK` — fraction of queries whose relevant item ranks in
  the top k of the score row (the retrieval convention with one
  relevant item per query — identical math to
  :class:`~tpusystem.train.metrics.TopKAccuracy` over the two-tower
  ``[B, B]`` in-batch score matrix).

* :class:`RecsysEvaluator` — drives a held-out :class:`~tpusystem.data.
  Loader` (pytree click batches riding the background prefetch thread)
  through an eval step and both accumulators. Wire it to the bus with
  :func:`evaluation_consumer`: the consumer reacts to each
  :class:`~tpusystem.observe.events.Trained` — phase cadence, exactly
  like the checkpoint and tensorboard consumers — and dispatches
  :class:`~tpusystem.observe.events.RecsysEvaluated` with the
  materialized metric floats, so the ledger/TB see recommender quality
  without the training service knowing its observers.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.observe.events import RecsysEvaluated, Trained
from tpusystem.services import Consumer
from tpusystem.train.metrics import Mean, TopKAccuracy


@partial(jax.jit, static_argnames='buckets')
def _auc_update(pos, neg, logits, targets, buckets: int):
    scores = jax.nn.sigmoid(logits.reshape(-1).astype(jnp.float32))
    index = jnp.clip((scores * buckets).astype(jnp.int32), 0, buckets - 1)
    labels = targets.reshape(-1).astype(jnp.float32)
    return pos.at[index].add(labels), neg.at[index].add(1.0 - labels)


class StreamingAUC:
    """Streaming ROC-AUC from histogrammed sigmoid scores.

    ``update(logits, targets)`` bins one batch on device (targets are
    0/1); ``compute`` syncs the two [buckets] histograms once and
    returns the rank-sum AUC (0.5 when a class is absent)."""

    def __init__(self, buckets: int = 512):
        self.buckets = buckets
        self.reset()

    def reset(self) -> None:
        self._pos = jnp.zeros((self.buckets,), jnp.float32)
        self._neg = jnp.zeros((self.buckets,), jnp.float32)

    def update(self, logits, targets) -> None:
        self._pos, self._neg = _auc_update(self._pos, self._neg,
                                           logits, targets, self.buckets)

    def compute(self) -> float:
        pos, neg = (np.asarray(part) for part in
                    jax.device_get((self._pos, self._neg)))
        positives, negatives = pos.sum(), neg.sum()
        if positives == 0 or negatives == 0:
            return 0.5
        below = np.cumsum(neg) - neg         # negatives strictly below
        wins = np.sum(pos * (below + 0.5 * neg))
        return float(wins / (positives * negatives))


class RecallAtK(TopKAccuracy):
    """Recall@k over score rows with one relevant item per query
    (``update(scores [B, C], relevant [B])``) — the retrieval reading of
    top-k accuracy, named for the recsys convention."""


class RecsysEvaluator:
    """Held-out streaming eval: AUC (+ loss) for click models, recall@k
    for retrieval models.

    ``run(state)`` iterates the loader once (pytree batches, background
    prefetch), feeds every batch through a jitted eval step, and updates
    the accumulators on device; metrics materialize in one host sync at
    the end. Which metrics apply follows the model's output rank: ``[B]``
    click logits feed AUC, a ``[B, B]`` in-batch score matrix feeds
    recall@k against the diagonal.
    """

    def __init__(self, module, loader, criterion=None, k: int = 10,
                 buckets: int = 512):
        from tpusystem.train import (BCEWithLogitsLoss, build_eval_step,
                                     flax_apply)
        self.loader = loader
        self.k = k
        # the default BCE criterion only means anything for [B] click
        # logits — for a retrieval model pass the training criterion
        # (e.g. CrossEntropyLoss) explicitly or no loss is reported
        self._explicit_criterion = criterion is not None
        self._step = build_eval_step(flax_apply(module),
                                     criterion or BCEWithLogitsLoss())
        self.auc = StreamingAUC(buckets)
        self.recall = RecallAtK(k)
        self.loss = Mean()

    def run(self, state) -> dict[str, float]:
        self.auc.reset()
        self.recall.reset()
        self.loss.reset()
        ranked = False
        for features, labels in self.loader:
            outputs, loss = self._step(state, features, labels)
            self.loss.update(loss)
            if outputs.ndim == 2:            # [B, B] in-batch score matrix
                ranked = True
                self.recall.update(outputs,
                                   jnp.arange(outputs.shape[0], dtype=jnp.int32))
            else:
                self.auc.update(outputs, labels)
        if ranked:
            # the default BCE loss is meaningless against a [B, B] score
            # matrix — report it only when the caller supplied the
            # criterion that matches the model's training objective
            metrics = ({'loss': self.loss.compute()}
                       if self._explicit_criterion else {})
            metrics[f'recall@{self.k}'] = self.recall.compute()
        else:
            metrics = {'loss': self.loss.compute(),
                       'auc': self.auc.compute()}
        return metrics


def evaluation_consumer(evaluator: RecsysEvaluator,
                        state_of: Callable[[Any], Any] | None = None,
                        producer=None, subject: Any = None):
    """Consumer running the streaming eval at phase cadence.

    Reacts to :class:`~tpusystem.observe.events.Trained` (the training
    service dispatches one per train phase), pulls the current
    ``TrainState`` off the aggregate (``state_of(model)``, default
    ``model.state``), runs the evaluator, and — when ``producer`` is
    given — dispatches :class:`~tpusystem.observe.events.RecsysEvaluated`
    so downstream consumers (ledger, tensorboard) chart the metrics.

    ``subject`` scopes the handler on a shared bus: pass the aggregate
    instance (or its ``id``) this evaluator's module belongs to, and
    ``Trained`` events from *other* models are ignored — the evaluator's
    eval step is bound to one module, so another model's state would be
    a param-tree mismatch. ``None`` (single-model buses) reacts to every
    ``Trained``."""
    state_of = state_of or (lambda model: model.state)
    consumer = Consumer('recsys-eval')

    @consumer.handler
    def on_trained(event: Trained) -> None:
        if subject is not None and event.model is not subject \
                and getattr(event.model, 'id', None) != subject:
            return
        metrics = evaluator.run(state_of(event.model))
        if producer is not None:
            producer.dispatch(RecsysEvaluated(event.model, metrics))

    return consumer
