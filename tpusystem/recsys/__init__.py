"""Recommender workload: sharded embedding tables + streaming eval.

The second "real workload" family (ROADMAP item 5): huge sparse lookups
into row-sharded tables, tiny dense compute, heavy multi-hot input
pipelines — the stress profile the LLM paths never apply. The models
themselves live with the rest of the zoo
(:class:`tpusystem.models.DLRM` / :class:`~tpusystem.models.TwoTower`);
this package owns the embedding tier and the rank-statistic evaluation.
"""

from tpusystem.recsys.embedding import (ShardedEmbedding, dedup_ids, lookup,
                                        route_plan)
from tpusystem.recsys.eval import (RecallAtK, RecsysEvaluator, StreamingAUC,
                                   evaluation_consumer)

__all__ = ['ShardedEmbedding', 'dedup_ids', 'lookup', 'route_plan',
           'StreamingAUC', 'RecallAtK', 'RecsysEvaluator',
           'evaluation_consumer']
