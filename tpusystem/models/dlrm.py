"""DLRM-style recommender models (the second "real workload" family).

The LLM families stress dense compute; these stress everything else —
huge sparse lookups into row-sharded tables
(:class:`tpusystem.recsys.ShardedEmbedding`), tiny dense MLPs, and heavy
multi-hot input pipelines. Two variants:

* :class:`DLRM` — the Meta DLRM shape (Naumov et al., 2019): dense
  features through a bottom MLP, multi-hot sparse features pooled from
  sharded embedding tables, pairwise dot-product feature interactions,
  a small top MLP onto one click logit. Trained with
  :class:`tpusystem.train.BCEWithLogitsLoss` through the ordinary
  ``build_train_step``/policy machinery — DP batch sharding composes
  with table row-sharding on the same mesh.

* :class:`TwoTower` — the retrieval shape: user and item towers over
  their own sharded tables, L2-normalized, scored against each other.
  ``__call__`` returns the in-batch ``[B, B]`` score matrix (sampled
  softmax training with ``targets = arange(B)``; recall@k eval reads
  the same matrix).

Both ship their ``partition_rules()`` (tables row-sharded via
:func:`tpusystem.parallel.sharding.table_row_spec`; the dense MLPs are
small enough to replicate) so ``TensorParallel``/``ShardingPolicy``
places them without per-experiment configuration. All dense math is
float32 — at these widths the MXU is never the bottleneck, the tables
are.
"""

from __future__ import annotations

from collections.abc import Sequence

import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from tpusystem.recsys.embedding import ShardedEmbedding
from tpusystem.registry import register


class _MLP(nn.Module):
    """Plain relu MLP (hidden widths then a linear head of ``out`` units)."""

    widths: Sequence[int]
    out: int

    @nn.compact
    def __call__(self, hidden):
        for index, width in enumerate(self.widths):
            hidden = nn.relu(nn.Dense(width, name=f'fc_{index}')(hidden))
        return nn.Dense(self.out, name='head')(hidden)


class DLRM(nn.Module):
    """Deep Learning Recommendation Model over sharded embedding tables.

    ``__call__(batch)`` takes a pytree batch (the shape
    :class:`tpusystem.data.SyntheticClicks` yields)::

        {'dense': [B, dense_features] float,
         'ids':   [B, features, hot] int32, -1-padded multi-hot,
         'weights': [B, features, hot] float (optional per-id weights)}

    and returns ``[B]`` click logits. Sparse feature *f* looks up table
    *f* (its own vocab), pools the hot rows by summation (padded ids
    contribute exact zero rows), and the ``1 + features`` vectors
    (bottom-MLP output first) interact via their pairwise dot products —
    the DLRM interaction arch — before the top MLP.

    Attributes:
        vocabs: per-sparse-feature table sizes.
        dim: embedding dimension (shared — interactions need one width).
        dense_features: width of the dense input slice (shape check).
        bottom: bottom-MLP hidden widths (output is always ``dim``).
        top: top-MLP hidden widths (output is always one logit).
        mesh: mesh whose ``expert``/``model`` axes row-shard the tables.
        impl / dedup: lookup knobs, threaded to every table
            (:class:`~tpusystem.recsys.ShardedEmbedding`).
    """

    vocabs: Sequence[int] = (128, 64)
    dim: int = 16
    dense_features: int = 4
    bottom: Sequence[int] = (32,)
    top: Sequence[int] = (32,)
    mesh: object = None
    impl: str = 'auto'
    dedup: bool = True

    @nn.compact
    def __call__(self, batch, train: bool = False):
        dense = jnp.asarray(batch['dense'], jnp.float32)
        ids = batch['ids']
        weights = batch.get('weights') if hasattr(batch, 'get') else None
        assert dense.shape[-1] == self.dense_features, (
            f'dense slice is {dense.shape[-1]} wide, '
            f'model expects {self.dense_features}')
        assert ids.shape[1] == len(self.vocabs), (
            f'batch carries {ids.shape[1]} sparse features, '
            f'model has {len(self.vocabs)} tables')

        bottom = _MLP(self.bottom, self.dim, name='bottom')(dense)
        vectors = [bottom]
        for feature, vocab in enumerate(self.vocabs):
            rows = ShardedEmbedding(
                vocab, self.dim, mesh=self.mesh, impl=self.impl,
                dedup=self.dedup, name=f'table_{feature}')(
                    ids[:, feature],
                    None if weights is None else weights[:, feature])
            vectors.append(jnp.sum(rows, axis=1))   # padded rows are zero
        stacked = jnp.stack(vectors, axis=1)        # [B, 1+F, dim]
        # pairwise dot-product interactions, strictly-lower triangle
        inter = jnp.einsum('btd,bsd->bts', stacked, stacked)
        lower = np.tril_indices(stacked.shape[1], k=-1)
        tri = inter[:, lower[0], lower[1]]
        logits = _MLP(self.top, 1, name='top')(
            jnp.concatenate([bottom, tri], axis=-1))
        return logits[:, 0]

    @staticmethod
    def partition_rules():
        """Tables row-sharded over the combined ``expert``/``model``
        axes (:func:`~tpusystem.parallel.sharding.table_row_spec`); the
        tiny MLPs stay replicated (combine with ``fsdp=True`` on the
        policy to scatter them anyway)."""
        from tpusystem.parallel.sharding import table_row_spec
        return ((r'table_\d+/embedding$', table_row_spec(2)),)


register(DLRM, excluded_kwargs={'mesh'})


class TwoTower(nn.Module):
    """Two-tower retrieval model over sharded user/item tables.

    ``__call__({'user': [B] or [B, K] ids, 'item': [B] ids})`` embeds
    each side (multi-hot user histories pool by mean), runs it through
    its tower MLP, L2-normalizes, and returns the in-batch ``[B, B]``
    score matrix ``scores[i, j] = <user_i, item_j> / temperature`` —
    train it as a B-way classification with ``targets = arange(B)``
    (in-batch sampled softmax) and evaluate recall@k on the same matrix.
    """

    users: int = 256
    items: int = 128
    dim: int = 16
    tower: Sequence[int] = (32,)
    temperature: float = 0.05
    mesh: object = None
    impl: str = 'auto'
    dedup: bool = True

    def _tower(self, name: str, vocab: int, ids):
        rows = ShardedEmbedding(vocab, self.dim, mesh=self.mesh,
                                impl=self.impl, dedup=self.dedup,
                                name=f'{name}_table')(ids)
        if rows.ndim == 3:                          # multi-hot history
            count = jnp.sum((ids >= 0).astype(jnp.float32), axis=1)
            rows = jnp.sum(rows, axis=1) / jnp.maximum(count, 1.0)[:, None]
        vector = _MLP(self.tower, self.dim, name=f'{name}_tower')(rows)
        norm = jnp.sqrt(jnp.sum(vector * vector, axis=-1, keepdims=True))
        return vector / jnp.maximum(norm, 1e-6)

    @nn.compact
    def __call__(self, batch, train: bool = False):
        user = self._tower('user', self.users, batch['user'])
        item = self._tower('item', self.items, batch['item'])
        return (user @ item.T) / self.temperature

    @staticmethod
    def partition_rules():
        from tpusystem.parallel.sharding import table_row_spec
        return ((r'(user|item)_table/embedding$', table_row_spec(2)),)


register(TwoTower, excluded_kwargs={'mesh'})


def dlrm_tiny(**overrides) -> DLRM:
    """Test/dry-run scale: compiles in seconds on CPU."""
    config = dict(vocabs=(64, 32), dim=8, dense_features=4,
                  bottom=(16,), top=(16,))
    config.update(overrides)
    return DLRM(**config)


def two_tower_tiny(**overrides) -> TwoTower:
    config = dict(users=64, items=32, dim=8, tower=(16,))
    config.update(overrides)
    return TwoTower(**config)
