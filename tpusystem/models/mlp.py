"""Multi-layer perceptron (the reference model zoo's first entry:
``examples/tinysys/tinysys/modules/mlp.py`` — 2-layer MLP with dropout)."""

from __future__ import annotations

from collections.abc import Sequence

import jax.numpy as jnp
from flax import linen as nn

from tpusystem.registry import register


@register
class MLP(nn.Module):
    """Flattening MLP classifier with dropout between hidden layers.

    Attributes:
        features: hidden-layer widths.
        classes: output dimension.
        dropout: drop probability applied after each hidden activation.
        dtype: activation dtype (bfloat16 on TPU keeps the MXU fed).
    """

    features: Sequence[int] = (256, 128)
    classes: int = 10
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs, train: bool = False):
        hidden = inputs.reshape((inputs.shape[0], -1)).astype(self.dtype)
        for width in self.features:
            hidden = nn.Dense(width, dtype=self.dtype)(hidden)
            hidden = nn.relu(hidden)
            hidden = nn.Dropout(self.dropout, deterministic=not train)(hidden)
        return nn.Dense(self.classes, dtype=jnp.float32)(hidden)
