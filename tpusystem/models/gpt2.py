"""GPT-2 language model (flagship of the BASELINE.md workload ladder:
"GPT-2 125M LM aggregate, GSPMD FSDP" — BASELINE.json configs[3]).

TPU-first choices: bfloat16 activations with float32 layernorm/softmax/loss,
weights kept float32 (master copies) and cast per-use; attention through
:func:`tpusystem.ops.attention.dot_product_attention`; Megatron-style tensor
partition rules shipped with the model (``GPT2.partition_rules()``) so the
``TensorParallel``/``FullyShardedDataParallel`` policies shard it without
per-experiment configuration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from tpusystem.ops.attention import attend
from tpusystem.ops.precision import head_logits
from tpusystem.registry import register

# Megatron TP splits for one transformer block's leaf paths: qkv/fc split
# columns on `model`, out/proj split rows (their all-reduce rides ICI).
# Single source for every layout: GPT2.partition_rules uses them plain and
# shifted past the `hs/` scan dim; GPT2Pipelined feeds them to
# PipelineParallel(stacked_rules=...) shifted past the stage dim(s).
BLOCK_TP_RULES = (
    (r'attn/qkv/kernel$', P(None, 'model')),
    (r'attn/out/kernel$', P('model', None)),
    (r'fc/kernel$', P(None, 'model')),
    (r'proj/kernel$', P('model', None)),
)


class SelfAttention(nn.Module):
    """Causal multi-head self-attention with a pluggable kernel.

    ``kernel='xla'`` (default) is einsum attention that GSPMD shards freely —
    required under the DP/FSDP/TP policies, since a Pallas call cannot be
    auto-partitioned. ``'flash'`` is the Pallas O(seq)-memory kernel for
    single-chip runs; ``'ring'``/``'ulysses'`` are the sequence-parallel
    variants (shard_map over the mesh's seq axis).

    ``attn_dropout=None`` (default) applies ``dropout`` to the attention
    probabilities on the 'xla' and 'flash' kernels — the torch-reference
    behavior ('flash' drops in-kernel via positional hash masks) — and 0.0
    on the sequence-parallel kernels; set it explicitly to override.
    """

    heads: int
    dropout: float
    dtype: jnp.dtype
    kernel: str = 'xla'    # 'xla' | 'flash' (Pallas) | 'ring' | 'ulysses'
    mesh: object = None    # required for 'ring'/'ulysses' (seq-sharded)
    attn_dropout: float | None = None  # None -> follow `dropout`
    decode: bool = False   # KV-cache incremental decoding (xla kernel only)
    max_seq: int = 1024    # cache capacity when decoding
    per_row_decode: bool = False  # per-row cache cursors (speculative decoding)
    decode_pages: tuple | None = None  # (num_blocks, block_size): paged
    # block-pool KV cache with per-row block tables (the serving engine's
    # layout — ops.attention.paged_attention)

    @nn.compact
    def __call__(self, hidden, train: bool = False):
        if self.attn_dropout is None:
            attn_dropout = (self.dropout if self.kernel in ('xla', 'flash')
                            else 0.0)
        else:
            attn_dropout = self.attn_dropout
            if attn_dropout and self.kernel not in ('xla', 'flash'):
                raise ValueError(
                    "attention-probability dropout is only implemented on "
                    f"the 'xla' and 'flash' kernels, not {self.kernel!r}")
        dim = hidden.shape[-1]
        head_dim = dim // self.heads
        qkv = nn.Dense(3 * dim, dtype=self.dtype, name='qkv')(hidden)
        query, key, value = jnp.split(qkv, 3, axis=-1)
        shape = hidden.shape[:2] + (self.heads, head_dim)
        query, key, value = (t.reshape(shape) for t in (query, key, value))
        if self.decode:
            from tpusystem.ops.attention import cached_attention
            context = cached_attention(self, query, key, value, self.max_seq,
                                       per_row=self.per_row_decode,
                                       pages=self.decode_pages)
        else:
            dropout = attn_dropout if train else 0.0
            context = attend(
                query, key, value, kernel=self.kernel, mesh=self.mesh,
                causal=True, dropout=dropout,
                dropout_rng=self.make_rng('dropout') if dropout else None)
        context = context.reshape(hidden.shape)
        return nn.Dense(dim, dtype=self.dtype, name='out')(context)


class Block(nn.Module):
    """Transformer block. With ``moe_experts > 0`` the FFN is an
    expert-parallel :class:`~tpusystem.ops.moe.MoEMLP` and the block
    returns ``(hidden, aux_loss)`` instead of ``hidden``."""

    heads: int
    mlp_ratio: int
    dropout: float
    dtype: jnp.dtype
    attention: str = 'xla'
    mesh: object = None
    attn_dropout: float | None = None
    decode: bool = False
    max_seq: int = 1024
    per_row_decode: bool = False
    decode_pages: tuple | None = None  # paged KV pool (see SelfAttention)
    moe_experts: int = 0
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_exchange: str = 'quota'
    moe_sparse_impl: str = 'gather'  # single-shard row movement:
    # 'gather' | 'scatter' | 'fused' (Pallas grouped gather-matmul)
    tp_impl: str = 'gspmd'  # dense-FFN TP collectives: 'gspmd' (monolithic
    # all-gather/reduce-scatter inserted by the partitioner) | 'overlap'
    # (decomposed latency-hiding ring matmuls, parallel/overlap.py)
    tp_chunks: int = 1  # ppermute payload split per overlap ring hop
    schedule: object = None  # OverlapSchedule composing the TP rings with
    # FSDP param-prefetch/grad-scatter under one knob
    # (parallel/schedule.py); None -> built from the legacy
    # tp_impl=/tp_chunks= pair (fsdp stays on the GSPMD path)

    @nn.compact
    def __call__(self, hidden, train: bool = False):
        from tpusystem.parallel.schedule import resolve_schedule
        schedule = resolve_schedule(self.schedule, self.tp_impl,
                                    self.tp_chunks)
        dim = hidden.shape[-1]
        normed = nn.LayerNorm(dtype=jnp.float32, name='ln_1')(hidden)
        attended = SelfAttention(self.heads, self.dropout, self.dtype,
                                 kernel=self.attention, mesh=self.mesh,
                                 attn_dropout=self.attn_dropout,
                                 decode=self.decode, max_seq=self.max_seq,
                                 per_row_decode=self.per_row_decode,
                                 decode_pages=self.decode_pages,
                                 name='attn')(
            normed.astype(self.dtype), train)
        attended = nn.Dropout(self.dropout, deterministic=not train)(attended)
        hidden = hidden + attended
        normed = nn.LayerNorm(dtype=jnp.float32, name='ln_2')(hidden)
        if self.moe_experts:
            from tpusystem.ops.moe import MoEMLP
            # the schedule's moe= arm reaches the expert dispatch here:
            # with moe='overlap' the sharded quota exchange pipelines its
            # all_to_all under the expert matmuls (ops/moe.py)
            # decode dispatches at FULL capacity: no drops, so a token's
            # expert mix is independent of co-batched traffic and of the
            # serving engine's pad buckets — the engine's token-exactness
            # contract (training keeps the capacity_factor economics)
            shrunk, aux = MoEMLP(self.moe_experts, k=self.moe_k,
                                 mlp_ratio=self.mlp_ratio,
                                 capacity_factor=self.moe_capacity_factor,
                                 dtype=self.dtype, mesh=self.mesh,
                                 exchange=self.moe_exchange,
                                 sparse_impl=self.moe_sparse_impl,
                                 schedule=schedule,
                                 full_capacity=self.decode,
                                 name='moe')(normed.astype(self.dtype))
        else:
            from tpusystem.parallel.overlap import DenseParams
            from tpusystem.parallel.schedule import (schedule_applicable,
                                                     scheduled_ffn)
            grown_features = self.mlp_ratio * dim
            # init ALWAYS takes the nn.Dense path below: the legacy
            # (non-partitionable) threefry generates different bits when
            # the scanned init program shards the drawn kernels through
            # the manual region's in_specs, so routing init through the
            # scheduled branch would silently change the draws on
            # composed fsdp x model meshes — nn.Dense is the single init
            # authority, the schedule a pure apply-time knob
            if (not self.is_initializing()
                    and schedule_applicable(schedule, self.mesh,
                                            normed.shape, grown_features)):
                # the scheduled FFN (parallel/schedule.py): the sequence
                # rows all-gather INTO the fc matmul and the proj matmul
                # reduce-scatters them back (decomposed rings when
                # schedule.tp='overlap'), and with schedule.fsdp=
                # 'prefetch' the kernels enter still FSDP-sharded — their
                # gathers issue at FFN entry (the proj kernel's transfer
                # hides under the fc matmul) and the grad reduce-scatter
                # is deferred off the backward critical path. Params are
                # created at nn.Dense's exact paths, so the knob never
                # changes a checkpoint; shapes that cannot tile fall
                # through to the GSPMD Dense path below.
                w_fc, b_fc = DenseParams(grown_features, name='fc')(dim)
                w_proj, b_proj = DenseParams(dim, name='proj')(grown_features)
                shrunk = scheduled_ffn(
                    normed.astype(self.dtype),
                    w_fc.astype(self.dtype), b_fc.astype(self.dtype),
                    w_proj.astype(self.dtype), b_proj.astype(self.dtype),
                    self.mesh, schedule=schedule, activation=nn.gelu)
            else:
                grown = nn.Dense(self.mlp_ratio * dim, dtype=self.dtype,
                                 name='fc')(normed.astype(self.dtype))
                grown = nn.gelu(grown)
                shrunk = nn.Dense(dim, dtype=self.dtype, name='proj')(grown)
            aux = None
        shrunk = nn.Dropout(self.dropout, deterministic=not train)(shrunk)
        hidden = hidden + shrunk
        return (hidden, aux) if self.moe_experts else hidden


class BlockSpan(nn.Module):
    """``span`` consecutive blocks; with ``moe_experts > 0`` every
    ``moe_every``-th block in the span is MoE.

    The homogeneous unit that lets heterogeneous/deep stacks ride
    ``nn.scan``: scanning over ``layers/span`` identical spans compiles
    ONE span body instead of unrolling. Two composable uses:

    * MoE-every-k: ``span`` a multiple of ``moe_every`` — block index
      ``i`` is MoE iff ``i % moe_every == moe_every - 1`` (params under
      ``moe_{i}``, dense under ``d_{i}``); returns ``(hidden, aux)`` with
      ``aux`` the mean router loss of the span's MoE blocks.
    * ``scan_unit`` grouping (``moe_experts == 0``): k dense layers per
      scan step keep the scan length under the TPU compiler's
      nested-loop cliff (an outer steps-loop over a layer-scan longer
      than ~8 iterations sends the AOT compile from seconds to >10
      minutes); returns ``hidden`` alone."""

    heads: int
    mlp_ratio: int
    dropout: float
    dtype: jnp.dtype
    span: int = 2
    attention: str = 'xla'
    mesh: object = None
    attn_dropout: float | None = None
    decode: bool = False
    max_seq: int = 1024
    per_row_decode: bool = False
    decode_pages: tuple | None = None  # paged KV pool (see SelfAttention)
    moe_experts: int = 0
    moe_every: int = 2
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_exchange: str = 'quota'
    moe_sparse_impl: str = 'gather'  # single-shard row movement:
    # 'gather' | 'scatter' | 'fused' (Pallas grouped gather-matmul)
    tp_impl: str = 'gspmd'  # dense-FFN TP collectives: 'gspmd' | 'overlap'
    tp_chunks: int = 1
    schedule: object = None  # OverlapSchedule (see Block.schedule)

    @nn.compact
    def __call__(self, hidden, train: bool = False):
        common = dict(attention=self.attention, mesh=self.mesh,
                      attn_dropout=self.attn_dropout, decode=self.decode,
                      max_seq=self.max_seq,
                      per_row_decode=self.per_row_decode,
                      decode_pages=self.decode_pages,
                      tp_impl=self.tp_impl, tp_chunks=self.tp_chunks,
                      schedule=self.schedule)
        if self.moe_experts and self.span % self.moe_every:
            raise ValueError(f'span ({self.span}) must be a multiple of '
                             f'moe_every ({self.moe_every})')
        aux_terms = []
        for index in range(self.span):
            is_moe = (self.moe_experts > 0
                      and index % self.moe_every == self.moe_every - 1)
            if is_moe:
                hidden, aux = Block(
                    self.heads, self.mlp_ratio, self.dropout, self.dtype,
                    moe_experts=self.moe_experts, moe_k=self.moe_k,
                    moe_capacity_factor=self.moe_capacity_factor,
                    moe_exchange=self.moe_exchange,
                    moe_sparse_impl=self.moe_sparse_impl,
                    name=f'moe_{index}', **common)(hidden, train)
                aux_terms.append(aux)
            else:
                hidden = Block(self.heads, self.mlp_ratio, self.dropout,
                               self.dtype, name=f'd_{index}',
                               **common)(hidden, train)
        if not aux_terms:
            return hidden
        return hidden, jnp.mean(jnp.stack(aux_terms))


class GPT2(nn.Module):
    """Decoder-only transformer with learned positions and tied LM head.

    125M preset == defaults (vocab 50257, 12 x 768, 12 heads, seq 1024).
    """

    vocab_size: int = 50257
    layers: int = 12
    dim: int = 768
    heads: int = 12
    max_seq: int = 1024
    mlp_ratio: int = 4
    dropout: float = 0.1
    dtype: str = 'bfloat16'
    attention: str = 'xla'  # 'xla' (GSPMD-shardable) | 'flash' | 'ring' | 'ulysses'
    mesh: object = None  # mesh for ring/ulysses sequence parallelism
    attn_dropout: float | None = None  # None -> follow `dropout` on the
    # 'xla' and 'flash' kernels (flash drops in-kernel), 0 elsewhere
    remat: bool = False  # recompute each block's activations in backward
    scan_layers: bool = False  # one lax.scan over stacked block params
    # instead of `layers` unrolled copies: XLA compiles ONE block body, so
    # compile time stops scaling with depth (the 32-layer 8B unroll is the
    # compile-time cliff); params live under 'hs' with a leading layer dim
    scan_unit: int = 1  # layers per scan step (scan_layers=True): group k
    # blocks into one BlockSpan body so the scan length is layers/k — the
    # TPU backend's nested-loop optimization goes super-linear when an
    # outer steps-loop wraps a layer-scan longer than ~8 iterations, so
    # deep stacks inside compiled training loops pick k with
    # layers/k <= 8 (measured: 12-layer scan in a 90-step loop >10 min
    # AOT; 6x2 compiles in seconds at identical runtime math)
    return_features: bool = False  # return (features, wte table) for a fused
    # chunked LM loss (train.ChunkedNextTokenLoss) instead of full logits
    decode: bool = False  # KV-cache autoregressive decoding (see
    # tpusystem.train.generate; apply with mutable=['cache'])
    per_row_decode: bool = False  # per-row cache cursors: cache writes use a
    # 2D gather-index scatter so rows advance independently (speculative
    # decoding); False keeps ordinary decode on the faster
    # dynamic_update_slice at the shared cursor
    decode_pages: tuple | None = None  # (num_blocks, block_size): paged
    # block-pool KV cache with per-row block tables — the serving
    # engine's layout (tpusystem.serve; ops.attention.paged_attention).
    # Implies per-row cursors; admission/eviction are host-side table
    # edits, never a cache reshape
    moe_experts: int = 0  # >0: MoE FFN in every `moe_every`-th block
    moe_every: int = 2
    moe_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_exchange: str = 'quota'  # multi-device exchange: 'quota' | 'ragged'
    # | 'ragged-emulated' (see tpusystem.ops.moe.MoEMLP)
    moe_sparse_impl: str = 'gather'  # single-shard row movement:
    # 'gather' | 'scatter' | 'fused' (Pallas grouped gather-matmul)
    tp_impl: str = 'gspmd'  # dense-FFN TP collectives: 'gspmd' (monolithic
    # partitioner-inserted all-gather/reduce-scatter) | 'overlap'
    # (decomposed latency-hiding ring matmuls — parallel/overlap.py;
    # needs a mesh with model > 1, falls back per-shape otherwise)
    tp_chunks: int = 1  # ppermute payload split per overlap ring hop
    schedule: object = None  # parallel.OverlapSchedule: ONE knob composing
    # the TP rings (tp='overlap') with FSDP param-prefetch/grad-scatter
    # hiding (fsdp='prefetch') and their shared ppermute chunking; None
    # keeps the legacy tp_impl=/tp_chunks= behavior (fsdp on GSPMD).
    # Purely an implementation schedule — param trees and checkpoints are
    # bitwise knob-invariant

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        compute_dtype = jnp.dtype(self.dtype)
        if self.decode:
            # absolute positions continue from the per-row cache cursor
            # ([batch] — speculative decoding rewinds rows independently)
            offset = self.variable(
                'cache', 'position',
                lambda: jnp.zeros((tokens.shape[0],), jnp.int32))
            positions = offset.value[:, None] + jnp.arange(tokens.shape[-1])
            if not self.is_initializing():
                offset.value = offset.value + tokens.shape[-1]
        else:
            positions = jnp.arange(tokens.shape[-1])
        token_embedding = nn.Embed(self.vocab_size, self.dim,
                                   dtype=jnp.float32, name='wte')
        hidden = token_embedding(tokens)
        hidden = hidden + nn.Embed(self.max_seq, self.dim,
                                   dtype=jnp.float32, name='wpe')(positions)
        hidden = nn.Dropout(self.dropout, deterministic=not train)(hidden)
        hidden = hidden.astype(compute_dtype)
        assert tokens.shape[-1] <= self.max_seq, (
            f'sequence length {tokens.shape[-1]} exceeds max_seq={self.max_seq}')
        block_cls = nn.remat(Block, static_argnums=(2,)) if self.remat else Block
        aux_losses = []
        if self.scan_layers:
            # one compiled block body, stacked params, lax.scan over depth —
            # compile time is O(1) in layer count instead of O(layers).
            # MoE-every-k stacks scan over homogeneous (dense*, moe) SPANS
            # (BlockSpan); decode-mode KV caches scan along with the params
            # (variable_axes carries the 'cache' collection, so each layer
            # slice owns its cache at a leading layer dim).
            common = dict(attention=self.attention, mesh=self.mesh,
                          attn_dropout=self.attn_dropout,
                          decode=self.decode, max_seq=self.max_seq,
                          per_row_decode=self.per_row_decode,
                          decode_pages=self.decode_pages,
                          tp_impl=self.tp_impl, tp_chunks=self.tp_chunks,
                          schedule=self.schedule)
            from tpusystem.parallel.mesh import scan_carry_constraint
            constrain = scan_carry_constraint(self.mesh)
            if self.moe_experts:
                # span = scan_unit when set (must be a multiple of
                # moe_every — the MoE pattern repeats inside the span),
                # else one moe_every group per scan step
                span_size = (self.scan_unit if self.scan_unit > 1
                             else self.moe_every)
                if span_size % self.moe_every:
                    raise ValueError(
                        f'scan_unit ({span_size}) must be a multiple of '
                        f'moe_every ({self.moe_every}) so each scanned '
                        f'span carries whole MoE groups')
                if self.layers % span_size:
                    raise ValueError(
                        f'scan_layers with moe_experts needs layers '
                        f'({self.layers}) divisible by the span '
                        f'({span_size})')
                span_cls = (nn.remat(BlockSpan, static_argnums=(2,))
                            if self.remat else BlockSpan)
                template = span_cls(self.heads, self.mlp_ratio,
                                    self.dropout, compute_dtype,
                                    span=span_size,
                                    moe_experts=self.moe_experts,
                                    moe_every=self.moe_every,
                                    moe_k=self.moe_k,
                                    moe_capacity_factor=self.moe_capacity_factor,
                                    moe_exchange=self.moe_exchange,
                                    moe_sparse_impl=self.moe_sparse_impl,
                                    name='hs', **common)
                length = self.layers // span_size
                body = lambda block, carry, _: block(constrain(carry), train)
            elif self.scan_unit > 1:
                if self.layers % self.scan_unit:
                    raise ValueError(
                        f'scan_unit={self.scan_unit} must divide layers '
                        f'({self.layers})')
                span_cls = (nn.remat(BlockSpan, static_argnums=(2,))
                            if self.remat else BlockSpan)
                template = span_cls(self.heads, self.mlp_ratio,
                                    self.dropout, compute_dtype,
                                    span=self.scan_unit, name='hs',
                                    **common)
                length = self.layers // self.scan_unit
                body = lambda block, carry, _: (block(constrain(carry),
                                                      train), None)
            else:
                template = block_cls(self.heads, self.mlp_ratio,
                                     self.dropout, compute_dtype,
                                     name='hs', **common)
                length = self.layers
                body = lambda block, carry, _: (block(constrain(carry),
                                                      train), None)
            scan = nn.scan(
                body,
                variable_axes={'params': 0, 'cache': 0},
                split_rngs={'params': True, 'dropout': True},
                length=length)
            hidden, aux_stack = scan(template, hidden, None)
            if self.moe_experts:
                aux_losses.append(jnp.mean(aux_stack))
        else:
            for index in range(self.layers):
                is_moe = (self.moe_experts > 0
                          and index % self.moe_every == self.moe_every - 1)
                block = block_cls(self.heads, self.mlp_ratio, self.dropout,
                                  compute_dtype, attention=self.attention,
                                  mesh=self.mesh,
                                  attn_dropout=self.attn_dropout,
                                  decode=self.decode, max_seq=self.max_seq,
                                  per_row_decode=self.per_row_decode,
                                  decode_pages=self.decode_pages,
                                  moe_experts=self.moe_experts if is_moe else 0,
                                  moe_k=self.moe_k,
                                  moe_capacity_factor=self.moe_capacity_factor,
                                  moe_exchange=self.moe_exchange,
                                  moe_sparse_impl=self.moe_sparse_impl,
                                  tp_impl=self.tp_impl,
                                  tp_chunks=self.tp_chunks,
                                  schedule=self.schedule,
                                  name=f'h_{index}')
                result = block(hidden, train)
                if is_moe:
                    hidden, aux = result
                    aux_losses.append(aux)
                else:
                    hidden = result
        hidden = nn.LayerNorm(dtype=jnp.float32, name='ln_f')(hidden)
        # tied LM head: logits against the token embedding table. The matmul
        # runs bf16 x bf16 (MXU rate) accumulating into f32 — f32 operands
        # here would put ~30% of the model's FLOPs on the slow path — and
        # the f32 logits keep the softmax/loss numerically stable.
        table = token_embedding.embedding.astype(compute_dtype)
        # MoE aux (router balance) exists only for the training loss; in
        # decode mode every output branch is aux-free
        emit_aux = self.moe_experts and not self.decode
        if self.return_features:
            # fused-head path: the criterion owns the head matmul and never
            # materializes the [batch*seq, vocab] f32 logits tensor
            features = hidden.astype(compute_dtype)
            if emit_aux:
                aux = jnp.mean(jnp.stack(aux_losses)) if aux_losses else jnp.float32(0)
                return (features, table), aux
            return features, table
        logits = head_logits(hidden.astype(compute_dtype), table, tied=True)
        if emit_aux:
            # arity is fixed by configuration, not by which layers happened
            # to be MoE, so the WithAuxLoss pairing can't be broken by a
            # (layers, moe_every) combination that selects no layer. In
            # decode mode the aux (router-balance) term is meaningless —
            # logits only, so generation works on MoE models too. Caveat:
            # expert capacity derives from the call's token count, so a
            # decode step (batch tokens) effectively never drops, while a
            # training-shaped forward (batch*seq tokens) may — decode
            # matches it exactly only where the training forward drops
            # nothing (capacity-based MoE's standard decode asymmetry).
            aux = jnp.mean(jnp.stack(aux_losses)) if aux_losses else jnp.float32(0)
            return logits, aux
        return logits

    @staticmethod
    def partition_rules():
        """Megatron-style TP rules (combined with FSDP via policy flag).

        qkv/fc split columns on ``model``; out/proj split rows (their
        all-reduce rides ICI); embeddings split the vocab/position table.
        The ``hs/`` rules cover the ``scan_layers`` stacked variant (same
        splits shifted one dim right past the leading layer axis).
        """
        from tpusystem.ops.moe import moe_partition_rules
        from tpusystem.parallel.mesh import EXPERT
        return (
            # `hs/.*` covers both the plain scanned stack (hs/attn/...)
            # and BlockSpan nesting (hs/d_0/attn/..., hs/moe_block/attn/...)
            # — either way one leading layer/span dim shifts the spec right
            *tuple((rf'hs/.*{pattern}', P(None, *spec))
                   for pattern, spec in BLOCK_TP_RULES),
            # scanned MoE expert stacks: span dim first, then experts
            (r'hs/.*moe/w1$', P(None, EXPERT, None, 'model')),
            (r'hs/.*moe/b1$', P(None, EXPERT, 'model')),
            (r'hs/.*moe/w2$', P(None, EXPERT, 'model', None)),
            (r'hs/.*moe/b2$', P(None, EXPERT, None)),
            (r'hs/.*moe/router$', P()),
            *BLOCK_TP_RULES,
            (r'wte/embedding$', P('model', None)),
            (r'wpe/embedding$', P(None, 'model')),
        ) + moe_partition_rules()


register(GPT2, excluded_kwargs={'mesh'})


class GPT2Pipelined:
    """GPT-2 with its block stack pipelined over the ``stage`` mesh axis.

    Blocks are initialized *stacked* (leading ``layers`` dimension via
    ``jax.vmap`` of ``Block.init``) and executed through
    :func:`tpusystem.parallel.pipeline.pipeline_apply`: each stage owns
    ``layers/stages`` layers, microbatch activations ride the ICI ring.
    Embeddings, final layernorm, and the tied LM head run replicated over
    ``stage`` (they are a tiny fraction of the FLOPs).

    Implements the same ``init``/``apply``/``__call__`` surface the step
    builders expect from a flax module, so ``init_state``/``flax_apply``
    work unchanged. Dropout is 0 inside the pipe (pretraining-scale
    convention); the reference never pipelines at all (SURVEY.md §2.4).

    ``schedule=OverlapSchedule(pp='overlap', ...)`` skews the GPipe loop
    so every stage-to-stage ``ppermute`` issues under a microbatch's
    compute (see :func:`tpusystem.parallel.pipeline.pipeline_apply`);
    ``moe_experts > 0`` makes every ``moe_every``-th block an MoE FFN
    (the stacked unit becomes a :class:`BlockSpan`, router aux losses
    ride the pipeline's aux channel, and ``apply`` returns
    ``(logits, aux)`` for ``WithAuxLoss`` — the GPipe path only; the
    1F1B builder rejects MoE spans).
    """

    def __init__(self, vocab_size: int = 50257, layers: int = 12,
                 dim: int = 768, heads: int = 12, max_seq: int = 1024,
                 mlp_ratio: int = 4, dtype: str = 'bfloat16',
                 microbatches: int = 4, remat: bool = True, mesh=None,
                 return_features: bool = False, interleave: int = 1,
                 schedule=None, moe_experts: int = 0, moe_every: int = 2,
                 moe_k: int = 2, moe_capacity_factor: float = 1.25):
        if mesh is None:
            raise ValueError('GPT2Pipelined needs a mesh with a stage axis')
        if layers % max(interleave, 1):
            raise ValueError(f'{layers} layers not divisible by '
                             f'interleave={interleave}')
        self.vocab_size, self.layers, self.dim = vocab_size, layers, dim
        self.heads, self.max_seq, self.mlp_ratio = heads, max_seq, mlp_ratio
        self.dtype = dtype
        self.microbatches, self.remat, self.mesh = microbatches, remat, mesh
        self.return_features = return_features
        # interleave > 1: the stacked params are stored chunk-major
        # ([interleave, layers/interleave, ...], a plain reshape of the
        # layer-major stack) so the interleaved 1F1B schedule's
        # P(None, stage) sharding places each device's v non-contiguous
        # chunks without per-step resharding
        self.interleave = interleave
        # schedule: parallel.OverlapSchedule — the pp= arm drives the
        # GPipe loop's skewed overlap ticks (pipeline_apply); tp=/fsdp=
        # stay on GSPMD inside stage bodies (the stage shard_map is
        # already the manual region, so the blocks see mesh=None and the
        # partial-manual model axis), and moe= reaches the blocks' MoEMLP
        # (single-shard inside the pipe — the exchange arms bite on the
        # non-pipelined expert meshes). Purely an implementation
        # schedule: param trees and losses are bitwise knob-invariant.
        self.schedule = schedule
        # moe_experts > 0: every `moe_every`-th block is an expert-
        # parallel MoEMLP. The stacked unit becomes a BlockSpan of
        # `moe_every` blocks (the homogeneous span nn.scan/vmap needs),
        # so the stage axis shards layers/moe_every spans; the router aux
        # losses ride pipeline_apply's aux channel (mean over every
        # (span, microbatch)) and the model returns (logits, aux) for
        # WithAuxLoss, exactly like the non-pipelined family.
        self.moe_experts = moe_experts
        self.moe_every = moe_every
        if moe_experts:
            if interleave > 1:
                raise ValueError('moe_experts with interleave > 1 is not '
                                 'supported (the aux channel rides the '
                                 'plain GPipe schedule)')
            if layers % moe_every:
                raise ValueError(f'{layers} layers not divisible by '
                                 f'moe_every ({moe_every})')
            self.block = BlockSpan(heads, mlp_ratio, 0.0, jnp.dtype(dtype),
                                   span=moe_every, moe_experts=moe_experts,
                                   moe_every=moe_every, moe_k=moe_k,
                                   moe_capacity_factor=moe_capacity_factor,
                                   schedule=schedule)
            self.stacked_units = layers // moe_every
        else:
            self.block = Block(heads, mlp_ratio, 0.0, jnp.dtype(dtype),
                               schedule=schedule)
            self.stacked_units = layers
        self.stacked_key = 'h'   # params key of the stage-sharded layer stack

    def __call__(self, tokens, train: bool = False):
        raise TypeError('bind parameters via .apply(), like a flax module')

    def init(self, rng, tokens, train: bool = False):
        units = self.stacked_units
        keys = jax.random.split(rng, units + 2)
        sample = jnp.zeros((1, 8, self.dim), jnp.dtype(self.dtype))
        stacked = jax.vmap(lambda key: self.block.init(key, sample)['params'])(
            keys[:units])
        if self.interleave > 1:
            stacked = jax.tree.map(
                lambda leaf: leaf.reshape(
                    (self.interleave, self.layers // self.interleave)
                    + leaf.shape[1:]),
                stacked)
        scale = 0.02
        wte = scale * jax.random.normal(keys[-2], (self.vocab_size, self.dim))
        wpe = scale * jax.random.normal(keys[-1], (self.max_seq, self.dim))
        return {'params': {
            'wte': {'embedding': wte}, 'wpe': {'embedding': wpe},
            'h': stacked,
            'ln_f': {'scale': jnp.ones(self.dim), 'bias': jnp.zeros(self.dim)},
        }}

    def _embed(self, params, tokens):
        length = tokens.shape[-1]
        assert length <= self.max_seq, (length, self.max_seq)
        embedding = params['wte']['embedding']
        hidden = embedding[tokens] + params['wpe']['embedding'][:length]
        return hidden.astype(jnp.dtype(self.dtype))

    def _head(self, params, hidden):
        # same ln_f the non-pipelined family uses, applied as a standalone
        # module so the two variants cannot drift numerically
        hidden = nn.LayerNorm(dtype=jnp.float32).apply(
            {'params': params['ln_f']}, hidden.astype(jnp.float32))
        table = params['wte']['embedding'].astype(jnp.dtype(self.dtype))
        if self.return_features:
            # fused-loss path (train.ChunkedNextTokenLoss): the criterion
            # owns the head matmul, logits are never materialized
            return hidden.astype(jnp.dtype(self.dtype)), table
        return head_logits(hidden, table, tied=True)

    def _block_fn(self):
        def block_fn(layer_params, activations):
            return self.block.apply({'params': layer_params}, activations)
        return block_fn

    def _flat_stack(self, stacked):
        """Layer-major view of the stacked block params (undoes the
        chunk-major interleave storage; identity when interleave == 1)."""
        if self.interleave <= 1:
            return stacked
        return jax.tree.map(
            lambda leaf: leaf.reshape((self.layers,) + leaf.shape[2:]),
            stacked)

    def apply(self, variables, tokens, rngs=None, train: bool = False):
        from tpusystem.parallel.pipeline import pipeline_apply
        params = variables['params']
        hidden = self._embed(params, tokens)
        # chunk-major stack passes straight through: pipeline_apply's
        # interleaved forward schedule shares pipeline_train's layout, so
        # the GPipe path gets the same (S-1)/v fill/drain bubble shrink.
        # schedule.pp='overlap' swaps in the skewed tick (sends under
        # compute); with MoE spans the router aux rides the aux channel.
        hidden = pipeline_apply(self._block_fn(), params['h'],
                                hidden, self.mesh,
                                microbatches=self.microbatches,
                                remat=self.remat,
                                interleave=self.interleave,
                                schedule=self.schedule,
                                has_aux=bool(self.moe_experts))
        if self.moe_experts:
            hidden, aux = hidden
            return self._head(params, hidden), aux
        return self._head(params, hidden)

    def sequential_apply(self, variables, tokens):
        """Reference forward without the pipeline (correctness harness).

        With MoE spans the aux is the mean over span units computed on
        the FULL batch — the pipelined aux averages per-microbatch span
        means instead (the balance loss is nonlinear in its token
        statistics, and expert capacity derives from the call's token
        count), so with drops or across that nonlinearity the two agree
        only approximately; schedule-on vs schedule-off pipelined runs
        agree bitwise."""
        params = variables['params']
        hidden = self._embed(params, tokens)
        block_fn = self._block_fn()

        if self.moe_experts:
            def moe_layer(carry, layer_params):
                x, aux = carry
                x, unit_aux = block_fn(layer_params, x)
                return (x, aux + unit_aux.astype(jnp.float32)), None
            (hidden, aux_sum), _ = jax.lax.scan(
                moe_layer, (hidden, jnp.float32(0)),
                self._flat_stack(params['h']))
            return (self._head(params, hidden),
                    aux_sum / self.stacked_units)

        def layer(carry, layer_params):
            return block_fn(layer_params, carry), None

        hidden, _ = jax.lax.scan(layer, hidden, self._flat_stack(params['h']))
        return self._head(params, hidden)

    @staticmethod
    def block_partition_rules():
        """Megatron TP rules for the *within-stack* block leaf paths
        (``attn/qkv/kernel`` etc. — no leading layer dim): qkv/fc split
        columns on ``model``, out/proj split rows — the same
        ``BLOCK_TP_RULES`` the non-pipelined family uses. Feed these to
        ``PipelineParallel(stacked_rules=...)``, which shifts them right
        past the stage dim(s); the pipeline's partial-manual ``shard_map``
        then runs each stage's matmuls model-partitioned (PP x TP)."""
        return BLOCK_TP_RULES

    def partition_rules(self):
        """Stage sharding for the stacked blocks, composed with the
        Megatron within-stage TP splits (inert on meshes with model=1, or
        wherever a dim doesn't divide — the policy drops non-dividing
        axes); embeddings/ln replicated (combine with ``fsdp=True`` on the
        policy to scatter them). With interleave, the chunk-major stack
        shards its *second* dim (the within-chunk layer index groups
        ``stages`` contiguous layers per device — see ``pipeline_train``'s
        layout contract)."""
        from tpusystem.parallel.pipeline import compose_stacked_rules
        return compose_stacked_rules(r'(^|/)h/', self.block_partition_rules(),
                                     self.interleave)


register(GPT2Pipelined, excluded_kwargs={'mesh'})


def gpt2_small(**overrides) -> GPT2:
    return GPT2(**overrides)


def gpt2_tiny(**overrides) -> GPT2:
    """Test/dry-run scale: compiles in seconds on CPU."""
    config = dict(vocab_size=256, layers=2, dim=64, heads=4, max_seq=128,
                  dropout=0.0)
    config.update(overrides)
    return GPT2(**config)
