"""Llama-3 language model family (BASELINE.md workload ladder #5:
"Llama-3 8B aggregate, sharded Service.handler" — BASELINE.json configs[4]).

The reference framework ships only an MNIST MLP (SURVEY.md §2.2,
``examples/tinysys/modules/mlp.py``); the 8B-scale decoder family is part of
the capability level this framework must supply (SURVEY.md §6).

TPU-first choices mirror :mod:`tpusystem.models.gpt2`: bfloat16 activations
with float32 RMSNorm/softmax/loss, float32 master weights cast per-use, and
Megatron-style partition rules shipped with the model so the
``TensorParallel``/``FullyShardedDataParallel`` policies shard it without
per-experiment configuration. Llama-specific pieces: rotary position
embeddings (no learned position table), grouped-query attention (8 KV heads
at 8B — KV broadcast happens inside
:func:`tpusystem.ops.attention.dot_product_attention`), SwiGLU FFN, RMSNorm,
no biases anywhere, untied LM head.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from tpusystem.ops.attention import attend
from tpusystem.ops.precision import head_logits
from tpusystem.registry import register


def rotary_embedding(positions: jax.Array, head_dim: int,
                     theta: float = 500_000.0) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) tables of shape [*positions.shape, head_dim/2], float32.

    ``positions`` is ``[len]`` for training/prefill or ``[batch, len]``
    when rows decode at independent cursors (speculative decoding)."""
    frequencies = 1.0 / theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    angles = positions.astype(jnp.float32)[..., None] * frequencies
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(tensor: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate [batch, len, heads, head_dim] pairs (x_even, x_odd) by the
    position angle. Runs in float32, returns in the input dtype. Tables
    are [len, head_dim/2] (shared across the batch) or
    [batch, len, head_dim/2] (per-row positions)."""
    dtype = tensor.dtype
    paired = tensor.astype(jnp.float32).reshape(*tensor.shape[:-1], -1, 2)
    even, odd = paired[..., 0], paired[..., 1]
    if cos.ndim == 2:
        cos, sin = cos[None], sin[None]
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    rotated = jnp.stack(
        (even * cos - odd * sin, even * sin + odd * cos), axis=-1)
    return rotated.reshape(tensor.shape).astype(dtype)


class _HeadKernel(nn.Module):
    """Bare ``kernel`` parameter under the module's scope — what ``nn.Dense``
    would create (same path, same initializer), but retrievable so the
    fused-loss path can pass the table to the criterion."""

    dim: int
    vocab: int

    @nn.compact
    def __call__(self):
        return self.param('kernel', nn.initializers.lecun_normal(),
                          (self.dim, self.vocab))


class RMSNorm(nn.Module):
    """Root-mean-square normalization in float32 (bf16-safe)."""

    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, hidden):
        dtype = hidden.dtype
        hidden = hidden.astype(jnp.float32)
        scale = self.param('scale', nn.initializers.ones, (hidden.shape[-1],))
        variance = jnp.mean(jnp.square(hidden), axis=-1, keepdims=True)
        return (hidden * jax.lax.rsqrt(variance + self.epsilon)
                * scale).astype(dtype)


class LlamaAttention(nn.Module):
    """Causal grouped-query attention with rotary embeddings.

    ``kernel='xla'`` (default) keeps the separate KV-head count through
    :func:`dot_product_attention` (which broadcasts KV over query-head
    groups); 'flash'/'ring'/'ulysses' kernels take full-head tensors, so KV
    is repeated up front for them.
    """

    heads: int
    kv_heads: int
    dtype: jnp.dtype
    rope_theta: float = 500_000.0
    kernel: str = 'xla'
    mesh: object = None
    decode: bool = False
    max_seq: int = 8192
    per_row_decode: bool = False  # per-row cache cursors (speculative decoding)
    decode_pages: tuple | None = None  # (num_blocks, block_size): paged
    # block-pool KV cache with per-row block tables (the serving engine's
    # layout — ops.attention.paged_attention)

    @nn.compact
    def __call__(self, hidden, train: bool = False):
        dim = hidden.shape[-1]
        head_dim = dim // self.heads
        dense = lambda features, name: nn.Dense(
            features, use_bias=False, dtype=self.dtype, name=name)
        query = dense(self.heads * head_dim, 'q')(hidden)
        key = dense(self.kv_heads * head_dim, 'k')(hidden)
        value = dense(self.kv_heads * head_dim, 'v')(hidden)
        batch, length = hidden.shape[:2]
        query = query.reshape(batch, length, self.heads, head_dim)
        key = key.reshape(batch, length, self.kv_heads, head_dim)
        value = value.reshape(batch, length, self.kv_heads, head_dim)

        if self.decode:
            # rotary runs at absolute positions: peek at the per-row cache
            # cursor ([batch] — declared and advanced by cached_attention;
            # absent on the prefill call, where every offset is 0)
            cursor = (self.get_variable('cache', 'index')
                      if self.has_variable('cache', 'index')
                      else jnp.zeros((batch,), jnp.int32))
            positions = cursor[:, None] + jnp.arange(length)
        else:
            positions = jnp.arange(length)
        cos, sin = rotary_embedding(positions, head_dim, self.rope_theta)
        query = apply_rotary(query, cos, sin)
        key = apply_rotary(key, cos, sin)

        if self.decode:
            from tpusystem.ops.attention import cached_attention
            context = cached_attention(self, query, key, value, self.max_seq,
                                       per_row=self.per_row_decode,
                                       pages=self.decode_pages)
        else:
            context = attend(query, key, value, kernel=self.kernel,
                             mesh=self.mesh, causal=True)
        context = context.reshape(batch, length, dim)
        return dense(dim, 'out')(context)


class LlamaBlock(nn.Module):
    """Pre-RMSNorm transformer block with a SwiGLU FFN."""

    heads: int
    kv_heads: int
    ffn_dim: int
    dtype: jnp.dtype
    rope_theta: float = 500_000.0
    attention: str = 'xla'
    mesh: object = None
    decode: bool = False
    max_seq: int = 8192
    per_row_decode: bool = False
    decode_pages: tuple | None = None  # paged KV pool (see LlamaAttention)
    tp_impl: str = 'gspmd'  # SwiGLU TP collectives: 'gspmd' | 'overlap'
    tp_chunks: int = 1
    schedule: object = None  # parallel.OverlapSchedule composing TP rings
    # with FSDP prefetch (see gpt2.Block.schedule); None -> legacy knobs

    @nn.compact
    def __call__(self, hidden, train: bool = False):
        from tpusystem.parallel.schedule import (resolve_schedule,
                                                 schedule_applicable,
                                                 scheduled_swiglu)
        schedule = resolve_schedule(self.schedule, self.tp_impl,
                                    self.tp_chunks)
        dim = hidden.shape[-1]
        normed = RMSNorm(name='attn_norm')(hidden)
        hidden = hidden + LlamaAttention(
            self.heads, self.kv_heads, self.dtype, self.rope_theta,
            kernel=self.attention, mesh=self.mesh, decode=self.decode,
            max_seq=self.max_seq, per_row_decode=self.per_row_decode,
            decode_pages=self.decode_pages,
            name='attn')(normed, train)
        normed = RMSNorm(name='ffn_norm')(hidden)
        from tpusystem.parallel.overlap import DenseParams
        # init ALWAYS takes the nn.Dense path below (see gpt2.Block: the
        # legacy threefry's draws depend on the sharding the manual
        # region imposes inside a scanned init program — nn.Dense is the
        # single init authority, the schedule a pure apply-time knob)
        if (not self.is_initializing()
                and schedule_applicable(schedule, self.mesh, normed.shape,
                                        self.ffn_dim)):
            # the scheduled SwiGLU (parallel/schedule.py): one ring
            # all-gathers the sequence rows into the fused gate|up matmul
            # and the down matmul reduce-scatters them back (decomposed
            # when schedule.tp='overlap'), and with schedule.fsdp=
            # 'prefetch' the three kernels enter still FSDP-sharded —
            # gathered at FFN entry so the transfers hide under the
            # upstream matmuls, grads reduce-scattered off the backward
            # critical path. Same param paths as nn.Dense, so the knob
            # never changes a checkpoint; non-tiling shapes fall through
            # to the GSPMD path below.
            w_gate, _ = DenseParams(self.ffn_dim, use_bias=False,
                                    name='gate')(dim)
            w_up, _ = DenseParams(self.ffn_dim, use_bias=False,
                                  name='up')(dim)
            w_down, _ = DenseParams(dim, use_bias=False,
                                    name='down')(self.ffn_dim)
            return hidden + scheduled_swiglu(
                normed, w_gate.astype(self.dtype), w_up.astype(self.dtype),
                w_down.astype(self.dtype), self.mesh, schedule=schedule)
        dense = lambda features, name: nn.Dense(
            features, use_bias=False, dtype=self.dtype, name=name)
        gated = nn.silu(dense(self.ffn_dim, 'gate')(normed)) \
            * dense(self.ffn_dim, 'up')(normed)
        return hidden + dense(dim, 'down')(gated)


class LlamaBlockSpan(nn.Module):
    """``span`` consecutive LlamaBlocks — the ``scan_unit``
    grouping that keeps deep scanned stacks under the TPU compiler's
    nested-loop cliff (see :class:`tpusystem.models.gpt2.BlockSpan`): an
    outer steps-loop over a layer-scan longer than ~8 iterations sends
    the AOT compile from seconds to >10 minutes, so the 32-layer 8B scans
    8 spans of 4."""

    heads: int
    kv_heads: int
    ffn_dim: int
    dtype: jnp.dtype
    rope_theta: float = 500_000.0
    span: int = 4
    attention: str = 'xla'
    mesh: object = None
    decode: bool = False
    max_seq: int = 8192
    per_row_decode: bool = False
    decode_pages: tuple | None = None  # paged KV pool (see LlamaAttention)
    tp_impl: str = 'gspmd'
    tp_chunks: int = 1
    schedule: object = None  # OverlapSchedule (see LlamaBlock.schedule)

    @nn.compact
    def __call__(self, hidden, train: bool = False):
        for index in range(self.span):
            hidden = LlamaBlock(self.heads, self.kv_heads, self.ffn_dim,
                                self.dtype, self.rope_theta,
                                attention=self.attention, mesh=self.mesh,
                                decode=self.decode, max_seq=self.max_seq,
                                per_row_decode=self.per_row_decode,
                                decode_pages=self.decode_pages,
                                tp_impl=self.tp_impl,
                                tp_chunks=self.tp_chunks,
                                schedule=self.schedule,
                                name=f'd_{index}')(hidden, train)
        return hidden


class Llama(nn.Module):
    """Llama-3-style decoder-only transformer.

    Defaults are the 8B shape (vocab 128256, 32 x 4096, 32 heads / 8 KV
    heads, SwiGLU 14336, RoPE theta 5e5). Use :func:`llama3_8b` /
    :func:`llama_tiny` presets.
    """

    vocab_size: int = 128_256
    layers: int = 32
    dim: int = 4096
    heads: int = 32
    kv_heads: int = 8
    ffn_dim: int = 14_336
    max_seq: int = 8192
    rope_theta: float = 500_000.0
    dtype: str = 'bfloat16'
    attention: str = 'xla'
    mesh: object = None
    remat: bool = False
    scan_layers: bool = False  # one lax.scan over stacked block params
    # instead of 32 unrolled copies: XLA compiles ONE block body, so 8B
    # compile time stops scaling with depth; params live under 'blocks'
    # with a leading layer dim (see partition_rules)
    scan_unit: int = 1  # layers per scan step (see gpt2.GPT2.scan_unit):
    # group k blocks per LlamaBlockSpan so the scan length is layers/k —
    # keep layers/k <= 8 when the step runs inside a compiled steps-loop
    # (the TPU backend's nested-loop cliff)
    return_features: bool = False  # return (features, head kernel) for a
    # fused chunked LM loss (train.ChunkedNextTokenLoss); at 128k vocab the
    # full f32 logits tensor is the dominant memory term
    decode: bool = False  # KV-cache autoregressive decoding (see
    # tpusystem.train.generate; apply with mutable=['cache'])
    per_row_decode: bool = False  # per-row cache cursors for speculative
    # decoding (scatter writes); False = ordinary decode, shared-cursor
    # dynamic_update_slice cache writes
    decode_pages: tuple | None = None  # (num_blocks, block_size): paged
    # block-pool KV cache with per-row block tables — the serving
    # engine's layout (tpusystem.serve; ops.attention.paged_attention)
    tp_impl: str = 'gspmd'  # SwiGLU TP collectives: 'gspmd' (monolithic
    # partitioner-inserted all-gather/reduce-scatter) | 'overlap'
    # (decomposed latency-hiding ring matmuls — parallel/overlap.py;
    # needs a mesh with model > 1, falls back per-shape otherwise)
    tp_chunks: int = 1  # ppermute payload split per overlap ring hop
    schedule: object = None  # parallel.OverlapSchedule: ONE knob composing
    # the TP rings with FSDP param-prefetch/grad-scatter hiding (see
    # gpt2.GPT2.schedule); None keeps the legacy tp_impl=/tp_chunks=
    # behavior. The pp=/moe= arms ride the same object but are inert in
    # this family (no pipelined/MoE Llama variant yet — pass the one
    # schedule everywhere and each model consumes the arms it has).
    # Param trees and checkpoints are bitwise knob-invariant

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        compute_dtype = jnp.dtype(self.dtype)
        assert tokens.shape[-1] <= self.max_seq, (
            f'sequence length {tokens.shape[-1]} exceeds max_seq={self.max_seq}')
        hidden = nn.Embed(self.vocab_size, self.dim, dtype=jnp.float32,
                          name='embed')(tokens)
        hidden = hidden.astype(compute_dtype)
        block_cls = (nn.remat(LlamaBlock, static_argnums=(2,))
                     if self.remat else LlamaBlock)
        if self.scan_layers:
            # one compiled block body + stacked params: compile time is
            # O(1) in depth. Decode scans too: the per-layer KV caches ride
            # the scan via variable_axes={'cache': 0} (each layer slice
            # owns its cache at a leading layer dim).
            if self.scan_unit > 1:
                if self.layers % self.scan_unit:
                    raise ValueError(
                        f'scan_unit={self.scan_unit} must divide layers '
                        f'({self.layers})')
                span_cls = (nn.remat(LlamaBlockSpan, static_argnums=(2,))
                            if self.remat else LlamaBlockSpan)
                template = span_cls(self.heads, self.kv_heads,
                                    self.ffn_dim, compute_dtype,
                                    self.rope_theta, span=self.scan_unit,
                                    attention=self.attention,
                                    mesh=self.mesh, decode=self.decode,
                                    max_seq=self.max_seq,
                                    per_row_decode=self.per_row_decode,
                                    decode_pages=self.decode_pages,
                                    tp_impl=self.tp_impl,
                                    tp_chunks=self.tp_chunks,
                                    schedule=self.schedule,
                                    name='blocks')
                length = self.layers // self.scan_unit
            else:
                template = block_cls(self.heads, self.kv_heads,
                                     self.ffn_dim, compute_dtype,
                                     self.rope_theta,
                                     attention=self.attention,
                                     mesh=self.mesh, decode=self.decode,
                                     max_seq=self.max_seq,
                                     per_row_decode=self.per_row_decode,
                                     decode_pages=self.decode_pages,
                                     tp_impl=self.tp_impl,
                                     tp_chunks=self.tp_chunks,
                                     schedule=self.schedule,
                                     name='blocks')
                length = self.layers
            from tpusystem.parallel.mesh import scan_carry_constraint
            constrain = scan_carry_constraint(self.mesh)
            scan = nn.scan(
                lambda block, carry, _: (block(constrain(carry), train),
                                         None),
                variable_axes={'params': 0, 'cache': 0},
                split_rngs={'params': True},
                length=length)
            hidden, _ = scan(template, hidden, None)
        else:
            for index in range(self.layers):
                hidden = block_cls(self.heads, self.kv_heads, self.ffn_dim,
                                   compute_dtype, self.rope_theta,
                                   attention=self.attention, mesh=self.mesh,
                                   decode=self.decode, max_seq=self.max_seq,
                                   per_row_decode=self.per_row_decode,
                                   decode_pages=self.decode_pages,
                                   tp_impl=self.tp_impl,
                                   tp_chunks=self.tp_chunks,
                                   schedule=self.schedule,
                                   name=f'layer_{index}')(hidden, train)
        hidden = RMSNorm(name='final_norm')(hidden)
        # untied head (Llama-3 convention). bf16 x bf16 operands at MXU
        # rate, f32 accumulation out for a stable softmax/loss. The kernel
        # lives in a param holder (same 'lm_head/kernel' path a Dense would
        # use) so the fused-loss path can hand it to the criterion.
        kernel = _HeadKernel(self.dim, self.vocab_size, name='lm_head')()
        table = kernel.astype(compute_dtype)
        if self.return_features:
            return hidden, table
        return head_logits(hidden, table, tied=False)

    @staticmethod
    def partition_rules():
        """Megatron-style TP rules: q/k/v/gate/up split columns on ``model``;
        out/down split rows (their all-reduce rides ICI); embedding and head
        split the vocab dimension. The ``blocks/`` rules cover the
        ``scan_layers`` stacked variant (same splits shifted one dim right
        past the leading layer axis)."""
        return (
            # `blocks/.*` covers both the plain scanned stack and the
            # LlamaBlockSpan nesting (blocks/d_0/attn/...) — either way
            # one leading layer/span dim shifts the spec right
            (r'blocks/.*attn/(q|k|v)/kernel$', P(None, None, 'model')),
            (r'blocks/.*attn/out/kernel$', P(None, 'model', None)),
            (r'blocks/.*(gate|up)/kernel$', P(None, None, 'model')),
            (r'blocks/.*down/kernel$', P(None, 'model', None)),
            (r'attn/(q|k|v)/kernel$', P(None, 'model')),
            (r'attn/out/kernel$', P('model', None)),
            (r'(gate|up)/kernel$', P(None, 'model')),
            (r'down/kernel$', P('model', None)),
            (r'embed/embedding$', P('model', None)),
            (r'lm_head/kernel$', P(None, 'model')),
        )


register(Llama, excluded_kwargs={'mesh'})


def llama3_8b(**overrides) -> Llama:
    """The 8B preset (== class defaults), gradient checkpointing on."""
    config = dict(remat=True)
    config.update(overrides)
    return Llama(**config)


def llama_tiny(**overrides) -> Llama:
    """Test/dry-run scale: compiles in seconds on CPU."""
    config = dict(vocab_size=256, layers=2, dim=64, heads=4, kv_heads=2,
                  ffn_dim=128, max_seq=128)
    config.update(overrides)
    return Llama(**config)
