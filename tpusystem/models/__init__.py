from tpusystem.models.mlp import MLP

__all__ = ['MLP']
