from tpusystem.models.mlp import MLP
from tpusystem.models.gpt2 import GPT2, GPT2Pipelined, gpt2_small, gpt2_tiny
from tpusystem.models.llama import Llama, llama3_8b, llama_tiny
from tpusystem.models.resnet import ResNet, resnet50, resnet_tiny
from tpusystem.models.dlrm import (DLRM, TwoTower, dlrm_tiny, two_tower_tiny)

__all__ = ['MLP', 'GPT2', 'GPT2Pipelined', 'gpt2_small', 'gpt2_tiny',
           'Llama', 'llama3_8b', 'llama_tiny',
           'ResNet', 'resnet50', 'resnet_tiny',
           'DLRM', 'TwoTower', 'dlrm_tiny', 'two_tower_tiny']
