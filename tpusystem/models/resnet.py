"""ResNet family (BASELINE.md workload ladder #3: "ResNet-50/ImageNet
aggregate via compiler→XLA" — BASELINE.json configs[2]).

The reference ships only an MNIST MLP (``examples/tinysys/modules/mlp.py``,
SURVEY.md §2.2); the CNN family is part of the capability ladder this
framework supplies.

TPU-first choices: NHWC layout (XLA:TPU's native conv layout — the MXU
consumes [spatial, channel] tiles directly), bfloat16 conv compute with
float32 normalization, and **GroupNorm instead of BatchNorm**: running
batch statistics are mutable state that would break the pure donated-step
model (``build_train_step`` donates the whole ``TrainState``) and require
cross-replica statistic sync under data parallelism; GroupNorm is the
standard stateless substitute at large batch scale and keeps the step
function identical on 1 chip and on a pod. Parallelism for CNNs is
data/FSDP (weight matrices are small relative to activations; tensor
parallelism buys nothing here), so :meth:`ResNet.partition_rules` only
splits the classifier head.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from tpusystem.registry import register


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with a projection shortcut on
    stride/width changes (the ResNet-50 block)."""

    features: int            # bottleneck width; block output is 4x this
    stride: int
    groups: int              # GroupNorm groups
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, hidden):
        conv = lambda features, size, stride, name: nn.Conv(
            features, (size, size), strides=(stride, stride), use_bias=False,
            dtype=self.dtype, name=name)
        norm = lambda name: nn.GroupNorm(
            num_groups=self.groups, dtype=jnp.float32, name=name)
        out_features = 4 * self.features

        shortcut = hidden
        if self.stride != 1 or hidden.shape[-1] != out_features:
            shortcut = conv(out_features, 1, self.stride, 'proj')(hidden)
            shortcut = norm('proj_norm')(shortcut)

        hidden = nn.relu(norm('norm1')(conv(self.features, 1, 1, 'conv1')(hidden)))
        hidden = nn.relu(norm('norm2')(conv(self.features, 3, self.stride, 'conv2')(hidden)))
        hidden = norm('norm3')(conv(out_features, 1, 1, 'conv3')(hidden))
        return nn.relu(hidden + shortcut)


class ResNet(nn.Module):
    """Bottleneck ResNet over NHWC images. Defaults are ResNet-50
    (stages 3-4-6-3, widths 64-128-256-512, 1000 classes)."""

    classes: int = 1000
    stages: tuple = (3, 4, 6, 3)
    width: int = 64
    groups: int = 32
    dtype: str = 'bfloat16'
    stem_stride: int = 2     # 1 for small (CIFAR-style) inputs
    stem_pool: bool = True   # max-pool after the stem (ImageNet-style)

    @nn.compact
    def __call__(self, images):
        compute_dtype = jnp.dtype(self.dtype)
        hidden = images.astype(compute_dtype)
        size = 7 if self.stem_stride == 2 else 3
        hidden = nn.Conv(self.width, (size, size),
                         strides=(self.stem_stride, self.stem_stride),
                         use_bias=False, dtype=compute_dtype, name='stem')(hidden)
        hidden = nn.relu(nn.GroupNorm(num_groups=self.groups,
                                      dtype=jnp.float32, name='stem_norm')(hidden))
        if self.stem_pool:
            hidden = nn.max_pool(hidden, (3, 3), strides=(2, 2), padding='SAME')
        for stage, blocks in enumerate(self.stages):
            for block in range(blocks):
                stride = 2 if stage > 0 and block == 0 else 1
                hidden = Bottleneck(self.width * 2 ** stage, stride,
                                    self.groups, compute_dtype,
                                    name=f's{stage}_b{block}')(hidden)
        pooled = jnp.mean(hidden, axis=(1, 2))  # global average pool
        # f32 head for a numerically stable softmax/loss
        return nn.Dense(self.classes, dtype=jnp.float32,
                        name='head')(pooled.astype(jnp.float32))

    @staticmethod
    def partition_rules():
        """Classifier head splits classes on ``model``; conv weights are
        left to the FSDP/data axes (TP buys nothing for CNN kernels)."""
        return ((r'head/kernel$', P(None, 'model')),)


register(ResNet)


def resnet50(**overrides) -> ResNet:
    return ResNet(**overrides)


def resnet_tiny(**overrides) -> ResNet:
    """Test scale: 8-group norm, 2 stages, CIFAR-style stem."""
    config = dict(classes=10, stages=(1, 1), width=16, groups=8,
                  stem_stride=1, stem_pool=False, dtype='float32')
    config.update(overrides)
    return ResNet(**config)
