from tpusystem.depends import Depends
from tpusystem.services.service import Service
from tpusystem.services.prodcon import Consumer, Producer, event
from tpusystem.services.pubsub import Publisher, Subscriber

__all__ = ['Service', 'Consumer', 'Producer', 'event', 'Publisher',
           'Subscriber', 'Depends']
