"""Named command handlers (the service layer entry point).

A ``Service`` registers stateless domain operations under names generated
from the handler function's name (default: snake_case -> kebab-case) and
executes them by name — CQS-style dispatch usable from code, a CLI, or a
REST surface. Handlers are DI-injected so runtime facts (the device mesh,
data loaders, metric stores) bind late and swap cleanly in tests.

Reference parity: ``torchsystem/services/service.py:70-153`` — kebab name
generation, handlers remain directly callable after registration, ``handle``
raises ``KeyError`` for unknown actions.
"""

from __future__ import annotations

from collections.abc import Callable
from re import sub
from typing import Any

from tpusystem.depends import Depends as Depends
from tpusystem.depends import Provider, inject


class Service:
    """Registry of injected command handlers addressable by generated name."""

    def __init__(
        self,
        name: str | None = None,
        *,
        provider: Provider | None = None,
        generator: Callable[[str], str] = lambda name: sub(r'_', '-', name),
    ) -> None:
        self.name = name
        self.handlers: dict[str, Callable[..., Any]] = {}
        self.generator = generator
        self.provider = provider or Provider()

    @property
    def dependency_overrides(self) -> dict:
        """Late-binding override table (see :class:`tpusystem.depends.Provider`)."""
        return self.provider.dependency_overrides

    def handler(self, wrapped: Callable[..., Any]) -> Callable[..., Any]:
        """Register ``wrapped`` under ``generator(wrapped.__name__)``.

        The returned callable is the injected version and is also usable
        directly (``train(model, loader)`` keeps working).
        """
        injected = inject(self.provider)(wrapped)
        self.handlers[self.generator(wrapped.__name__)] = injected
        return injected

    def handle(self, action: str, *arguments: Any) -> Any:
        """Invoke the handler registered under ``action``.

        Raises:
            KeyError: when no handler exists for the action.
        """
        handler = self.handlers.get(action)
        if not handler:
            raise KeyError(f'Handler not found for action: {action}')
        return handler(*arguments)
