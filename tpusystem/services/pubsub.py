"""Topic-routed message bus: Publisher -> Subscribers.

Where the Consumer routes by event *type*, a ``Subscriber`` routes by
*topic* string — the natural shape for metric streams (``'loss'``,
``'accuracy'``) where many handlers observe the same scalar channel.
Handler exceptions propagate to the publisher, which is the designed
early-stopping signal path (reference parity
``torchsystem/services/pubsub.py:73-222``; exception propagation pinned by
``tests/test_pubsub.py:25-37``).

``receive`` is safely re-entrant: a handler may re-route a message to
another topic on the same subscriber.
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any

from tpusystem.depends import Depends as Depends
from tpusystem.depends import Provider, inject


class Subscriber:
    """Holds topic -> handler-list routing with DI-injected handlers."""

    def __init__(
        self,
        name: str | None = None,
        *,
        provider: Provider | None = None,
    ) -> None:
        self.name = name
        self.provider = provider or Provider()
        self.handlers: dict[str, list[Callable[..., None]]] = {}

    @property
    def dependency_overrides(self) -> dict:
        return self.provider.dependency_overrides

    def register(self, topic: str, wrapped: Callable[..., None]) -> None:
        """Attach an injected handler to a topic."""
        self.handlers.setdefault(topic, []).append(inject(self.provider)(wrapped))

    def subscribe(self, *topics: str) -> Callable[[Callable], Callable]:
        """Decorator registering a handler on one or more topics."""
        def decorator(wrapped: Callable[..., None]) -> Callable[..., None]:
            for topic in topics:
                self.register(topic, wrapped)
            return wrapped
        return decorator

    def receive(self, message: Any, topic: str) -> None:
        """Run every handler subscribed to ``topic`` with ``message``."""
        for handler in self.handlers.get(topic, []):
            handler(message)


class Publisher:
    """Delivers (message, topic) to every registered subscriber."""

    def __init__(self) -> None:
        self.subscribers: list[Subscriber] = []

    def register(self, *subscribers: Subscriber) -> None:
        self.subscribers.extend(subscribers)

    def publish(self, message: Any, topic: str) -> None:
        """Route to subscribers; handler exceptions propagate to the caller
        (early-stop signal path)."""
        for subscriber in self.subscribers:
            subscriber.receive(message, topic)
