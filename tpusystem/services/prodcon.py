"""Type-routed event bus: Producer -> Consumers.

Domain occurrences ("Trained", "Validated", "Iterated") are dataclass events
dispatched to every registered consumer; each consumer routes by the event's
*type name* through a configurable name generator (default PascalCase ->
kebab-lower). A handler annotated ``ModelTrained | ModelEvaluated`` is
registered for every member of the union — both ``typing.Union`` and PEP-604
forms (reference parity ``torchsystem/services/prodcon.py:77-241``).

This in-process bus is the degenerate single-host case of the control plane:
:class:`tpusystem.parallel.multihost.DistributedProducer` carries the same
API across TPU-VM workers over DCN, so training code is identical on one
chip and on a pod. Consumers must only ever touch *materialized* host values
— never device arrays that would force a sync inside the hot loop.
"""

from __future__ import annotations

import types
import typing
from collections.abc import Callable
from dataclasses import dataclass
from inspect import signature
from re import sub
from typing import Any

from tpusystem.depends import Depends as Depends
from tpusystem.depends import Provider, inject


def _pascal_to_kebab(name: str) -> str:
    return sub(r'(?<!^)(?=[A-Z])', '-', name).lower()


def _union_members(annotation: Any) -> tuple | None:
    """Members of a union annotation, or None when not a union.

    Handles ``typing.Union[A, B]``, PEP-604 ``A | B``, and parameterized
    generics (whose origin is registered instead).
    """
    if isinstance(annotation, types.UnionType):
        return typing.get_args(annotation)
    if typing.get_origin(annotation) is typing.Union:
        return typing.get_args(annotation)
    return None


class Consumer:
    """Routes events to handlers keyed by generated type name."""

    def __init__(
        self,
        name: str | None = None,
        *,
        provider: Provider | None = None,
        generator: Callable[[str], str] = _pascal_to_kebab,
    ) -> None:
        self.name = name
        self.handlers: dict[str, list[Callable[[Any], None]]] = {}
        self.types: dict[str, Any] = {}
        self.generator = generator
        self.provider = provider or Provider()

    @property
    def dependency_overrides(self) -> dict:
        return self.provider.dependency_overrides

    def register(self, annotation: Any, handler: Callable[..., None]) -> Callable[..., None]:
        """Register ``handler`` for ``annotation``; unions register every member."""
        members = _union_members(annotation)
        if members is not None:
            injected = handler
            for member in members:
                injected = self.register(member, handler)
            return injected
        origin = typing.get_origin(annotation)
        if origin is not None:
            return self.register(origin, handler)
        key = self.generator(annotation.__name__)
        self.types[key] = annotation
        injected = inject(self.provider)(handler)
        self.handlers.setdefault(key, []).append(injected)
        return injected

    def handler(self, wrapped: Callable[..., None]) -> Callable[..., None]:
        """Decorator: route by the **first parameter's annotation**."""
        parameters = signature(wrapped).parameters
        if not parameters:
            raise TypeError(
                f'consumer handler {wrapped.__name__!r} needs a first parameter '
                'annotated with the event type(s) it consumes')
        first = next(iter(parameters.values()))
        annotation = first.annotation
        if annotation is first.empty:
            raise TypeError(
                f'consumer handler {wrapped.__name__!r} first parameter must be '
                'annotated with the event type(s) it consumes')
        if isinstance(annotation, str):
            # PEP 563 (`from __future__ import annotations`) stringizes
            # annotations; resolve only the routing parameter so unrelated
            # unresolvable annotations (TYPE_CHECKING-only imports, locals)
            # don't break registration.
            function = getattr(wrapped, '__func__', wrapped)
            annotation = eval(annotation, getattr(function, '__globals__', {}))  # noqa: S307
        return self.register(annotation, wrapped)

    def consume(self, message: Any) -> None:
        """Invoke all handlers for the message's type; unknown types are ignored."""
        key = self.generator(message.__class__.__name__)
        for handler in self.handlers.get(key, []):
            handler(message)


class Producer:
    """Fans events out to every registered consumer, synchronously, in order.

    ``taps`` observe every dispatched message before routing — the hook used
    by :class:`tpusystem.observe.EventLedger` to hash-chain the event stream
    for cross-host divergence detection.
    """

    def __init__(self) -> None:
        self.consumers: list[Consumer] = []
        self.taps: list[Callable[[Any], None]] = []

    def register(self, *consumers: Consumer) -> None:
        self.consumers.extend(consumers)

    def dispatch(self, message: Any) -> None:
        for tap in self.taps:
            tap(message)
        for consumer in self.consumers:
            consumer.consume(message)


def event(cls: type) -> type:
    """Declare an event message (a plain dataclass)."""
    return dataclass(cls)
