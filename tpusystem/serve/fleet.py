"""Serving fleet failover: a health-checked router over N replicas.

PR 12 gave ONE replica a survival story — journaled requests, token-
prefix replay, a supervised relaunch (:mod:`tpusystem.serve.failover`).
This module is the tier above it: the thing that turns a surviving
*replica* into a surviving *service* (ROADMAP item 2, the vLLM/DistServe
router-over-replicas split). A :class:`Router` fronts N
:class:`~tpusystem.serve.ServingReplica`\\ s and owns four fleet-level
robustness moves:

* **Health-checked routing** — every replica carries a router-side
  verdict (:class:`ReplicaHandle`): healthy replicas take traffic by
  least load, a replica whose step or submit dies (the in-process
  signature of SIGKILL — :exc:`ReplicaDead` /
  :class:`~tpusystem.parallel.chaos.WorkerKilled` / ``OSError``) or
  whose heartbeat goes stale (externally-driven handles,
  :meth:`ReplicaHandle.beat`) is marked unhealthy, narrated as a
  ``ReplicaUnhealthy`` event, and **never routed to again** — the
  verdict is one-way; a replaced replica joins as a fresh handle
  (:meth:`Router.adopt`). Queue-depth and the scheduler's
  ``Backpressure`` flag feed the same placement decision: a
  backpressured replica is passed over whenever a calmer one exists.
* **Journal handoff** (the headline): on a replica's death the router
  recovers its :class:`~tpusystem.serve.RequestJournal` through the
  existing :func:`~tpusystem.serve.recover_journal` preference chain —
  the dead replica's supervisor RAM first, then the buddy's
  ``journal:{identity}`` replica slot over the blob plane — and
  **redistributes** the rows across the surviving replicas:
  seated rows re-prefill ``prompt + emitted prefix`` on a *different
  engine* and resume decode (hot handoff), queued-only rows re-submit
  cold. Greedy and seeded sampled decode are both deterministic (the
  sampling counter is a pure function of ``(seed, position)``), so the
  final completions are token-exact against an uninterrupted fleet —
  drilled by
  ``tests/test_serve_fleet.py`` with a
  :class:`~tpusystem.parallel.chaos.PreemptionWave` killing replicas
  mid-stream. Rows routed after the journal's last push (the cadence
  window) are re-submitted cold from the router's own routing table, so
  **no request is ever silently dropped**, journal or not.
* **Timeout, retry, hedging** — :class:`RoutePolicy` bounds every
  request's time on one replica: past ``timeout * retry_backoff **
  attempt`` the request is cancelled there and re-routed (its partial
  tokens carry over as a hot prefix; ``max_retries`` caps the ladder),
  and an optional ``hedge_after`` fires a duplicate on a second replica
  — first completion wins, the loser is cancelled. Both reroute paths
  thread the ORIGINAL submission time through
  :meth:`~tpusystem.serve.Scheduler.restore`'s ``waited=``, so TTFT and
  latency accounting never reset on a retry. Hedging is safe for greedy
  AND seeded sampled decode alike: with counter-based sampling both legs
  of a hedge emit the identical stream (token at position ``p`` is a
  pure function of ``(seed, p)``), so first-completion-wins can never
  race two different answers. The one thing that would break this — an
  *unseeded* sampled request — is refused typed
  (:exc:`~tpusystem.serve.UnseededSampling`) at the front door.
* **Fleet degradation + autoscale** — fleet-scope
  :class:`~tpusystem.serve.Watermarks` shed by deadline slack across
  the WHOLE fleet's queues (the globally most-doomed request goes
  first), and past the high mark the fleet **browns out**: new requests
  without a deadline are refused typed (:exc:`FleetSaturated`) at the
  front door before the backlog collapses into shedding everything.
  Sustained backpressure grows the replica set and sustained idleness
  shrinks it (:class:`AutoscalePolicy` + ``provision``/``release``
  callables — the :meth:`~tpusystem.parallel.Supervisor.resize` /
  elastic-membership seam that carves chips from training and gives
  them back), narrated as ``FleetResized`` with ``fleet/*`` TensorBoard
  charts.

Everything runs on ONE injectable ``clock`` shared with every replica
and scheduler (the failover discipline), so timeout/hedge/shed/autoscale
policy is tier-1-testable with zero real sleeps.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable

import pickle

from tpusystem.parallel.chaos import WorkerKilled
from tpusystem.parallel.multihost import _blob_digest
from tpusystem.parallel.recovery import ROUTER_FENCED_EXIT
from tpusystem.serve.disagg import (HandoffCorrupt, RoleMismatch,
                                    kv_namespace, pack_handoff,
                                    unpack_handoff)
from tpusystem.serve.failover import (JournalCorrupt, RouterJournal,
                                      Watermarks, recover_journal,
                                      recover_router_journal)
from tpusystem.serve.scheduler import QueueFull
from tpusystem.serve.engine import Saturated, UnseededSampling

logger = logging.getLogger('tpusystem.serve.fleet')

__all__ = ['ReplicaDead', 'NoHealthyReplica', 'FleetSaturated',
           'RouterFenced', 'RouterLease', 'RoutePolicy', 'AutoscalePolicy',
           'ReplicaHandle', 'FleetTick', 'Router']


class ReplicaDead(RuntimeError):
    """The replica behind a handle is gone — raised by the handle's own
    kill seam (the in-process stand-in for SIGKILL) and treated, like
    :class:`~tpusystem.parallel.chaos.WorkerKilled` and ``OSError``,
    as a health verdict by the router: recover the journal, redistribute
    the rows, never route there again."""


class NoHealthyReplica(RuntimeError):
    """Every replica in the fleet is unhealthy — nothing can take the
    request right now. Submissions raise it; rows recovered from a dead
    replica's journal are parked in the router's orphan buffer instead
    (placed the moment a replica is adopted), so recovery itself never
    loses work to a momentary zero-healthy window."""


class FleetSaturated(RuntimeError):
    """The fleet refused the request at the front door: every healthy
    replica's backlog is full (``QueueFull`` everywhere), or the fleet
    is in brownout (global queue past the high watermark) and the
    request carries no deadline — unbounded-patience work is the first
    thing a degrading fleet stops accepting, BEFORE the backlog
    collapses into shedding requests that could still meet their
    deadlines."""


class RouterFenced(RuntimeError):
    """This router's lease term was superseded: a standby observed its
    missed renewals, fenced the term, and took over. The deposed router
    must STOP — keep placing requests against the new incumbent and the
    fleet split-brains. ``exit_code`` maps it into the supervisor
    contract (:data:`~tpusystem.parallel.recovery.ROUTER_FENCED_EXIT`,
    deliberately not restartable: the standby IS the restart)."""

    exit_code = ROUTER_FENCED_EXIT

    def __init__(self, term: int, observed: int):
        super().__init__(
            f'router lease term {term} fenced by term {observed}: a '
            f'standby took over; halt (exit {ROUTER_FENCED_EXIT}) instead '
            f'of split-braining placements against the new incumbent')
        self.term = term
        self.observed = observed


class RouterLease:
    """Monotonic-term lease over the memstore plane — the split-brain
    guard of warm-standby router takeover.

    No new consensus system: the lease record is one digest-framed blob
    under ``router-lease:{name}``, pushed with the memstore step encoded
    as ``term * 1_000_000 + count`` — the store's monotonic-step rule
    (an older step never replaces a newer one) then IS the fence: once a
    standby publishes ``term + 1``, every renewal the deposed router
    pushes is too old to land. The echo discipline of
    :mod:`tpusystem.parallel.elastic` closes the loop: after every push
    the holder re-reads the record, and a higher term echoed back is the
    typed :exc:`RouterFenced` verdict (exit 47 under a supervisor).

    Two sides, one clock (injectable — the tier-1 drills run with zero
    real sleeps):

    * the **active** router calls :meth:`renew` once per fleet tick; the
      lease self-gates to ``renew_every`` seconds, so tick rate never
      hammers the store. A push that cannot reach the plane degrades
      (log-once) — the lease is a takeover accelerator, never allowed to
      take routing down on a store hiccup.
    * the **standby** calls :meth:`watch` on its own loop: renewals
      advancing reset its patience; a record silent for ``miss_after``
      seconds returns True — fence with :meth:`acquire` (term + 1),
      rebuild via :meth:`Router.recover`, and serve.
    """

    def __init__(self, name: str = 'router', *, client: Any,
                 holder: str = 'router', renew_every: float = 1.0,
                 miss_after: float = 3.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if renew_every <= 0 or miss_after <= 0:
            raise ValueError('renew_every and miss_after must be positive '
                             'seconds')
        self.name = name
        self.identity = f'router-lease:{name}'
        self.client = client
        self.holder = holder
        self.renew_every = renew_every
        self.miss_after = miss_after
        self._clock = clock
        self.term = 0
        self.count = 0
        self._last_renewed: float | None = None
        self._seen: tuple[int, int] | None = None
        self._seen_at: float | None = None
        self._push_failed = False

    # ------------------------------------------------------------- wire

    def _pack(self) -> bytes:
        payload = pickle.dumps((self.term, self.count, self.holder),
                               protocol=pickle.HIGHEST_PROTOCOL)
        return _blob_digest(payload).encode('ascii') + b':' + payload

    @staticmethod
    def _unpack(data: bytes) -> tuple[int, int, str]:
        digest, sep, payload = bytes(data).partition(b':')
        if not sep or _blob_digest(payload).encode('ascii') != digest:
            raise JournalCorrupt('lease bytes failed their digest check — '
                                 'torn copy; treating as absent')
        try:
            term, count, holder = pickle.loads(payload)
            return int(term), int(count), str(holder)
        except Exception as error:
            raise JournalCorrupt(f'lease payload does not decode ({error}); '
                                 f'treating as absent') from error

    def _push(self) -> bool:
        step = self.term * 1_000_000 + self.count
        try:
            push = getattr(self.client, 'push', None)
            if push is not None:
                ok = bool(push(self.identity, step, self._pack()))
            else:             # bare MemStore (in-process drills, bench)
                self.client.put(self.identity, step, self._pack())
                ok = True
        except (OSError, ValueError):
            # ValueError includes the store's non-monotonic-step refusal:
            # a zombie term's renewal is too old to land — the echo read
            # below turns that into the RouterFenced verdict
            ok = False
        if ok:
            self._push_failed = False
        elif not self._push_failed:
            logger.warning('lease push for %r failed at term %d; routing '
                           'continues degraded', self.name, self.term)
            self._push_failed = True
        return ok

    def observe(self) -> tuple[int, int, str] | None:
        """The newest verified lease record ``(term, count, holder)``,
        or None when the plane is unreachable or the copy is torn."""
        try:
            entry = self.client.fetch(self.identity)
        except OSError:
            return None
        if entry is None:
            return None
        try:
            return self._unpack(entry.blob)
        except JournalCorrupt:
            return None

    # ----------------------------------------------------------- holder

    def acquire(self) -> int:
        """Fence every prior term and become the incumbent: publish
        ``observed term + 1``. Raises :exc:`RouterFenced` if another
        acquirer won the race (the echo reads back a higher term)."""
        observed = self.observe()
        self.term = (observed[0] if observed is not None else 0) + 1
        self.count = 0
        self._push()
        echo = self.observe()
        if echo is not None and echo[0] > self.term:
            raise RouterFenced(self.term, echo[0])
        self._last_renewed = self._clock()
        return self.term

    def renew(self) -> None:
        """One holder heartbeat (self-gated to ``renew_every``). Raises
        :exc:`RouterFenced` the moment a higher term is observed — the
        zombie-router guard."""
        if self.term < 1:
            raise ValueError('renew() before acquire(): the lease has no '
                             'term to renew')
        now = self._clock()
        if (self._last_renewed is not None
                and now - self._last_renewed < self.renew_every):
            return
        self.count += 1
        self._last_renewed = now
        self._push()
        echo = self.observe()
        if echo is not None and echo[0] > self.term:
            raise RouterFenced(self.term, echo[0])

    # ---------------------------------------------------------- standby

    def watch(self) -> bool:
        """Standby-side staleness probe: True when the incumbent's
        record has not advanced for ``miss_after`` seconds (time to
        fence and take over). An unreachable plane never trips it — a
        store outage must not look like a router death."""
        now = self._clock()
        observed = self.observe()
        if observed is None:
            return False
        seen = (observed[0], observed[1])
        if seen != self._seen:
            self._seen = seen
            self._seen_at = now
            return False
        return now - self._seen_at >= self.miss_after


# the exception classes the router reads as "this replica is dead", as
# opposed to a routing signal (QueueFull/Saturated) or a caller error
# (ValueError): the handle's own kill seam, the chaos harness's worker
# death, and the socket deaths a remote-replica transport would surface
_DEAD = (ReplicaDead, WorkerKilled, ConnectionError, OSError)


@dataclasses.dataclass(frozen=True)
class RoutePolicy:
    """Per-request placement policy.

    ``timeout`` bounds a request's time on one replica: past
    ``timeout * retry_backoff ** attempt`` it is cancelled there and
    re-routed to another healthy replica with its partial tokens as a
    hot prefix — capped exponential patience, at most ``max_retries``
    reroutes (after that the request stays put and its own ``deadline``
    is the last word). ``hedge_after`` (None = off) duplicates a
    still-unfinished request onto a second replica after that many
    seconds; the first completion wins and the loser is cancelled.
    """

    timeout: float | None = None
    max_retries: int = 2
    retry_backoff: float = 2.0
    hedge_after: float | None = None

    def __post_init__(self) -> None:
        if self.timeout is not None and self.timeout <= 0:
            raise ValueError(f'timeout must be positive seconds, got '
                             f'{self.timeout!r}')
        if self.max_retries < 0 or self.retry_backoff < 1.0:
            raise ValueError(
                f'need max_retries >= 0 and retry_backoff >= 1.0, got '
                f'{self.max_retries}/{self.retry_backoff}')
        if self.hedge_after is not None and self.hedge_after <= 0:
            raise ValueError(f'hedge_after must be positive seconds, got '
                             f'{self.hedge_after!r}')


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Traffic-driven fleet sizing.

    ``grow_after`` consecutive backpressured router ticks add a replica
    (up to ``max_replicas``) through the ``provision`` callable;
    ``shrink_after`` consecutive fully-idle ticks retire the emptiest
    one (down to ``min_replicas``) through ``release``. ``cooldown``
    ticks must pass between resizes so one burst cannot thrash the
    resize seam — the same rate-limit discipline as the elastic
    coordinator's cooldown.
    """

    min_replicas: int = 1
    max_replicas: int = 8
    grow_after: int = 3
    shrink_after: int = 50
    cooldown: int = 10

    def __post_init__(self) -> None:
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                f'need 1 <= min_replicas <= max_replicas, got '
                f'{self.min_replicas}/{self.max_replicas}')
        if self.grow_after < 1 or self.shrink_after < 1 or self.cooldown < 0:
            raise ValueError('grow_after/shrink_after must be >= 1 ticks '
                             'and cooldown >= 0')


class ReplicaHandle:
    """The router's view of one replica: placement counters, the health
    verdict, and the journal recovery chain.

    ``replica`` is a :class:`~tpusystem.serve.ServingReplica` or any
    object with its surface (``submit``/``step``/``results``/``idle``
    plus a ``scheduler``) — the fleet policy tests drive fakes through
    the same seam. ``journal_clients`` is the recovery preference chain
    for THIS replica's journal (dead replica's supervisor RAM first,
    then the buddy's replica slot — exactly
    :func:`~tpusystem.serve.recover_journal`'s contract); it defaults
    to the replica's own ``client`` + ``fallbacks``.

    ``external=True`` marks a replica the router must NOT step — it is
    driven by its own thread or process and proves liveness by calling
    :meth:`beat`; the router's ``heartbeat_timeout`` turns a stale beat
    into the unhealthy verdict (the remote-fleet liveness signal,
    mirrored in-process).

    :meth:`kill` is the chaos seam: the in-process analogue of SIGKILL
    (every later touch raises :exc:`ReplicaDead`), while the journal's
    out-of-process store — the supervisor RAM a real kill leaves behind
    — survives in ``journal_clients``.

    ``role`` is the disaggregated-serving placement tier (defaults to
    the replica's own ``role`` attribute, else ``'both'``):
    ``'prefill'`` replicas take new submissions and export KV handoffs
    (their scheduler is ``prefill_only``); ``'decode'`` replicas seat
    shipped strips and decode. Role is *placement policy, not
    capability* — a decode replica keeps its full prefill programs, so
    journal recovery can re-prefill rows on it. ``transport``/``rank``
    give the handle a blob plane: when both ends of a handoff carry
    one, the strips travel ``send_blob``/``fetch_blob`` (chunked,
    digest-verified) instead of by direct reference.
    """

    def __init__(self, replica: Any, *, name: str | None = None,
                 journal_clients: tuple = (), external: bool = False,
                 role: str | None = None, transport: Any = None,
                 rank: int = 0) -> None:
        self.replica = replica
        self.identity = getattr(replica, 'identity', None) or name or 'serve'
        self.name = name or self.identity
        if journal_clients:
            self.journal_clients = tuple(journal_clients)
        else:
            self.journal_clients = (getattr(replica, 'client', None),
                                    *getattr(replica, 'fallbacks', ()))
        self.external = external
        self.role = role or getattr(replica, 'role', 'both')
        if self.role not in ('both', 'prefill', 'decode'):
            raise ValueError(f"role must be 'both', 'prefill' or 'decode', "
                             f'got {self.role!r}')
        self.transport = transport
        self.rank = rank
        self.strips = None           # KVStripStore, attached on first offer
        self.healthy = True
        self.cause: str | None = None
        self.placements = 0          # submits + restores routed here
        self.last_beat: float | None = None
        self._beat_pending = False
        self._killed = False

    # ------------------------------------------------------------ state

    @property
    def scheduler(self) -> Any:
        return getattr(self.replica, 'scheduler', self.replica)

    @property
    def queue_depth(self) -> int:
        return self.scheduler.queue_depth

    @property
    def depth(self) -> int:
        """Load metric for least-loaded placement: queued + seated (+
        exported handoffs awaiting shipment on a prefill replica)."""
        return (self.scheduler.queue_depth + self.scheduler.active
                + len(getattr(self.scheduler, 'outbox', ())))

    @property
    def backpressure(self) -> bool:
        return bool(getattr(self.scheduler, 'backpressure', False))

    def cached_prefix(self, prompt) -> int:
        """Prefix-affinity probe: how many leading prompt tokens this
        replica's engine already holds in its radix tree (0 when the
        engine doesn't share prefixes, or for fleet-policy fakes
        without an engine). Never raises — affinity is a steering hint,
        not a correctness surface."""
        try:
            return int(self.scheduler.engine.prefix_cached_len(prompt))
        except (AttributeError, TypeError, *_DEAD):
            return 0

    @property
    def idle(self) -> bool:
        return bool(self.replica.idle)

    @property
    def results(self) -> dict:
        return self.replica.results

    # ------------------------------------------------------------ seams

    def kill(self) -> None:
        """Chaos seam: abrupt replica death (``PreemptionWave(kills=
        (handle.kill,))``). Every subsequent touch raises
        :exc:`ReplicaDead`; the journal stores outlive it."""
        self._killed = True

    def beat(self) -> None:
        """Externally-driven replicas call this from their own loop; the
        router stamps it with ITS clock at the next health check (the
        replica's thread must not race the router's time base) and
        ``heartbeat_timeout`` judges staleness."""
        self._beat_pending = True

    def _check(self) -> None:
        if self._killed:
            raise ReplicaDead(f'replica {self.name!r} was killed')

    def submit(self, request: Any) -> None:
        self._check()
        self.replica.submit(request)
        self.placements += 1

    def restore(self, request: Any, *, waited: float, prefix=()) -> None:
        """Place a rerouted/recovered row here: the scheduler re-queues
        it with its original wait and emitted prefix (and its journal —
        ``scheduler.journal`` — witnesses the restore, so a later death
        of THIS replica hands the row on again)."""
        self._check()
        self.scheduler.restore(request, waited=waited, prefix=prefix)
        self.placements += 1

    def cancel(self, request_id: str) -> str | None:
        if self._killed or not self.healthy:
            return None
        try:
            return self.scheduler.cancel(request_id)
        except _DEAD:
            return None

    def step(self) -> Any:
        self._check()
        return self.replica.step()

    # ------------------------------------------------- disaggregated seams

    def take_handoffs(self) -> list:
        """Drain a prefill replica's exported KV handoffs (empty for
        schedulers without the seam — fleet-policy fakes)."""
        self._check()
        take = getattr(self.scheduler, 'take_handoffs', None)
        return take() if take is not None else []

    def shipped(self, request_id: str) -> None:
        """Ack a delivered handoff on the prefill side (journal row
        closes, trace span ends)."""
        self._check()
        self.scheduler.shipped(request_id)

    def ingest(self, handoff: Any, *, waited: float = 0.0) -> None:
        """Queue a shipped handoff on this (decode-capable) replica."""
        self._check()
        self.scheduler.ingest(handoff, waited=waited)
        self.placements += 1

    def offer_strips(self, request_id: str, payload: bytes) -> None:
        """Publish a packed handoff on this handle's blob-request plane
        (``kv:{request}``), creating and chaining the
        :class:`~tpusystem.serve.disagg.KVStripStore` on first use."""
        if self.strips is None:
            from tpusystem.serve.disagg import KVStripStore
            self.strips = KVStripStore()
            if self.transport is not None:
                self.strips.attach(self.transport)
        self.strips.offer(request_id, payload)


@dataclasses.dataclass
class _Route:
    """The router's own record of where a request lives — the authority
    that guarantees no-silent-drop even past the journal's cadence
    window, and the source of the ORIGINAL submission time every
    reroute's ``waited=`` is computed from."""

    request: Any
    handle: str                      # current primary placement
    submitted: float                 # original router-clock submission
    routed_at: float                 # last (re)placement
    attempt: int = 0                 # reroutes consumed (timeout ladder)
    hedged: str | None = None        # secondary placement, when hedged


@dataclasses.dataclass
class FleetTick:
    """One router step's outcome, fleet-wide."""

    replicas: int                    # handles still healthy
    queued: int                      # global queue depth (healthy replicas)
    active: int
    completed: list                  # request ids settled this tick
    rerouted: list                   # RequestRerouted narrations this tick
    shed: list                       # fleet-watermark victims this tick
    orphans: int                     # recovered rows awaiting a replica
    handoffs: list = dataclasses.field(default_factory=list)
    # request ids whose KV strips moved prefill -> decode this tick
    emitted: dict = dataclasses.field(default_factory=dict)
    # request id -> list of tokens, merged across the replicas' ticks —
    # what the fleet delivered this step (the recovery bench watches it
    # for the first post-handoff token; speculative replicas can land
    # several tokens per request per tick)


class Router:
    """The fleet front door: health-checked, least-loaded, journal-aware.

    Args:
        handles: the initial fleet — :class:`ReplicaHandle` instances
            (bare ``ServingReplica``\\ s are wrapped automatically).
        policy: per-request :class:`RoutePolicy` (timeout/retry/hedge).
        watermarks: fleet-scope :class:`~tpusystem.serve.Watermarks`
            over the GLOBAL queue depth — shed by deadline slack across
            every replica's queue, brownout past the high mark.
        heartbeat_timeout: seconds after which an ``external`` handle's
            stale :meth:`~ReplicaHandle.beat` reads as death (None =
            externally-driven replicas are never judged by heartbeat).
        autoscale / provision / release: :class:`AutoscalePolicy` plus
            the resize seam — ``provision() -> ReplicaHandle`` grows
            the fleet (a supervised replica on capacity carved from
            training: :meth:`tpusystem.parallel.Supervisor.resize` /
            the elastic membership protocol), ``release(handle)``
            gives an idle replica's chips back.
        producer: event bus for ``ReplicaUnhealthy`` /
            ``RequestRerouted`` / ``FleetResized`` + the fleet-scope
            ``LoadShed``/``Backpressure`` narration.
        journal: a :class:`~tpusystem.serve.RouterJournal` — every
            ``cadence`` ticks the router's authoritative state
            (placements, orphans, in-flight handoffs, settled results,
            brownout/cooldown) replicates to the memstore plane, and a
            relaunched or standby router rebuilds it with
            :meth:`recover`. None = crash recovery falls back to the
            health sweep alone (cold rebuild).
        lease: a :class:`RouterLease` this router holds while serving —
            renewed once per tick (self-gated); a higher term observed
            raises :exc:`RouterFenced` out of :meth:`step` (exit 47
            under a supervisor: the standby has taken over).
        clock: THE fleet clock — must be the same callable every
            replica and scheduler in the fleet runs on (enforced per
            replica by ``ServingReplica``; timeouts, hedging, shedding
            and waited-accounting all subtract its timestamps).
    """

    def __init__(self, handles, *, policy: RoutePolicy | None = None,
                 watermarks: Watermarks | None = None,
                 heartbeat_timeout: float | None = None,
                 autoscale: AutoscalePolicy | None = None,
                 provision: Callable[[], ReplicaHandle] | None = None,
                 release: Callable[[ReplicaHandle], None] | None = None,
                 producer: Any = None, tracer: Any = None,
                 journal: RouterJournal | None = None,
                 lease: RouterLease | None = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.handles = [handle if isinstance(handle, ReplicaHandle)
                        else ReplicaHandle(handle) for handle in handles]
        names = [handle.name for handle in self.handles]
        if len(set(names)) != len(names):
            raise ValueError(f'replica names must be unique, got {names}')
        self.policy = policy or RoutePolicy()
        self.watermarks = watermarks
        self.heartbeat_timeout = heartbeat_timeout
        self.autoscale = autoscale
        if autoscale is not None and provision is None:
            raise ValueError('autoscale needs a provision() callable — the '
                             'supervisor/elastic resize seam that builds a '
                             'new replica')
        self._provision = provision
        self._release = release
        self.producer = producer
        # observe.Tracer | None: the router roots ONE trace per request
        # (request.trace then travels with the work — through every
        # replica's scheduler, the journal, and any reroute — so a
        # request's whole fleet journey is one connected trace); reroute
        # and hedge decisions mark as instants in that trace. None = no
        # tracing work on any path.
        self.tracer = tracer
        self._trace_roots: dict[str, Any] = {}
        self.journal = journal
        self.lease = lease
        self._clock = clock
        self.results: dict[str, Any] = {}
        self.brownout = False
        self.ticks = 0
        self._routes: dict[str, _Route] = {}
        self._orphans: list = []     # (request, submitted_at, prefix) rows
        self._undelivered: list = []  # (source_name, KVHandoff) retry queue
        self._reroutes_pending: list = []   # drained into the next FleetTick
        self._pressure_ticks = 0
        self._idle_ticks = 0
        self._cooldown = 0
        for handle in self.handles:
            handle.last_beat = self._clock()

    # ------------------------------------------------------------ intake

    @property
    def healthy(self) -> list[ReplicaHandle]:
        return [handle for handle in self.handles if handle.healthy]

    def _by_name(self, name: str) -> ReplicaHandle | None:
        for handle in self.handles:
            if handle.name == name:
                return handle
        return None

    @property
    def _split_roles(self) -> bool:
        """Whether the fleet runs a dedicated prefill tier (any healthy
        ``role='prefill'`` handle) — the switch that turns on role-aware
        placement and the handoff pump."""
        return any(handle.role == 'prefill' for handle in self.healthy)

    def _targets(self, *, exclude: str | None = None,
                 prompt=None, role: str | None = None) -> list[ReplicaHandle]:
        """Healthy replicas in placement order: calm before
        backpressured, then — when the request's prompt is known —
        prefix affinity (most cached leading tokens first: the replica
        whose radix tree already holds the blocks adopts them instead
        of re-prefilling), then least-loaded, fleet order as the stable
        tie-break. Affinity never outranks backpressure: a calm replica
        with a cold cache beats a backpressured one with a warm cache,
        so a hot shared prefix cannot pile the whole fleet's traffic
        onto one replica.

        ``role`` picks the tier in a split fleet: ``'prefill'`` ranks
        the prefill replicas by queue depth (prompts go where admission
        prefill will run soonest); ``'decode'`` ranks the
        decode-capable replicas (``role != 'prefill'``) by decode
        occupancy with prefix affinity preserved within the tier;
        ``None`` keeps the whole fleet (the colocated contract)."""
        ranked = [handle for handle in self.healthy
                  if handle.name != exclude]
        if role == 'prefill':
            ranked = [handle for handle in ranked
                      if handle.role == 'prefill']
        elif role == 'decode':
            ranked = [handle for handle in ranked
                      if handle.role != 'prefill']
        if prompt is not None:
            return sorted(ranked, key=lambda handle: (
                handle.backpressure, -handle.cached_prefix(prompt),
                handle.depth))
        return sorted(ranked,
                      key=lambda handle: (handle.backpressure, handle.depth))

    def submit(self, request: Any) -> str:
        """Route a request to the best healthy replica; returns the
        replica name it landed on. Raises :exc:`NoHealthyReplica` when
        the fleet is empty/dead and :exc:`FleetSaturated` when every
        healthy backlog is full — or when the fleet is in brownout and
        the request carries no deadline (degrade at the front door
        before the backlog collapses). An *unseeded* sampled request is
        refused typed (:exc:`~tpusystem.serve.UnseededSampling`) before
        placement: every fleet robustness move — replay, reroute,
        hedging — relies on decode being reproducible, and unseeded
        sampling is the one configuration that is not.

        Submission is **request-id idempotent**: a client resubmitting
        after a router redial (the takeover contract) is a no-op —
        already settled returns the ``'settled'`` sentinel (read the
        result from :attr:`results`), still in flight returns its
        current placement; neither double-places."""
        if request.id in self.results:
            return 'settled'
        routed = self._routes.get(request.id)
        if routed is not None:
            return routed.handle
        sampling = getattr(request, 'sampling', None)
        if (sampling is not None and sampling.sampled
                and sampling.seed is None):
            raise UnseededSampling(
                f'request {request.id!r} refused: sampled decode '
                f'(temperature > 0) without a seed is not reproducible — '
                f'replay, reroute, and hedging all require a seeded '
                f'stream; set SamplingParams.seed')
        if self.brownout and getattr(request, 'deadline', None) is None:
            raise FleetSaturated(
                f'request {request.id!r} refused: the fleet is past its '
                f'high watermark and the request has no deadline — '
                f'brownout sheds unbounded-patience work at the front door')
        now = self._clock()
        # a split fleet admits prompts on the prefill tier (ranked by
        # queue depth — where admission prefill runs soonest); the KV
        # strip reaches a decode replica through the handoff pump
        targets = self._targets(prompt=getattr(request, 'prompt', None),
                                role='prefill' if self._split_roles else None)
        if not targets:
            raise NoHealthyReplica('no healthy replica in the fleet')
        if self.tracer is not None and request.trace is None:
            root = self.tracer.begin(f'request {request.id}', cat='request',
                                     args={'request': request.id})
            request.trace = root.context
            self._trace_roots[request.id] = root
        full = 0
        for handle in targets:
            try:
                handle.submit(request)
            except (QueueFull, Saturated):
                full += 1
                continue
            except ValueError:
                # a request that can never run (oversized prompt/budget):
                # a caller error, not a routing signal — close its trace
                # truthfully before re-raising so the root can't leak open
                if self.tracer is not None:
                    self.tracer.end(self._trace_roots.pop(request.id, None),
                                    reason='invalid')
                    request.trace = None
                raise
            except _DEAD as death:
                self._fail(handle, f'died at submit ({death})')
                continue
            self._routes[request.id] = _Route(request, handle.name, now, now)
            return handle.name
        if self.tracer is not None:      # refused: close the trace truthfully
            refused_root = self._trace_roots.pop(request.id, None)
            if refused_root is not None:
                self.tracer.end(refused_root, reason='refused')
                # a documented retry-after-FleetSaturated must root a
                # FRESH trace, not parent into this closed one
                request.trace = None
        if full:
            raise FleetSaturated(
                f'request {request.id!r} refused: every healthy replica '
                f'is at max_queued')
        raise NoHealthyReplica('every replica died during placement')

    def cancel(self, request_id: str) -> str | None:
        """Cancel a request wherever the fleet holds it (both legs of a
        hedge, AND the orphan buffer — a cancelled row must not be
        resurrected by the next adopt); returns the primary leg's
        verdict (orphans count as ``'queued'``: silently dropped, the
        scheduler's queued-cancel contract)."""
        route = self._routes.pop(request_id, None)
        if self.tracer is not None:
            self.tracer.end(self._trace_roots.pop(request_id, None),
                            reason='cancelled')
        orphaned = [entry for entry in self._orphans
                    if entry[0].id == request_id]
        for entry in orphaned:
            self._orphans.remove(entry)
        # a handoff parked between tiers dies here too: ack the prefill
        # side (clears its shipping ledger) and drop the strips
        parked = [entry for entry in self._undelivered
                  if entry[1].request.id == request_id]
        for entry in parked:
            self._undelivered.remove(entry)
            source = self._by_name(entry[0])
            if source is not None:
                source.shipped(request_id)
        orphaned = orphaned or parked
        if route is None:
            return 'queued' if orphaned else None
        where = 'queued' if orphaned else None
        for name in (route.handle, route.hedged):
            if name is None:
                continue
            handle = self._by_name(name)
            if handle is None:
                continue
            verdict = handle.cancel(request_id)
            if name == route.handle:
                where = verdict if verdict is not None else where
                completion = handle.scheduler.results.get(request_id)
                if completion is not None:
                    self.results[request_id] = completion
        return where

    # ------------------------------------------------------------ health

    def _dispatch(self, event: Any) -> None:
        if self.producer is not None:
            self.producer.dispatch(event)

    def _fail(self, handle: ReplicaHandle, cause: str) -> None:
        """The health verdict: mark the replica unhealthy (one-way),
        recover its journal through the preference chain, and hand its
        rows to the survivors — hot for seated rows, cold for queued
        ones and for anything only the router's own table remembers."""
        if not handle.healthy:
            return
        handle.healthy = False
        handle.cause = cause
        in_flight = [route for route in self._routes.values()
                     if handle.name in (route.handle, route.hedged)]
        logger.warning(
            'replica %r marked unhealthy (%s); recovering its journal and '
            're-homing %d in-flight requests', handle.name, cause,
            len(in_flight))
        from tpusystem.observe.events import ReplicaUnhealthy
        self._dispatch(ReplicaUnhealthy(name=handle.name, cause=cause,
                                        routed=len(in_flight)))
        if self.tracer is not None:  # its own one-span trace: the verdict
            self.tracer.instant('replica-unhealthy', cat='fleet',
                                args={'replica': handle.name, 'cause': cause,
                                      'routed': len(in_flight)})
        recovered = recover_journal(handle.identity, handle.journal_clients)
        rows = recovered[1] if recovered is not None else []
        if recovered is None:
            logger.warning(
                'no recoverable journal for %r; its rows re-home cold from '
                'the routing table alone', handle.name)
        handled: set[str] = set()
        for request, waited, emitted in rows:
            handled.add(request.id)
            route = self._routes.get(request.id)
            if request.id in self.results:
                continue             # already settled (hedge won elsewhere)
            if route is not None:
                if route.hedged == handle.name:
                    route.hedged = None       # dead hedge leg: primary lives
                    continue
                if (route.handle != handle.name
                        and self._is_healthy(route.handle)):
                    continue         # live elsewhere (rerouted earlier)
                # prefer the router's own clock over the journal's
                # packed waited-seconds: the journal cannot count the
                # outage between its last push and this recovery
                waited = self._clock() - route.submitted
            self._place(request, waited, list(emitted), origin=handle.name,
                        cause='failover', route=route)
        # the cadence window: rows routed after the journal's last push
        # exist only in the routing table — cold re-submit, never drop
        for route in in_flight:
            request = route.request
            if request.id in handled or request.id in self.results:
                continue
            if route.hedged == handle.name:
                route.hedged = None
                continue
            if route.handle != handle.name and self._is_healthy(route.handle):
                continue
            self._place(request, self._clock() - route.submitted, [],
                        origin=handle.name, cause='failover', route=route)

    def _is_healthy(self, name: str) -> bool:
        handle = self._by_name(name)
        return handle is not None and handle.healthy

    def _place(self, request, waited: float, emitted: list, *, origin: str,
               cause: str, route: _Route | None) -> None:
        """Re-home one row on the best survivor (or the orphan buffer
        when none is healthy), narrated as ``RequestRerouted``."""
        now = self._clock()
        # affinity probes the REPLAYED prompt (original + emitted prefix)
        # — exactly the token sequence the adopting scheduler re-prefills
        prompt = getattr(request, 'prompt', None)
        replay = (list(prompt) + list(emitted)) if prompt is not None else None
        # a hot row (emitted prefix) re-prefills AND decodes — only a
        # decode-capable replica may adopt it (a prefill-only scheduler
        # raises RoleMismatch, typed precisely so it cannot be mistaken
        # for the finished-row ValueError below). A cold row re-enters
        # at the front door: the prefill tier when one exists.
        role = None
        if self._split_roles:
            role = 'decode' if emitted else 'prefill'
        targets = self._targets(exclude=origin, prompt=replay, role=role)
        if not targets and role == 'prefill':
            # no prefill replica can take it (the origin WAS the tier):
            # decode replicas keep their full prefill programs — role is
            # placement policy, not capability — so a cold row lands
            # there rather than orphaning
            targets = self._targets(exclude=origin, prompt=replay,
                                    role='decode')
        placed = None
        for handle in targets:
            try:
                handle.restore(request, waited=waited, prefix=emitted)
            except RoleMismatch:
                # the role guard fired: a decode-carrying row was offered
                # to a prefill-only scheduler (the role map and the fleet
                # disagree — should be unreachable through _targets).
                # Narrate typed and try the next target; the dashboard's
                # serve/role_mismatch counter charts the rate.
                from tpusystem.observe.events import RoleMismatched
                self._dispatch(RoleMismatched(id=request.id,
                                              replica=handle.name,
                                              prefix=len(emitted)))
                continue
            except _DEAD as death:
                self._fail(handle, f'died at restore ({death})')
                continue
            except ValueError:
                # a finished row has no business being re-homed (the
                # journal copy predates its completion): settle nothing,
                # the completion already stands where it was delivered
                return
            placed = handle
            break
        if placed is None:
            self._orphans.append((request, now - waited, list(emitted)))
            logger.warning('no healthy replica can adopt %r; parked in the '
                           'orphan buffer', request.id)
            return
        if route is None:
            route = self._routes[request.id] = _Route(
                request, placed.name, now - waited, now)
        route.handle, route.routed_at = placed.name, now
        if self.tracer is not None:
            self.tracer.instant(
                'reroute', cat='fleet', trace=request.trace,
                args={'request': request.id, 'origin': origin,
                      'target': placed.name,
                      'where': 'hot' if emitted else 'cold',
                      'prefix': len(emitted), 'cause': cause})
        from tpusystem.observe.events import RequestRerouted
        narration = RequestRerouted(
            id=request.id, origin=origin, target=placed.name,
            where='hot' if emitted else 'cold', prefix=len(emitted),
            cause=cause)
        self._reroutes_pending.append(narration)
        self._dispatch(narration)

    def adopt(self, handle: ReplicaHandle | Any) -> ReplicaHandle:
        """Add a replica to the fleet (a provisioned grow, or a replaced
        host rejoining as a FRESH handle — verdicts are one-way) and
        drain any orphaned rows onto it."""
        if not isinstance(handle, ReplicaHandle):
            handle = ReplicaHandle(handle)
        if self._by_name(handle.name) is not None:
            raise ValueError(f'replica name {handle.name!r} already in the '
                             f'fleet — retire the old handle first')
        handle.last_beat = self._clock()
        self.handles.append(handle)
        orphans, self._orphans = self._orphans, []
        for request, submitted_at, emitted in orphans:
            self._place(request, self._clock() - submitted_at, emitted,
                        origin='orphans', cause='failover',
                        route=self._routes.get(request.id))
        return handle

    # ----------------------------------------------------- crash recovery

    def snapshot(self) -> dict:
        """The router's authoritative state as a clock-portable dict —
        what :class:`~tpusystem.serve.RouterJournal` packs every cadence
        tick. Timestamps convert to waited-seconds at snapshot time
        (monotonic clocks do not compare across processes) and parked
        handoffs carry their digest-framed payload, so a relaunched
        router can re-ship them without the prefill tier re-exporting."""
        now = self._clock()
        return {
            'term': self.lease.term if self.lease is not None else 0,
            'brownout': self.brownout,
            'cooldown': self._cooldown,
            'results': dict(self.results),
            'routes': [(route.request, now - route.submitted, route.handle,
                        route.attempt, route.hedged)
                       for route in self._routes.values()],
            'orphans': [(request, now - submitted_at, list(emitted))
                        for request, submitted_at, emitted in self._orphans],
            'undelivered': [(source_name, handoff.request, handoff.waited,
                             list(handoff.prefix), pack_handoff(handoff))
                            for source_name, handoff in self._undelivered],
        }

    def recover(self, clients: Any = ()) -> dict:
        """Rebuild the fleet's authoritative state after a router crash
        or standby takeover: read the router journal through the
        ``clients`` preference chain (default: the journal's own client),
        then health-sweep every replica. The completion-edge idempotency
        table (``results``) restores FIRST, so nothing the old router
        already settled can double-complete; journaled routes whose
        replica still holds the row live (its request journal knows the
        id) re-attach and **keep streaming**; routes on dead or unaware
        replicas re-place (hot from the replica's own recovered journal
        where possible, cold otherwise); parked ``kv:{request}``
        handoffs re-queue for delivery from their journaled payload — a
        corrupt payload re-prefills cold, never wrong. Narrated as one
        ``RouterTakeover`` event; returns its counts as a dict."""
        started = self._clock()
        if self.lease is not None and self.journal is not None:
            self.journal.term = self.lease.term
        chain = tuple(clients)
        if not chain and self.journal is not None:
            chain = (self.journal.client,)
        recovered = (recover_router_journal(self.journal.name, chain)
                     if self.journal is not None else None)
        reseated = replaced = settled = handoffs = 0
        source = 'sweep'
        requeued: set[str] = set()
        if recovered is not None:
            tick, state = recovered
            self.journal.tick = tick     # pushes stay monotonic in the store
            source = 'journal'
            self.brownout = bool(state.get('brownout', False))
            self._cooldown = int(state.get('cooldown', 0))
            for request_id, completion in state.get('results', {}).items():
                if request_id not in self.results:
                    self.results[request_id] = completion
                    settled += 1
            # in-flight handoffs first, so the route loop below can tell
            # "parked but re-shippable" from "strips lost with the router"
            for source_name, request, waited, prefix, packed in \
                    state.get('undelivered', ()):
                if request.id in self.results:
                    continue
                try:
                    handoff = unpack_handoff(packed)
                except HandoffCorrupt:
                    from tpusystem.observe.events import HandoffCorrupted
                    self._dispatch(HandoffCorrupted(
                        id=request.id, origin=source_name,
                        target='(journal)'))
                    src = self._by_name(source_name)
                    if src is not None and src.healthy:
                        try:
                            src.shipped(request.id)
                        except _DEAD as death:
                            self._fail(src, f'died at takeover ({death})')
                    if request.id not in self._routes:
                        self._place(request, waited, list(prefix),
                                    origin=source_name,
                                    cause='handoff-corrupt', route=None)
                        replaced += 1
                    continue
                self._undelivered.append((source_name, handoff))
                requeued.add(request.id)
                handoffs += 1
            now = self._clock()
            for request, waited, handle_name, attempt, hedged in \
                    state.get('routes', ()):
                request_id = request.id
                if request_id in self.results or request_id in self._routes:
                    continue
                handle = self._by_name(handle_name)
                if handle is not None and handle.healthy:
                    try:
                        handle._check()
                        completion = handle.scheduler.results.get(request_id)
                        journal = getattr(handle.scheduler, 'journal', None)
                        row = (journal.rows.get(request_id)
                               if journal is not None else None)
                        shipping = request_id in getattr(
                            handle.scheduler, '_shipping', ())
                    except _DEAD as death:
                        self._fail(handle,
                                   f'died at takeover sweep ({death})')
                    else:
                        if completion is not None:
                            # finished while the router was down: settle
                            # at the completion edge, never re-place
                            self.results[request_id] = completion
                            settled += 1
                            continue
                        if row is not None and (not shipping
                                                or request_id in requeued):
                            # the seated row never stopped streaming (or
                            # its handoff re-queued above): re-attach and
                            # let it finish
                            self._routes[request_id] = _Route(
                                request, handle_name, now - waited, now,
                                attempt=int(attempt),
                                hedged=(hedged if hedged is not None
                                        and self._is_healthy(hedged)
                                        else None))
                            reseated += 1
                            continue
                        if shipping:
                            # the old router took the handoff but its
                            # strips died with it: close the prefill
                            # ledger and re-prefill on the decode tier
                            try:
                                handle.shipped(request_id)
                            except _DEAD as death:
                                self._fail(handle,
                                           f'died at takeover ({death})')
                        emitted = list(row.emitted) if row is not None else []
                        if request_id not in self._routes:
                            self._place(request, waited, emitted,
                                        origin=handle_name,
                                        cause='takeover', route=None)
                            replaced += 1
                        continue
                # dead or missing replica: _fail above (or an earlier
                # iteration) may already have re-homed it from the
                # replica's own journal — only the remainder goes cold
                if request_id in self.results:
                    settled += 1
                    continue
                if request_id in self._routes:
                    replaced += 1
                    continue
                self._place(request, waited, [], origin=handle_name,
                            cause='takeover', route=None)
                replaced += 1
            for request, waited, emitted in state.get('orphans', ()):
                if request.id in self.results or request.id in self._routes:
                    continue
                self._place(request, waited, list(emitted),
                            origin='orphans', cause='takeover', route=None)
                replaced += 1
        swept_routes, swept_settled = self._sweep(requeued)
        reseated += swept_routes
        settled += swept_settled
        seconds = self._clock() - started
        term = self.lease.term if self.lease is not None else 0
        logger.info(
            'router takeover (%s, term %d): %d reseated, %d replaced, %d '
            'settled, %d handoffs re-queued in %.3fs', source, term,
            reseated, replaced, settled, handoffs, seconds)
        from tpusystem.observe.events import RouterTakeover
        report = dict(term=term, source=source, reseated=reseated,
                      replaced=replaced, settled=settled, handoffs=handoffs,
                      seconds=seconds)
        self._dispatch(RouterTakeover(**report))
        return report

    def _sweep(self, requeued: set | None = None) -> tuple[int, int]:
        """Health sweep: adopt whatever the replicas themselves still
        know — their results dicts settle into the idempotency table,
        their request journals' live rows become routes. This is the
        whole cold rebuild when no router journal survives, and the
        cadence-window backstop when one does. A live row stuck in a
        replica's shipping ledger whose handoff did NOT survive
        (``requeued``) re-prefills on the decode tier instead of
        re-attaching — the strips died with the old router."""
        requeued = requeued or set()
        reseated = settled = 0
        now = self._clock()
        for handle in list(self.handles):
            if not handle.healthy:
                continue
            try:
                handle._check()
                results = dict(handle.scheduler.results)
                journal = getattr(handle.scheduler, 'journal', None)
                rows = dict(journal.rows) if journal is not None else {}
                shipping = set(getattr(handle.scheduler, '_shipping', ()))
            except _DEAD as death:
                self._fail(handle, f'died at takeover sweep ({death})')
                continue
            for request_id, completion in results.items():
                if request_id in self.results:
                    continue
                self.results[request_id] = completion
                self._routes.pop(request_id, None)
                settled += 1
            for request_id, row in rows.items():
                if request_id in self.results or request_id in self._routes:
                    continue
                if request_id in shipping and request_id not in requeued:
                    try:
                        handle.shipped(request_id)
                    except _DEAD as death:
                        self._fail(handle,
                                   f'died at takeover sweep ({death})')
                        break
                    self._place(row.request, now - row.submitted,
                                list(row.emitted), origin=handle.name,
                                cause='takeover', route=None)
                    reseated += 1
                    continue
                self._routes[request_id] = _Route(row.request, handle.name,
                                                  row.submitted, now)
                reseated += 1
        return reseated, settled

    def _renew_lease(self) -> None:
        try:
            self.lease.renew()
        except RouterFenced as fenced:
            from tpusystem.observe.events import RouterDeposed
            self._dispatch(RouterDeposed(term=fenced.term,
                                         observed=fenced.observed))
            raise

    # ------------------------------------------------------------ serving

    def step(self) -> FleetTick:
        """One fleet tick: step every healthy replica, settle
        completions (first wins under hedging), judge heartbeats, run
        the timeout/hedge ladder, shed past the fleet watermark, and
        let the autoscaler breathe. A held lease renews FIRST — a
        deposed router must stop before placing anything this tick
        (:exc:`RouterFenced` propagates; exit 47 under a supervisor) —
        and the router journal replicates LAST, after every state change
        the tick made."""
        self.ticks += 1
        if self.lease is not None:
            self._renew_lease()
        now = self._clock()
        completed: list = []
        emitted: dict = {}
        for handle in list(self.handles):
            if not handle.healthy:
                continue
            if handle.external:
                # an external replica is stepped by its own thread — the
                # router never sees its Ticks, so settle its routed
                # requests from the results dict instead (the scheduler
                # records every terminal transition there)
                self._judge_heartbeat(handle, now)
                if handle.healthy:
                    self._harvest_external(handle, completed)
                continue
            try:
                tick = handle.step()
            except _DEAD as death:
                self._fail(handle, f'died mid-step ({death})')
                continue
            handle.last_beat = self._clock()
            if tick is None:         # the replica relaunched in-process
                continue
            emitted.update(tick.emitted)
            for completion in tick.completed:
                self._settle(completion, handle, completed)
            for completion, _where in tick.expired:
                self._settle(completion, handle, completed)
            for completion, _slack in tick.shed:
                self._settle(completion, handle, completed)
        handoffs = self._pump_handoffs()
        self._retry_and_hedge()
        shed = self._fleet_shed()
        self._breathe()
        reroutes, self._reroutes_pending = self._reroutes_pending, []
        queued = sum(h.scheduler.queue_depth for h in self.healthy)
        active = sum(h.scheduler.active for h in self.healthy)
        tick = FleetTick(replicas=len(self.healthy), queued=queued,
                         active=active, completed=completed,
                         rerouted=reroutes, shed=shed,
                         orphans=len(self._orphans), handoffs=handoffs,
                         emitted=emitted)
        if self.journal is not None:
            if self.lease is not None:
                self.journal.term = self.lease.term
            self.journal.observe_tick(self.snapshot)
        return tick

    # ------------------------------------------------------------ handoff

    def _pump_handoffs(self) -> list:
        """Move every finished prefill's KV strips to a decode replica:
        drain each healthy prefill handle's outbox, deliver over the
        blob plane when both sides carry a transport (offered under
        ``kv:{request}``, fetched chunk-digest-verified, released on
        ack) or in-process otherwise, verify the end-to-end digest, and
        seat the strip through the target's ``ingest`` →
        ``admit_prefilled`` → ``adopt_prefill`` chain. Returns the
        request ids that moved this tick. A corrupt payload falls back
        to a cold re-place (the prompt re-prefills — slower, never
        wrong); no healthy decode target parks the handoff in the
        ``_undelivered`` retry queue, drained first next tick."""
        moved: list = []
        retries, self._undelivered = self._undelivered, []
        for source_name, handoff in retries:
            source = self._by_name(source_name)
            if source is None or not source.healthy:
                # the prefill replica died after export: the strips are
                # gone with it, but the prompt is not — re-place cold,
                # unless the journal recovery in _fail already re-homed
                # the row (or a hedge settled it)
                route = self._routes.get(handoff.request.id)
                if (handoff.request.id in self.results
                        or (route is not None
                            and route.handle != source_name
                            and self._is_healthy(route.handle))):
                    continue
                self._place(handoff.request, handoff.waited,
                            list(handoff.prefix),
                            origin=source_name or 'handoffs',
                            cause='failover', route=route)
                continue
            self._deliver(source, handoff, moved)
        for handle in list(self.handles):
            if not handle.healthy or handle.role != 'prefill':
                continue
            try:
                outbox = handle.take_handoffs()
            except _DEAD as death:
                self._fail(handle, f'died at handoff export ({death})')
                continue
            for handoff in outbox:
                self._deliver(handle, handoff, moved)
        return moved

    def _deliver(self, source: ReplicaHandle, handoff, moved: list) -> None:
        request = handoff.request
        if request.id in self.results:   # settled while queued (cancel/shed)
            source.shipped(request.id)
            return
        now = self._clock()
        # decode-side affinity probes prompt + replayed prefix — the
        # tokens whose blocks a warm radix tree could already hold
        prompt = getattr(request, 'prompt', None)
        replay = ((list(prompt) + list(handoff.prefix))
                  if prompt is not None else None)
        targets = self._targets(exclude=source.name, prompt=replay,
                                role='decode')
        route = self._routes.get(request.id)
        placed = None
        for target in targets:
            try:
                if (source.transport is not None
                        and target.transport is not None):
                    # the real disaggregation wire: offer on the prefill
                    # side, pull over the chunked digest-verified blob
                    # plane, release on ack — a fetch that dies mid-
                    # flight just retries, the strip is still offered
                    source.offer_strips(request.id, pack_handoff(handoff))
                    data = target.transport.fetch_blob(
                        source.rank, kv_namespace(request.id))
                    source.strips.release(request.id)
                else:
                    data = pack_handoff(handoff)
                received = unpack_handoff(data)
            except HandoffCorrupt as corrupt:
                logger.warning(
                    'KV handoff for %r failed verification (%s); '
                    're-prefilling cold on the decode tier', request.id,
                    corrupt)
                from tpusystem.observe.events import HandoffCorrupted
                self._dispatch(HandoffCorrupted(id=request.id,
                                                origin=source.name,
                                                target=target.name))
                source.shipped(request.id)
                self._place(request, handoff.waited, list(handoff.prefix),
                            origin=source.name, cause='handoff-corrupt',
                            route=route)
                return
            except _DEAD as death:
                self._fail(target, f'died at handoff ingest ({death})')
                continue
            try:
                waited = (now - route.submitted if route is not None
                          else handoff.waited)
                target.ingest(received, waited=waited)
            except _DEAD as death:
                self._fail(target, f'died at handoff ingest ({death})')
                continue
            placed = target
            break
        if placed is None:
            self._undelivered.append((source.name, handoff))
            logger.warning('no healthy decode replica can seat %r; handoff '
                           'parked for retry', request.id)
            return
        if route is None:
            route = self._routes[request.id] = _Route(
                request, placed.name, now - handoff.waited, now)
        route.handle, route.routed_at = placed.name, now
        source.shipped(request.id)
        moved.append(request.id)
        size = sum(getattr(strip, 'nbytes', 0)
                   for strip in handoff.kv.values())
        tokens = (len(prompt) if prompt is not None else 0) \
            + len(handoff.prefix)
        if self.tracer is not None:
            self.tracer.instant(
                'kv-handoff', cat='fleet', trace=request.trace,
                args={'request': request.id, 'origin': source.name,
                      'target': placed.name, 'tokens': tokens,
                      'bytes': size})
        from tpusystem.observe.events import PrefillHandoff
        self._dispatch(PrefillHandoff(
            id=request.id, origin=source.name, target=placed.name,
            tokens=tokens, bytes=size))

    def _harvest_external(self, handle: ReplicaHandle,
                          completed: list) -> None:
        """Settle routed requests an externally-driven replica finished
        on its own loop. A route the router itself cancelled is already
        popped before the cancel lands, so anything still routed here
        with a terminal result is a genuine completion."""
        for route in list(self._routes.values()):
            if handle.name not in (route.handle, route.hedged):
                continue
            completion = handle.results.get(route.request.id)
            if completion is not None:
                self._settle(completion, handle, completed)

    def _judge_heartbeat(self, handle: ReplicaHandle, now: float) -> None:
        if getattr(handle, '_beat_pending', False):
            handle._beat_pending = False
            handle.last_beat = now
        if (self.heartbeat_timeout is not None
                and handle.last_beat is not None
                and now - handle.last_beat >= self.heartbeat_timeout):
            self._fail(handle, f'heartbeat stale ({self.heartbeat_timeout}s)')

    def _settle(self, completion: Any, handle: ReplicaHandle,
                completed: list) -> None:
        """First terminal verdict wins: record the completion, drop the
        route, and cancel the losing hedge leg."""
        request_id = completion.request.id
        if request_id in self.results:
            return                   # a hedge already won elsewhere
        self.results[request_id] = completion
        completed.append(request_id)
        if self.tracer is not None:
            self.tracer.end(self._trace_roots.pop(request_id, None),
                            reason=completion.reason, replica=handle.name,
                            produced=len(completion.tokens))
        route = self._routes.pop(request_id, None)
        if route is None:
            return
        for name in (route.handle, route.hedged):
            if name is not None and name != handle.name:
                loser = self._by_name(name)
                if loser is not None:
                    loser.cancel(request_id)

    def _retry_and_hedge(self) -> None:
        if self.policy.timeout is None and self.policy.hedge_after is None:
            return
        now = self._clock()
        for route in list(self._routes.values()):
            if route.request.id in self.results:
                continue
            elapsed = now - route.routed_at
            if (self.policy.timeout is not None
                    and route.attempt < self.policy.max_retries
                    and elapsed >= self.policy.timeout
                    * self.policy.retry_backoff ** route.attempt):
                self._reroute_timeout(route)
                continue
            if (self.policy.hedge_after is not None and route.hedged is None
                    and elapsed >= self.policy.hedge_after):
                self._hedge(route)

    def _reroute_timeout(self, route: _Route) -> None:
        """The request overstayed its per-replica patience: cancel it
        there (keeping its partial tokens as the new placement's hot
        prefix) and re-place it elsewhere, original submission time
        intact — a retry reports latency from the FIRST submission."""
        handle = self._by_name(route.handle)
        prefix: list = []
        if handle is not None:
            verdict = handle.cancel(route.request.id)
            if verdict == 'active':
                partial = handle.scheduler.results.get(route.request.id)
                if partial is not None:
                    prefix = list(partial.tokens)
        route.attempt += 1
        self._place(route.request, self._clock() - route.submitted, prefix,
                    origin=route.handle, cause='timeout', route=route)

    def _hedge(self, route: _Route) -> None:
        # a hedge leg runs the request end to end — prefill-only
        # replicas cannot host it, so a split fleet hedges on the
        # decode tier (which colocated replicas also belong to)
        targets = self._targets(
            exclude=route.handle,
            role='decode' if self._split_roles else None)
        if not targets:
            return                   # nowhere to hedge
        target = targets[0]
        try:
            target.restore(route.request,
                           waited=self._clock() - route.submitted, prefix=())
        except _DEAD as death:
            self._fail(target, f'died at hedge ({death})')
            return
        except ValueError:
            return
        route.hedged = target.name
        if self.tracer is not None:
            self.tracer.instant(
                'hedge', cat='fleet', trace=route.request.trace,
                args={'request': route.request.id, 'origin': route.handle,
                      'target': target.name})
        from tpusystem.observe.events import RequestRerouted
        narration = RequestRerouted(
            id=route.request.id, origin=route.handle, target=target.name,
            where='cold', prefix=0, cause='hedge')
        self._reroutes_pending.append(narration)
        self._dispatch(narration)

    # ------------------------------------------------------ degradation

    def _fleet_shed(self) -> list:
        """Past the fleet high watermark, shed down to the low one by
        deadline slack across EVERY healthy replica's queue — the
        globally most-doomed request goes first, no-deadline requests
        last newest-first (each replica's own ordering contract, lifted
        to the fleet). Maintains the brownout flag and narrates
        fleet-scope ``LoadShed``/``Backpressure``."""
        if self.watermarks is None:
            return []
        depth = sum(h.scheduler.queue_depth for h in self.healthy)
        excess = self.watermarks.excess(depth)
        if not excess:
            if self.brownout and depth <= self.watermarks.low:
                self.brownout = False
                self._narrate_backpressure(depth)
            return []
        engaged_now = not self.brownout
        self.brownout = True
        candidates = []
        for handle in self.healthy:
            for request_id, slack, waited in \
                    handle.scheduler.shed_candidates():
                key = ((0, slack) if slack is not None else (1, waited))
                candidates.append((key, request_id, slack, handle))
        candidates.sort(key=lambda item: item[0])
        shed = []
        from tpusystem.observe.events import LoadShed
        for _key, request_id, slack, handle in candidates[:excess]:
            completion = handle.scheduler.shed(request_id)
            if completion is None:
                continue
            self.results[request_id] = completion
            if self.tracer is not None:
                self.tracer.end(self._trace_roots.pop(request_id, None),
                                reason='shed')
            self._routes.pop(request_id, None)
            shed.append((completion, slack))
            self._dispatch(LoadShed(id=request_id,
                                    produced=len(completion.tokens),
                                    queue_depth=depth, slack=slack))
        if engaged_now:
            self._narrate_backpressure(depth)
        return shed

    def _narrate_backpressure(self, depth: int) -> None:
        from tpusystem.observe.events import Backpressure
        self._dispatch(Backpressure(engaged=self.brownout,
                                    queue_depth=depth))

    # -------------------------------------------------------- autoscale

    def _pressured_role(self) -> str:
        """Which tier of a split fleet needs the next replica: compare
        prefill vs decode by (replicas backpressured, total queue
        depth); undelivered handoffs count against the decode tier —
        they are literally work with no decode seat. This is how the
        autoscaler rebalances the prefill:decode ratio instead of
        blindly growing whichever role ``provision`` defaults to."""
        score = {'prefill': [0, 0], 'decode': [0, 0]}
        for handle in self.healthy:
            tier = 'prefill' if handle.role == 'prefill' else 'decode'
            score[tier][0] += int(handle.backpressure)
            score[tier][1] += handle.depth
        score['decode'][1] += len(self._undelivered)
        return max(('decode', 'prefill'),
                   key=lambda tier: tuple(score[tier]))

    def _breathe(self) -> None:
        """Traffic-driven sizing: sustained backpressure (or orphaned
        rows, or undeliverable KV handoffs) grows the fleet through
        ``provision``; sustained full idleness retires the emptiest
        replica through ``release``. A split fleet grows the MORE
        pressured tier (``provision(role=...)``, falling back to a
        role-less ``provision()`` for legacy callables) and never
        shrinks a tier to zero."""
        if self.autoscale is None:
            return
        pressured = (self.brownout or bool(self._orphans)
                     or bool(self._undelivered)
                     or any(handle.backpressure for handle in self.healthy))
        busy = bool(self._routes) or not all(
            handle.idle for handle in self.healthy)
        self._pressure_ticks = self._pressure_ticks + 1 if pressured else 0
        self._idle_ticks = 0 if (pressured or busy) else self._idle_ticks + 1
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        from tpusystem.observe.events import FleetResized
        if (pressured and self._pressure_ticks >= self.autoscale.grow_after
                and len(self.healthy) < self.autoscale.max_replicas):
            if self._split_roles:
                role = self._pressured_role()
                try:
                    replica = self._provision(role=role)
                except TypeError:    # a role-blind provision callable
                    replica = self._provision()
            else:
                replica = self._provision()
            handle = self.adopt(replica)
            self._pressure_ticks = 0
            self._cooldown = self.autoscale.cooldown
            logger.info('fleet grew to %d replicas (+%r): sustained '
                        'backpressure', len(self.healthy), handle.name)
            self._dispatch(FleetResized(action='grow',
                                        replicas=len(self.healthy),
                                        cause='backpressure',
                                        name=handle.name))
            return
        if (self._idle_ticks >= self.autoscale.shrink_after
                and len(self.healthy) > self.autoscale.min_replicas):
            idle = [handle for handle in self.healthy if handle.idle]
            if self._split_roles:
                # never shrink a tier to zero: a fleet with prompts but
                # no prefill replica (or strips but no decode replica)
                # deadlocks until the next grow
                tiers: dict[str, int] = {}
                for handle in self.healthy:
                    tier = 'prefill' if handle.role == 'prefill' else 'decode'
                    tiers[tier] = tiers.get(tier, 0) + 1
                idle = [handle for handle in idle if tiers.get(
                    'prefill' if handle.role == 'prefill' else 'decode',
                    0) > 1]
            if not idle:
                return               # never retire a replica holding work
            victim = idle[-1]        # newest-added idle replica goes back
            self.handles.remove(victim)
            self._idle_ticks = 0
            self._cooldown = self.autoscale.cooldown
            logger.info('fleet shrank to %d replicas (-%r): traffic ebbed',
                        len(self.healthy), victim.name)
            self._dispatch(FleetResized(action='shrink',
                                        replicas=len(self.healthy),
                                        cause='idle', name=victim.name))
            if self._release is not None:
                self._release(victim)

    # ------------------------------------------------------------- drain

    @property
    def idle(self) -> bool:
        return (not self._routes and not self._orphans
                and not self._undelivered
                and all(handle.idle for handle in self.healthy))

    def run_until_idle(self, max_steps: int = 10_000) -> dict:
        """Step until every routed request settles; returns request id
        -> Completion across the whole fleet."""
        for _ in range(max_steps):
            if self.idle:
                return self.results
            self.step()
        raise RuntimeError(
            f'fleet did not drain in {max_steps} steps '
            f'({len(self._routes)} in flight, {len(self._orphans)} '
            f'orphaned, {len(self.healthy)} healthy replicas)')
