"""Serving survives the chaos drill: journal, replay, watchdog, shedding.

The training side has a complete survival story — chaos-tested control
plane, divergence sentinel, supervised hot restore, elastic resize — but
until now a SIGKILL'd serving engine lost every queued and in-flight
request, a hung decode step wedged forever, and overload was handled
only by ``Saturated`` at submit. This module is the serving replica's
survival layer, built on the machinery that already exists:

* **Request journal** (:class:`RequestJournal`) — every submission
  records ``(request, waited)`` and every tick appends each active row's
  emitted-token delta. Tokens only: the journal is tiny (ints, not KV
  state), so it can be pushed **out of the worker process** at a
  configurable cadence — to the supervisor's in-memory store over the
  existing :class:`~tpusystem.checkpoint.memstore.MemStoreClient` wire,
  under the new identity namespace ``journal:{identity}``
  (:func:`journal_identity`). The supervisor's buddy replication then
  mirrors it cross-host over ``send_blob``/``fetch_blob`` exactly like
  hot training state — the PR-5 MemStore/buddy discipline, inherited for
  free. Every packed journal carries its own digest
  (:meth:`RequestJournal.pack`), so a torn copy reads as absent
  (:exc:`JournalCorrupt`), never as requests.
* **Replay** (:func:`replay`) — a relaunched engine rebuilds its batch
  by re-queueing each journaled request with its emitted prefix; the
  scheduler re-prefills ``prompt + prefix`` and resumes decode. Greedy
  and seeded sampled decode are both deterministic (counter-based
  sampling: token at stream position ``p`` is a pure function of the
  request's seed and ``p``), so the final completion (prefix + resumed
  tokens) is **token-exact** against an uninterrupted reference — the
  headline drill of ``tests/test_serve_failover.py`` and the SIGKILL
  stage of ``__graft_entry__.dryrun_multichip``. A row the journal only
  knew as queued re-submits cold (full re-prefill) — still token-exact,
  just more work; an unrecoverable journal degrades to serving new
  traffic, never a crash.
* **Step watchdog** (:class:`StepWatchdog`) — a hung or anomalously slow
  decode step becomes a typed :exc:`EngineStalled` instead of a silent
  wedge: restart-and-replay is the remedy, the same relaunch path a kill
  takes. For a step that never returns at all, :meth:`StepWatchdog.guard`
  arms a deadman timer that exits the worker with the restart contract's
  worker-lost code so the :class:`~tpusystem.parallel.Supervisor`
  relaunches it (the 42/43/1 exit table — docs/multihost.md).
* **Load shedding** (:class:`Watermarks`) — admission control grows
  high/low queue watermarks: past ``high`` the scheduler sheds queued
  requests down to ``low``, picking victims by **deadline slack** (the
  request that will expire anyway goes first; an active, almost-done row
  is never shed), narrated as typed ``LoadShed`` + ``Backpressure``
  events instead of silent unbounded backlog.

:class:`ServingReplica` ties it together for one replica: a supervised
serving loop that journals every tick, watches the step clock, and on a
stall — or at construction, when a journal is recoverable (the
relaunched-worker path) — rebuilds the engine and replays. Everything is
narrated on the bus (``RequestReplayed`` / ``EngineRestarted`` /
``LoadShed``) and charted by the TensorBoard consumer
(``serve/recovery_seconds|replayed|shed``).

Determinism: replay is token-exact for greedy AND seeded sampled
decode. The sampling counter is a pure function of ``(seed, position)``
(:func:`tpusystem.train.generate.sampling_key`), so no RNG state needs
journaling beyond what the journal already holds — the emitted prefix IS
the position. A pre-sampling packed journal (no ``sampling`` field on
its requests) unpacks as greedy (:meth:`RequestJournal.unpack`).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import threading
import time
from typing import Any, Callable

# the shared digest primitive, imported exactly the way memstore.py
# imports it: the PUBLIC wrapper (checkpoint.memstore.blob_digest) lives
# behind the orbax-taxed checkpoint package import, which a client-less
# serving replica must not pay — all three digest call sites (transport
# blob frames, memstore slots, journal packs) deliberately share this one
# underscore seam so "verified" can never mean two different things
from tpusystem.parallel.multihost import _blob_digest
from tpusystem.parallel.recovery import LOST_WORKER_EXIT

logger = logging.getLogger('tpusystem.serve.failover')

__all__ = ['EngineStalled', 'JournalCorrupt', 'journal_identity',
           'JournalRow', 'RequestJournal', 'recover_journal', 'replay',
           'ReplayReport', 'StepWatchdog', 'Watermarks', 'ServingReplica',
           'router_identity', 'RouterJournal', 'recover_router_journal']


class EngineStalled(RuntimeError):
    """A decode step hung or ran anomalously slow — the serving
    equivalent of a lost worker. The remedy is the relaunch path a kill
    takes: rebuild the engine, replay the journal. Supervised workers map
    it to the restart contract's worker-lost exit (42) so the
    :class:`~tpusystem.parallel.Supervisor` relaunches them."""

    def __init__(self, seconds: float, threshold: float, kind: str):
        super().__init__(
            f'decode step took {seconds:.3f}s against a {threshold:.3f}s '
            f'{kind} threshold — treating the engine as stalled; restart '
            f'and replay the request journal')
        self.seconds = seconds
        self.threshold = threshold
        self.kind = kind                  # 'stall' | 'slow'


class JournalCorrupt(ValueError):
    """Packed journal bytes failed their digest or shape check — the
    copy reads as absent (recovery falls to the next replica or to cold),
    never as requests."""


def journal_identity(identity: str) -> str:
    """The memstore identity a replica's journal travels under. A
    distinct namespace (``journal:{identity}``) keeps journal pushes from
    ever colliding with the same identity's hot *training-state* slots,
    while riding the identical push/replicate/pull machinery — the
    supervisor's buddy replication and replaced-host pull work on it
    unchanged (the ``replica:``/``hot:``/``own:`` key discipline of
    :mod:`tpusystem.parallel.supervisor`)."""
    return f'journal:{identity}'


# ---------------------------------------------------------------------------
# the journal


@dataclasses.dataclass
class JournalRow:
    """One request's survival record: the request itself, when it was
    submitted (scheduler clock; packed as *waited seconds* so the record
    stays meaningful across a process boundary — monotonic clocks do not
    compare between processes), and every token emitted so far. There is
    deliberately no seated flag: a row with emitted tokens was seated by
    construction (admission emits the first token), so the derived fact
    ``bool(emitted)`` is the one source of truth."""

    request: Any
    submitted: float
    emitted: list = dataclasses.field(default_factory=list)


class RequestJournal:
    """In-memory request journal with out-of-process replication.

    The scheduler drives it through five hooks (``record`` at submit,
    ``seated`` + ``append`` as tokens emit, ``finished`` at any terminal
    transition, ``restored`` when replay re-queues a row) and calls
    :meth:`observe_tick` once per scheduler step — which packs and pushes
    the journal to ``client`` every ``cadence`` ticks. ``cadence`` is the
    durability window: a kill can lose at most the last ``cadence - 1``
    ticks of token deltas, and replay simply re-decodes them (greedy
    and seeded sampled decode are deterministic, so the outcome is
    unchanged — only the recovery does more work).

    ``client`` is anything with the memstore read/write surface: a
    :class:`~tpusystem.checkpoint.memstore.MemStoreClient` (the
    supervised worker's wire), a bare
    :class:`~tpusystem.checkpoint.memstore.MemStore` (the in-process
    drills), or None (journaling off — the scheduler runs exactly as
    before). Push failures degrade and log once — the journal is a
    recovery accelerator, never allowed to take serving down.
    """

    def __init__(self, identity: str = 'serve', *, client: Any = None,
                 cadence: int = 1,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if cadence < 1:
            raise ValueError(f'cadence must be >= 1 ticks, got {cadence}')
        self.identity = identity
        self.client = client
        self.cadence = cadence
        self.rows: dict[str, JournalRow] = {}
        self.tick = 0                 # monotonic across relaunches (seeded
        self.pushes = 0               # from the recovered journal's tick)
        self._clock = clock
        self._push_failed = False

    # ---------------------------------------------------------- hooks

    def record(self, request: Any, submitted: float) -> None:
        self.rows[request.id] = JournalRow(request, submitted)

    def restored(self, request: Any, submitted: float,
                 emitted: list) -> None:
        """Replay re-queued a journaled row: pre-seed its emitted prefix
        so the next ``seated``/``append`` hooks extend it instead of
        restarting the record."""
        self.rows[request.id] = JournalRow(request, submitted,
                                           emitted=list(emitted))

    def append(self, request_id: str, token: int) -> None:
        row = self.rows.get(request_id)
        if row is not None:
            row.emitted.append(int(token))

    # the admission-token hook: same record as a decode emission (a row
    # with any emitted token is seated by construction), named so the
    # scheduler's call sites read as the lifecycle they witness
    seated = append

    def finished(self, request_id: str) -> None:
        self.rows.pop(request_id, None)

    # ---------------------------------------------------- pack / wire

    def pack(self) -> bytes:
        """The journal as digest-prefixed bytes. Rows pack in FIFO
        submission order with ``submitted`` converted to waited-seconds
        (clock-portable across a relaunch)."""
        now = self._clock()
        rows = [(row.request, now - row.submitted, list(row.emitted))
                for row in self.rows.values()]
        payload = pickle.dumps((self.tick, rows),
                               protocol=pickle.HIGHEST_PROTOCOL)
        return _blob_digest(payload).encode('ascii') + b':' + payload

    @staticmethod
    def unpack(data: bytes) -> tuple[int, list]:
        """``(tick, [(request, waited, emitted), ...])`` from
        :meth:`pack` bytes; raises :exc:`JournalCorrupt` when the digest
        or shape does not verify. A journal packed before sampling
        existed carries requests with no ``sampling`` attribute in their
        pickled ``__dict__`` — those normalize to ``sampling = None``
        (greedy), so an upgrade mid-incident replays an old journal
        token-exactly instead of crashing on the missing field."""
        digest, sep, payload = bytes(data).partition(b':')
        if not sep or _blob_digest(payload).encode('ascii') != digest:
            raise JournalCorrupt(
                'journal bytes failed their digest check — torn or '
                'corrupted copy; treating as absent')
        try:
            tick, rows = pickle.loads(payload)
            rows = [(request, float(waited), list(emitted))
                    for request, waited, emitted in rows]
            for request, _, _ in rows:
                # instance __dict__, not hasattr: the dataclass default
                # is a class attribute, so hasattr is always True
                if 'sampling' not in vars(request):
                    request.sampling = None       # pre-sampling journal
        except Exception as error:
            raise JournalCorrupt(
                f'journal payload does not decode ({error}); treating as '
                f'absent') from error
        return int(tick), rows

    def observe_tick(self) -> None:
        """One scheduler step elapsed: advance the tick and replicate at
        the cadence. The tick is journal-owned (NOT the scheduler's step
        counter, which restarts at relaunch) so pushes stay monotonic
        across relaunches — the memstore slot discipline requires it."""
        self.tick += 1
        if self.client is None or self.tick % self.cadence:
            return
        self.replicate()

    def replicate(self) -> bool:
        """Push the packed journal now (also called directly for an
        off-cadence flush, e.g. right before a planned drain)."""
        if self.client is None:
            return False
        packed = self.pack()
        why = 'push not acknowledged'
        try:
            push = getattr(self.client, 'push', None)
            if push is not None:
                ok = bool(push(journal_identity(self.identity), self.tick,
                               packed))
            else:             # bare MemStore (in-process drills, bench)
                self.client.put(journal_identity(self.identity), self.tick,
                                packed)
                ok = True
        except (OSError, ValueError) as error:
            ok, why = False, str(error)
        if ok:
            self.pushes += 1
            if self._push_failed:    # the store healed (e.g. the client
                # redialed a restarted supervisor): say so once, so an
                # incident's log shows WHERE the durability window closed
                logger.info('journal replication for %r recovered at tick '
                            '%d', self.identity, self.tick)
            self._push_failed = False
        else:
            if not self._push_failed:
                logger.warning(
                    'journal replication for %r failed at tick %d (%s); '
                    'serving continues — a kill now replays from the last '
                    'verified copy', self.identity, self.tick, why)
            self._push_failed = True
        return ok


def recover_journal(identity: str, clients: Any) -> tuple[int, list] | None:
    """Fetch and verify the newest journal for ``identity`` from the
    first client that has an intact copy — ``clients`` in preference
    order (local supervisor first, then explicit fallbacks; the
    supervisor's own buddy pull already hides behind the first fetch on a
    replaced host). Returns :meth:`RequestJournal.unpack`'s
    ``(tick, rows)`` or None — a corrupt copy logs and falls through to
    the next client, never restores."""
    for client in clients:
        if client is None:
            continue
        try:
            entry = client.fetch(journal_identity(identity))
        except OSError as error:
            logger.warning('journal fetch for %r failed (%s); trying the '
                           'next replica', identity, error)
            continue
        if entry is None:
            continue
        try:
            return RequestJournal.unpack(entry.blob)
        except JournalCorrupt as error:
            logger.warning('journal for %r at tick %d rejected (%s); '
                           'trying the next replica', identity,
                           getattr(entry, 'step', -1), error)
    return None


# ---------------------------------------------------------------------------
# the router journal — same framing and wire discipline as the request
# journal, different schema: the router's authoritative fleet state


def router_identity(name: str = 'router') -> str:
    """The memstore identity a router's state journal travels under —
    its own namespace (``router:{name}``) beside ``journal:{identity}``,
    riding the identical push/replicate/buddy machinery."""
    return f'router:{name}'


class RouterJournal:
    """The fleet router's crash journal: placements, orphans, in-flight
    handoffs, settled completions, brownout/cooldown flags — everything a
    relaunched (or standby-takeover) router needs to rebuild without
    asking clients to resubmit.

    The schema is the router's business (:meth:`tpusystem.serve.fleet.
    Router.snapshot` builds the state dict, with timestamps converted to
    clock-portable waited-seconds at pack time); this class owns only the
    :class:`RequestJournal` disciplines — digest-framed pickle so a torn
    copy reads as absent (:exc:`JournalCorrupt`), a journal-owned
    monotonic tick so pushes never regress in the store, cadence-gated
    replication with log-once degrade (the journal is a recovery
    accelerator, never allowed to take routing down).
    """

    def __init__(self, name: str = 'router', *, client: Any = None,
                 cadence: int = 1) -> None:
        if cadence < 1:
            raise ValueError(f'cadence must be >= 1 ticks, got {cadence}')
        self.name = name
        self.identity = router_identity(name)
        self.client = client
        self.cadence = cadence
        self.tick = 0                 # monotonic across relaunches (seeded
        self.pushes = 0               # from the recovered journal's tick)
        # lease term, when the router holds one: the push step encodes
        # term * 1_000_000 + tick, so the store's monotonic-step rule
        # fences a deposed router's journal pushes exactly like its
        # lease renewals — a zombie can never overwrite the incumbent's
        # state (the payload still carries the raw tick)
        self.term = 0
        self._push_failed = False

    def pack(self, state: dict) -> bytes:
        payload = pickle.dumps((self.tick, dict(state)),
                               protocol=pickle.HIGHEST_PROTOCOL)
        return _blob_digest(payload).encode('ascii') + b':' + payload

    @staticmethod
    def unpack(data: bytes) -> tuple[int, dict]:
        """``(tick, state)`` from :meth:`pack` bytes; raises
        :exc:`JournalCorrupt` when the digest or shape does not verify."""
        digest, sep, payload = bytes(data).partition(b':')
        if not sep or _blob_digest(payload).encode('ascii') != digest:
            raise JournalCorrupt(
                'router journal bytes failed their digest check — torn or '
                'corrupted copy; treating as absent')
        try:
            tick, state = pickle.loads(payload)
            if not isinstance(state, dict):
                raise TypeError(f'state is {type(state).__name__}, not dict')
        except Exception as error:
            raise JournalCorrupt(
                f'router journal payload does not decode ({error}); '
                f'treating as absent') from error
        return int(tick), state

    def observe_tick(self, state: Callable[[], dict]) -> None:
        """One router step elapsed: advance the tick and replicate at the
        cadence. ``state`` is a thunk so off-cadence ticks never pay the
        snapshot cost."""
        self.tick += 1
        if self.client is None or self.tick % self.cadence:
            return
        self.replicate(state())

    def replicate(self, state: dict) -> bool:
        """Push the packed state now (also called directly for an
        off-cadence flush, e.g. right before a planned handover)."""
        if self.client is None:
            return False
        packed = self.pack(state)
        step = self.term * 1_000_000 + self.tick
        why = 'push not acknowledged'
        try:
            push = getattr(self.client, 'push', None)
            if push is not None:
                ok = bool(push(self.identity, step, packed))
            else:             # bare MemStore (in-process drills, bench)
                self.client.put(self.identity, step, packed)
                ok = True
        except (OSError, ValueError) as error:
            ok, why = False, str(error)
        if ok:
            self.pushes += 1
            if self._push_failed:
                logger.info('router journal for %r recovered at tick %d',
                            self.name, self.tick)
            self._push_failed = False
        else:
            if not self._push_failed:
                logger.warning(
                    'router journal for %r failed at tick %d (%s); routing '
                    'continues — a takeover now rebuilds from the last '
                    'verified copy plus a health sweep', self.name,
                    self.tick, why)
            self._push_failed = True
        return ok


def recover_router_journal(name: str, clients: Any) -> tuple[int, dict] | None:
    """Fetch and verify the newest router journal for ``name`` from the
    first client with an intact copy — ``clients`` in preference order,
    :func:`recover_journal`'s contract: a corrupt copy logs and falls
    through to the next client, never restores."""
    for client in clients:
        if client is None:
            continue
        try:
            entry = client.fetch(router_identity(name))
        except OSError as error:
            logger.warning('router journal fetch for %r failed (%s); '
                           'trying the next replica', name, error)
            continue
        if entry is None:
            continue
        try:
            return RouterJournal.unpack(entry.blob)
        except JournalCorrupt as error:
            logger.warning('router journal for %r at tick %d rejected (%s); '
                           'trying the next replica', name,
                           getattr(entry, 'step', -1), error)
    return None


# ---------------------------------------------------------------------------
# replay


@dataclasses.dataclass
class ReplayReport:
    """What a relaunch recovered: ``replayed`` rows re-prefill
    ``prompt + emitted`` and resume mid-stream ('hot'); ``resubmitted``
    rows were only ever queued and re-enter cold. Either way the final
    completion is token-exact — greedy and seeded sampled alike."""

    replayed: list = dataclasses.field(default_factory=list)
    resubmitted: list = dataclasses.field(default_factory=list)


def replay(scheduler: Any, rows: list, *,
           producer: Any = None) -> ReplayReport:
    """Re-queue journaled rows onto a fresh scheduler, FIFO order
    preserved (the journal packs in submission order). Each row re-enters
    through :meth:`~tpusystem.serve.Scheduler.restore` — original
    deadline accounting kept via the journaled waited-seconds — and is
    narrated as a ``RequestReplayed`` event. A row whose deadline already
    passed during the outage is still queued; the scheduler's ordinary
    expiry retires it with the truthful ``'expired'`` verdict on the next
    step (replay never silently drops). A decode-carrying row replayed
    onto a prefill-only scheduler is a wiring bug, not a recoverable
    fault: the typed :exc:`~tpusystem.serve.disagg.RoleMismatch`
    re-raises, narrated as a ``RoleMismatched`` event first so the
    dashboard's ``serve/role_mismatch`` counter sees it."""
    from tpusystem.observe.events import RequestReplayed, RoleMismatched
    from tpusystem.serve.disagg import RoleMismatch
    result = ReplayReport()
    for request, waited, emitted in rows:
        try:
            scheduler.restore(request, waited=waited, prefix=emitted)
        except RoleMismatch:
            if producer is not None:
                producer.dispatch(RoleMismatched(
                    id=request.id, replica=scheduler.journal.identity
                    if getattr(scheduler, 'journal', None) is not None
                    else 'replay', prefix=len(emitted)))
            raise
        where = 'hot' if emitted else 'cold'
        (result.replayed if emitted else result.resubmitted).append(
            request.id)
        if producer is not None:
            producer.dispatch(RequestReplayed(
                id=request.id, prefix=len(emitted), where=where,
                waited=waited))
    return result


# ---------------------------------------------------------------------------
# the step watchdog


class StepWatchdog:
    """Turn a hung or anomalously slow serving step into a typed verdict.

    Two rungs, both optional:

    * ``stall_after`` — an absolute wall-second bound; any observed step
      at or past it raises :exc:`EngineStalled` (kind ``'stall'``).
    * ``slow_factor`` — an anomaly multiple of the healthy-step EMA
      (bias toward the common case: warmup-gated, and an anomalous step
      is **not** folded into the EMA that detected it — the sentinel's
      discipline). A step at or past ``slow_factor * max(ema, floor)``
      raises kind ``'slow'``. ``floor`` keeps microsecond-scale steps
      from tripping on ordinary scheduler jitter.

    Feed ``observe`` whatever wall time the loop can measure:
    :class:`ServingReplica` feeds whole-tick seconds on its injectable
    clock (exempting the first tick after each rebuild — it pays the
    decode compile and the replay re-prefills, which must not read as
    the next stall); a custom loop can feed the engine's decode-only
    probe (``Engine.last_step_seconds``) to keep admission cost out of
    the EMA entirely.

    ``observe`` is post-hoc — it can only run when the step *returns*.
    For a step that never returns, :meth:`guard` arms a deadman timer
    around the dispatch: if it fires, ``on_stall`` runs (default:
    ``os._exit(42)`` — the restart contract's worker-lost code, so a
    supervised worker is relaunched and replays its journal; docs/
    multihost.md has the table). Tests inject ``timer`` to drive the
    deadman without real waits.
    """

    def __init__(self, *, stall_after: float | None = None,
                 slow_factor: float | None = 8.0, warmup: int = 8,
                 decay: float = 0.9, floor: float = 1e-3,
                 on_stall: Callable[[], None] | None = None,
                 timer: Callable[..., Any] = threading.Timer) -> None:
        if stall_after is None and slow_factor is None:
            raise ValueError('an unarmed watchdog watches nothing: set '
                             'stall_after and/or slow_factor')
        self.stall_after = stall_after
        self.slow_factor = slow_factor
        self.warmup = warmup
        self.decay = decay
        self.floor = floor
        self.on_stall = on_stall
        self._timer = timer
        self.observed = 0
        self.ema = 0.0

    def observe(self, seconds: float) -> None:
        """Fold one step's wall seconds; raises :exc:`EngineStalled` on a
        stall/slow verdict (the anomalous sample is not folded)."""
        if self.stall_after is not None and seconds >= self.stall_after:
            raise EngineStalled(seconds, self.stall_after, 'stall')
        if self.slow_factor is not None and self.observed >= self.warmup:
            threshold = self.slow_factor * max(self.ema, self.floor)
            if seconds >= threshold:
                raise EngineStalled(seconds, threshold, 'slow')
        self.ema = (seconds if not self.observed
                    else self.decay * self.ema + (1 - self.decay) * seconds)
        self.observed += 1

    def guard(self):
        """Deadman context manager for one dispatch: a timer fires
        ``on_stall`` after ``stall_after`` seconds unless the step
        returns first. Requires ``stall_after``."""
        if self.stall_after is None:
            raise ValueError('the deadman guard needs stall_after')
        watchdog = self

        class _Guard:
            def __enter__(self):
                default = lambda: os._exit(LOST_WORKER_EXIT)
                self.timer = watchdog._timer(
                    watchdog.stall_after, watchdog.on_stall or default)
                self.timer.daemon = True
                self.timer.start()
                return self

            def __exit__(self, *exc):
                self.timer.cancel()
                return False

        return _Guard()


# ---------------------------------------------------------------------------
# admission-control watermarks


@dataclasses.dataclass(frozen=True)
class Watermarks:
    """High/low queue-depth watermarks for typed load shedding.

    When the queue grows past ``high``, the scheduler sheds queued
    requests down to ``low`` (hysteresis: shedding every step would
    thrash at the boundary), choosing victims by **deadline slack** —
    the request that will expire anyway is shed first; requests without
    deadlines shed last, newest-first, so the oldest waiters keep their
    FIFO claim. Active rows are never shed: their prefill is sunk cost
    and they are closest to done. Each shed is a typed ``LoadShed``
    event and crossing the watermarks toggles ``Backpressure`` — the
    upstream router's signal to route elsewhere."""

    high: int
    low: int

    def __post_init__(self) -> None:
        if self.low < 0 or self.high < max(1, self.low):
            raise ValueError(
                f'watermarks need 0 <= low <= high (and high >= 1), got '
                f'high={self.high} low={self.low}')

    def excess(self, depth: int) -> int:
        """How many queued requests to shed at this depth (0 = none)."""
        return depth - self.low if depth > self.high else 0


# ---------------------------------------------------------------------------
# the supervised replica loop


class ServingReplica:
    """One serving replica under the failover discipline.

    Wraps a scheduler *factory* (``build() -> Scheduler`` — a fresh
    engine each call; params and module are closed over) with the
    journal, the watchdog, and the relaunch path:

    * at construction, a recoverable journal (this replica was killed
      and relaunched — the :class:`~tpusystem.parallel.Supervisor`
      restart contract) is replayed before any new traffic
      (``recovered`` is the witness);
    * each :meth:`step` runs one scheduler tick, feeds the watchdog, and
      replicates the journal at its cadence;
    * an :exc:`EngineStalled` verdict — from the watchdog or raised
      inside the step by a wedged engine — triggers :meth:`relaunch`:
      the old engine is abandoned, a fresh one is built, and the journal
      (which already holds this tick's tokens — hooks run inside the
      step) replays. ``EngineRestarted`` narrates cause and cost.

    ``fallbacks`` are extra journal read clients tried after ``client``
    (e.g. the buddy's store in an in-process drill; on a real pod the
    supervisor's replaced-host pull already hides behind ``client``).
    ``fault`` is the chaos seam: a callable invoked with the 1-based
    upcoming tick before each step (``DieAtStep`` / ``StalledStep``).

    ``deadman=True`` additionally arms :meth:`StepWatchdog.guard` around
    every watched tick, so a step that NEVER returns (a device hang —
    the case post-hoc ``observe`` can't see) fires ``on_stall`` (default
    ``os._exit(42)``) and the :class:`~tpusystem.parallel.Supervisor`
    relaunches the worker. Opt-in, because the default action kills the
    process: it belongs on supervised workers, not in-process embeddings
    (and the first tick after each build is exempt, like ``observe`` —
    a decode compile must not read as a hang).

    One clock rules everything: the replica, its journal, and the
    scheduler ``build()`` constructs must share ``clock`` — journaled
    waited-seconds subtract the scheduler's timestamps from the
    replica's clock, so a mismatch would backdate replays by garbage.
    Enforced at construction.
    """

    def __init__(self, build: Callable[[], Any], *, identity: str = 'serve',
                 client: Any = None, fallbacks: tuple = (),
                 cadence: int = 1, watchdog: StepWatchdog | None = None,
                 deadman: bool = False, producer: Any = None,
                 clock: Callable[[], float] = time.monotonic,
                 fault: Callable[[int], None] | None = None,
                 recorder: Any = None, role: str = 'both') -> None:
        if deadman and (watchdog is None or watchdog.stall_after is None):
            raise ValueError('deadman=True needs a watchdog with '
                             'stall_after set (the timer interval)')
        if role not in ('both', 'prefill', 'decode'):
            raise ValueError(f"role must be 'both', 'prefill' or 'decode', "
                             f'got {role!r}')
        self._build = build
        self.role = role
        # placement policy, not capability: a 'decode' replica keeps its
        # full prefill programs (recovery re-prefills journaled rows on
        # it); only 'prefill' changes the scheduler contract, and that
        # is build()'s job (Scheduler(prefill_only=True)) — enforced in
        # _boot so a mis-built replica fails at construction, not when
        # the first strip goes missing
        self.identity = identity
        self.client = client
        self.fallbacks = tuple(fallbacks)
        self.cadence = cadence
        self.watchdog = watchdog
        self.deadman = deadman
        self.producer = producer
        self._clock = clock
        self._fault = fault
        # the black box (observe.FlightRecorder | None): every tick's
        # admissions/emissions land in its write-ahead ring, so a SIGKILL
        # leaves a post-mortem whose tail matches the journal the
        # Supervisor recovers; an EngineStalled verdict dumps explicitly
        self.recorder = recorder
        self.recovered = False
        self.relaunches = 0
        self.results: dict[str, Any] = {}
        self.report: ReplayReport | None = None
        self._boot(cause=None)

    # ------------------------------------------------------------ boot

    def _boot(self, cause: str | None,
              live: RequestJournal | None = None) -> None:
        started = self._clock()
        self.scheduler = self._build()
        prefill_only = getattr(self.scheduler, 'prefill_only', False)
        if prefill_only != (self.role == 'prefill'):
            raise ValueError(
                f'replica role {self.role!r} but build() constructed a '
                f'scheduler with prefill_only={prefill_only} — the role '
                'and the scheduler contract must agree')
        scheduler_clock = getattr(self.scheduler, '_clock', self._clock)
        if scheduler_clock is not self._clock:
            raise ValueError(
                'the replica and the scheduler its build() constructs must '
                'share one clock — journaled waited-seconds subtract '
                'scheduler timestamps from the replica clock, and a '
                'mismatch backdates every replay by garbage; pass the same '
                'clock to ServingReplica(clock=) and Scheduler(clock=)')
        journal = RequestJournal(self.identity, client=self.client,
                                 cadence=self.cadence, clock=self._clock)
        recovered = None
        if live is not None:
            # in-process relaunch: the live journal survived with this
            # process and is at least as fresh as any replicated copy
            # (pushes lag it by up to cadence-1 ticks) — replay from it,
            # round-tripped through pack/unpack so the re-entry runs the
            # exact path a cross-process recovery takes. This is also
            # what makes a client-less replica (journaling only in RAM)
            # lossless across a watchdog relaunch.
            recovered = RequestJournal.unpack(live.pack())
        if recovered is None:
            recovered = recover_journal(self.identity,
                                        (self.client, *self.fallbacks))
        self.scheduler.journal = journal
        self._fresh = True            # watchdog holds off the build tick
        report = ReplayReport()
        if recovered is not None:
            tick, rows = recovered
            journal.tick = tick       # pushes stay monotonic in the store
            report = replay(self.scheduler, rows, producer=self.producer)
            self.recovered = True
        self.report = report
        if self.recorder is not None and (cause is not None
                                          or recovered is not None):
            self.recorder.note('engine-restarted', cause=cause or 'relaunch',
                               replayed=len(report.replayed),
                               resubmitted=len(report.resubmitted))
        if cause is not None or recovered is not None:
            seconds = self._clock() - started
            self._dispatch_restart(cause or 'relaunch', report, seconds)

    def _dispatch_restart(self, cause: str, report: ReplayReport,
                          seconds: float) -> None:
        logger.info(
            'serving replica %r restarted (%s): %d replayed, %d '
            'resubmitted in %.3fs', self.identity, cause,
            len(report.replayed), len(report.resubmitted), seconds)
        if self.producer is not None:
            from tpusystem.observe.events import EngineRestarted
            self.producer.dispatch(EngineRestarted(
                cause=cause, replayed=len(report.replayed),
                resubmitted=len(report.resubmitted), seconds=seconds))

    # ------------------------------------------------------------ serve

    def submit(self, request: Any) -> None:
        self.scheduler.submit(request)

    def relaunch(self, cause: str) -> None:
        """Abandon the engine and rebuild from the journal — the
        in-process form of the supervised kill/relaunch cycle (one
        process, fresh device state; the subprocess form is the
        Supervisor's job and rides the same journal). The live journal
        is handed to the rebuild directly: in-process it is strictly
        fresher than any replicated copy, so a replica journaling only
        in RAM (no client) still loses nothing."""
        self.relaunches += 1
        self.results.update(self.scheduler.results)
        self._boot(cause=cause, live=self.scheduler.journal)

    def step(self):
        """One supervised tick: chaos seam, scheduler step, watchdog
        verdict, results merge. Returns the scheduler's Tick, or None
        when the step ended in a relaunch (the replayed work surfaces on
        subsequent ticks).

        The watchdog observes whole-tick wall time on the replica's own
        (injectable) clock — EXCEPT the first tick after each (re)build,
        which pays the fresh engine's decode compile and, after a
        relaunch, every replayed row's re-prefill: holding the watchdog
        off that tick keeps one genuine stall from cascading into a
        relaunch loop where every recovery tick reads as the next stall.
        Idle ticks (nothing admitted or emitted) are not folded either —
        near-zero samples would drag the EMA under real decode cost."""
        started = self._clock()
        try:
            if self._fault is not None:
                self._fault(self.scheduler.steps + 1)
            if self.deadman and not self._fresh:
                with self.watchdog.guard():    # a hang exits for restart
                    tick = self.scheduler.step()
            else:
                tick = self.scheduler.step()
            if self.watchdog is not None:
                if self._fresh:
                    self._fresh = False
                elif tick.emitted or tick.admitted:
                    self.watchdog.observe(self._clock() - started)
        except EngineStalled as stall:
            logger.warning('serving replica %r: %s', self.identity, stall)
            if self.recorder is not None:   # the watchdog verdict is a
                # post-mortem moment even though the process survives:
                # dump what the engine saw BEFORE the rebuild replaces it
                self.recorder.note('engine-stalled', kind=stall.kind,
                                   seconds=round(stall.seconds, 6),
                                   threshold=round(stall.threshold, 6))
                self.recorder.dump(reason='engine-stalled')
            self.relaunch('stalled')
            return None
        self.results.update(self.scheduler.results)
        if self.recorder is not None:
            self.recorder.note(
                'tick', step=self.scheduler.steps,
                admitted={request.id: admission.token
                          for request, admission, _ in tick.admitted},
                emitted=dict(tick.emitted),
                completed=[completion.request.id
                           for completion in tick.completed])
        return tick

    @property
    def idle(self) -> bool:
        return self.scheduler.idle

    def run_until_idle(self, max_steps: int = 10_000) -> dict:
        """Step until every queued and seated request completes; returns
        request id -> Completion (merged across relaunches)."""
        for _ in range(max_steps):
            if self.scheduler.idle:
                self.results.update(self.scheduler.results)
                return self.results
            self.step()
        raise RuntimeError(f'replica did not drain in {max_steps} steps')
