"""Serving tier: a continuous-batching inference engine on the bus.

The single biggest step from "can train at scale" to "can serve
millions of users" (ROADMAP item 1): a decode program that compiles
**once** and whose batch membership changes every step without
retracing. Orca's iteration-level scheduling and vLLM's PagedAttention
block-table KV management, built on machinery this repo already had —
per-row decode cursors (:mod:`tpusystem.train.cursors`), bucketed
cache attention (:func:`tpusystem.ops.attention.paged_attention`), and
the PR-7 weight-streaming levers.

Layers, bottom up:

* :class:`PagedKVCache` (+ ``adopt_prefill`` / ``write_tables``) — the
  block pool free-list and per-sequence block tables, with optional
  refcounted radix prefix sharing (``share_prefix=True``)
  (:mod:`tpusystem.serve.kvcache`);
* :class:`Engine` — the fixed-shape compiled decode step with
  admit/evict row churn; ``decode_impl='fused'`` routes it through the
  Pallas fused decode chain and ``draft_module=`` turns rows into
  speculative draft/verify groups. Per-request :class:`SamplingParams`
  ride the same compiled step as batched device arrays (seeded
  counter-based sampling + grammar vocab masks, compile-once across
  param churn; :exc:`UnseededSampling` is the typed refusal of the one
  non-reproducible configuration) (:mod:`tpusystem.serve.engine`);
* :class:`Scheduler` / :class:`Request` — prefill/decode phase packing
  under a token budget (:mod:`tpusystem.serve.scheduler`);
* :class:`InferenceService` — the command/event bus front door
  (:mod:`tpusystem.serve.service`);
* the failover layer (:mod:`tpusystem.serve.failover`) — the journaled
  request log, token-prefix replay, step watchdog, and watermark load
  shedding that let a replica survive kill, hang, and overload
  (:class:`ServingReplica` is the supervised loop; docs/serving.md
  "Surviving engine failure");
* the fleet tier (:mod:`tpusystem.serve.fleet`) — a health-checked
  :class:`Router` over N replicas: least-loaded routing with timeout /
  retry / hedging, journal handoff onto the survivors when a replica
  dies, fleet-scope watermark shedding with brownout, and
  traffic-driven autoscale through the supervisor/elastic resize seam
  (docs/serving.md "A fleet of replicas");
* disaggregated prefill/decode (:mod:`tpusystem.serve.disagg`) — a
  ``role='prefill'`` replica runs only admission prefill and exports
  each request's KV strips (``Engine.export_prefill``); the router
  ships them over the chunked digest-verified blob plane under
  ``kv:{request}`` (:class:`KVHandoff` / :class:`KVStripStore`) to a
  decode replica that seats them through ``Engine.admit_prefilled`` —
  the existing ``adopt_prefill``/``write_tables`` seam. Engines also
  take ``mesh=``/``schedule=`` to tensor-shard the compiled steps over
  the ``'model'`` axis (GSPMD; token-exact vs single-device)
  (docs/serving.md "Disaggregated prefill/decode").
"""

from tpusystem.serve.certify import (CertifyReport, FleetHarness,
                                     certify_fleet)
from tpusystem.serve.disagg import (HandoffCorrupt, KVHandoff, KVStripStore,
                                    RoleMismatch, fetch_handoff,
                                    kv_namespace, pack_handoff,
                                    unpack_handoff)
from tpusystem.serve.engine import (Admission, Engine, SamplingParams,
                                    Saturated, StepReport,
                                    UnseededSampling,
                                    engine_unsupported_reason,
                                    prefill_bucket)
from tpusystem.serve.failover import (EngineStalled, JournalCorrupt,
                                      ReplayReport, RequestJournal,
                                      RouterJournal, ServingReplica,
                                      StepWatchdog, Watermarks,
                                      journal_identity, recover_journal,
                                      recover_router_journal, replay,
                                      router_identity)
from tpusystem.serve.fleet import (AutoscalePolicy, FleetSaturated,
                                   FleetTick, NoHealthyReplica,
                                   ReplicaDead, ReplicaHandle, RoutePolicy,
                                   Router, RouterFenced, RouterLease)
from tpusystem.serve.kvcache import (TRASH_BLOCK, PagedKVCache,
                                     adopt_prefill, pool_shardings,
                                     write_tables)
from tpusystem.serve.scheduler import (Completion, QueueFull, Request,
                                       Scheduler, Tick, serve_levers)
from tpusystem.serve.service import FleetClient, InferenceService

__all__ = ['Engine', 'Admission', 'StepReport', 'Saturated',
           'SamplingParams', 'UnseededSampling',
           'engine_unsupported_reason', 'prefill_bucket',
           'PagedKVCache', 'TRASH_BLOCK', 'adopt_prefill', 'write_tables',
           'Scheduler', 'Request', 'Completion', 'Tick', 'serve_levers',
           'QueueFull', 'InferenceService', 'FleetClient',
           'EngineStalled', 'JournalCorrupt', 'RequestJournal',
           'ReplayReport', 'ServingReplica', 'StepWatchdog', 'Watermarks',
           'journal_identity', 'recover_journal', 'replay',
           'RouterJournal', 'router_identity', 'recover_router_journal',
           'Router', 'RouterFenced', 'RouterLease',
           'ReplicaHandle', 'RoutePolicy', 'AutoscalePolicy',
           'FleetTick', 'ReplicaDead', 'NoHealthyReplica', 'FleetSaturated',
           'KVHandoff', 'KVStripStore', 'HandoffCorrupt', 'RoleMismatch',
           'kv_namespace', 'pack_handoff', 'unpack_handoff', 'fetch_handoff',
           'pool_shardings',
           'CertifyReport', 'FleetHarness', 'certify_fleet']
