"""The request bus front-end: serving as a message-driven service.

Requests fan in as commands over the TorchSystem-style service layer
(:class:`tpusystem.services.Service` — ``'submit'`` / ``'cancel'`` by
name, so a CLI, REST surface, or the multihost control plane can drive
the engine without importing it), and the request lifecycle fans out as
domain events on a :class:`tpusystem.services.Producer`:
``RequestAdmitted`` / ``RequestEvicted`` / ``RequestCompleted`` /
``ServeStepped`` / ``TokenStreamed`` (:mod:`tpusystem.observe.events`).
Streaming requests (``submit(..., on_token=)``) additionally get every
token delivered incrementally the step it materializes. The TensorBoard
consumer charts queue depth, time-to-first-token, and tokens/sec off
those events with zero engine code — the observability discipline every
other subsystem in this framework follows.

Hot-path rule: every event payload is an already-materialized host value
(ints, floats, token lists) — consumers never see device arrays.

:class:`FleetClient` is the fleet-level front door for callers that must
survive the *router* dying (the PR-19 no-single-point-of-failure
contract): it resolves "which router is serving right now" per call,
redials the dead-router signatures with capped exponential backoff +
jitter, and resubmits by request-id — idempotent, because
:meth:`~tpusystem.serve.fleet.Router.submit` treats a known id as a
no-op and the router journal carries settled results across a takeover.
"""

from __future__ import annotations

import random
import time

from tpusystem.observe.events import (Backpressure, LoadShed,
                                      RequestAdmitted, RequestCompleted,
                                      RequestEvicted, RequestExpired,
                                      ServeStepped, TokenStreamed)
from tpusystem.serve.engine import Engine
from tpusystem.serve.fleet import RouterFenced
from tpusystem.serve.scheduler import Request, Scheduler, serve_levers
from tpusystem.services.prodcon import Producer
from tpusystem.services.service import Service


class InferenceService:
    """Continuous-batching inference behind a command/event bus.

    Composes an :class:`~tpusystem.serve.Engine` (built with
    :func:`~tpusystem.serve.serve_levers` defaults — int8 weight
    streaming on TPU) under a :class:`~tpusystem.serve.Scheduler`, and
    narrates every lifecycle transition on ``producer``. Drive it
    directly (:meth:`submit` / :meth:`step` / :meth:`run_until_idle`) or
    by name through :attr:`service` (``handle('submit', request)``).
    """

    def __init__(self, module, params, *, producer: Producer | None = None,
                 rows: int = 4, block_size: int = 16,
                 blocks: int | None = None, prefill_budget: int = 512,
                 clock=time.monotonic, max_queued: int | None = None,
                 watermarks=None, tracer=None, **levers) -> None:
        knobs = {**serve_levers(), **levers}
        self.engine = Engine(module, params, rows=rows,
                             block_size=block_size, blocks=blocks, **knobs)
        self.scheduler = Scheduler(self.engine,
                                   prefill_budget=prefill_budget,
                                   clock=clock, max_queued=max_queued,
                                   watermarks=watermarks, tracer=tracer)
        self.producer = producer or Producer()
        self._clock = clock          # tok/s runs on the SAME injectable
        self._emitted = 0            # clock as the scheduler's deadlines
        self._started = None         # first-step wall clock, for tok/s
        self._backpressure = False   # last narrated watermark state
        self._streams: dict = {}     # request id -> on_token callback
        self._stream_index: dict = {}  # request id -> next stream index
        self.service = Service('serve')
        self.service.handler(self._named('submit', self.submit))
        self.service.handler(self._named('cancel', self.cancel))

    @staticmethod
    def _named(name, bound):
        # Service registers by function __name__; bound methods carry the
        # mangled method name, so wrap with the public command name
        def command(*arguments):
            return bound(*arguments)
        command.__name__ = name
        return command

    # -------------------------------------------------------------- intake

    def submit(self, request: Request, on_token=None) -> None:
        """Queue a request (command name ``'submit'``).

        ``on_token`` turns the request streaming: called as
        ``on_token(index, token)`` the step each token materializes —
        index 0 is the first token (delivered at admission, so its
        latency IS the TTFT the admission event charts), later indices
        arrive one per decode step (a burst per step under speculative
        rows). A cancel, deadline expiry, or completion ends the stream;
        tokens already delivered stay delivered (a mid-stream ``expired``
        verdict is truthful about the partial output). Each token is
        also narrated as :class:`~tpusystem.observe.events.TokenStreamed`
        for streaming requests."""
        self.scheduler.submit(request)
        if on_token is not None:
            self._streams[request.id] = on_token
            self._stream_index[request.id] = 0

    def cancel(self, request_id: str) -> str | None:
        """Cancel a request (command name ``'cancel'``); an active one is
        evicted mid-decode and narrated as ``RequestEvicted``. A
        streaming request's ``on_token`` just stops being called —
        tokens delivered before the cancel landed stay delivered."""
        where = self.scheduler.cancel(request_id)
        self._close_stream(request_id)
        if where == 'active':
            completion = self.scheduler.results[request_id]
            self.producer.dispatch(RequestEvicted(
                id=request_id, produced=len(completion.tokens),
                reason='cancelled'))
        return where

    # ------------------------------------------------------------ streaming

    def _deliver(self, request_id: str, tokens) -> None:
        stream = self._streams.get(request_id)
        if stream is None:
            return
        for token in tokens:
            index = self._stream_index[request_id]
            self._stream_index[request_id] = index + 1
            stream(index, int(token))
            self.producer.dispatch(TokenStreamed(
                id=request_id, index=index, token=int(token)))

    def _close_stream(self, request_id: str) -> None:
        self._streams.pop(request_id, None)
        self._stream_index.pop(request_id, None)

    # ------------------------------------------------------------- serving

    def step(self) -> None:
        """One scheduler iteration, narrated on the bus."""
        if self._started is None:
            self._started = self._clock()
        tick = self.scheduler.step()
        # shed/backpressure narrate the depth that TRIGGERED them
        # (tick.shed_depth, pre-shed) — the final queue_depth is
        # post-admission and would under-report the overload
        for completion, slack in tick.shed:
            self._close_stream(completion.request.id)
            self.producer.dispatch(LoadShed(
                id=completion.request.id,
                produced=len(completion.tokens),
                queue_depth=tick.shed_depth, slack=slack))
        if self.scheduler.backpressure != self._backpressure:
            self._backpressure = self.scheduler.backpressure
            self.producer.dispatch(Backpressure(
                engaged=self._backpressure,
                queue_depth=(tick.shed_depth if self._backpressure
                             and tick.shed_depth is not None
                             else tick.queue_depth)))
        for completion, where in tick.expired:
            self.producer.dispatch(RequestExpired(
                id=completion.request.id, where=where,
                produced=len(completion.tokens),
                waited=completion.seconds))
        for request, admission, ttft in tick.admitted:
            self.producer.dispatch(RequestAdmitted(
                id=request.id, row=admission.row,
                prompt_tokens=len(request.prompt), ttft=ttft,
                queue_depth=tick.queue_depth))
            # stream the first token NOW — its delivery latency is the
            # ttft the admission event just charted
            self._deliver(request.id, [admission.token])
        for request_id, tokens in tick.emitted.items():
            self._deliver(request_id, tokens)
        for completion, _ in tick.expired:
            self._close_stream(completion.request.id)
        for completion in tick.completed:
            self._close_stream(completion.request.id)
            if completion.reason != 'cancelled':
                self.producer.dispatch(RequestCompleted(
                    id=completion.request.id,
                    produced=len(completion.tokens),
                    reason=completion.reason,
                    seconds=completion.seconds))
        step_tokens = sum(len(tokens) for tokens in tick.emitted.values())
        self._emitted += len(tick.admitted) + step_tokens
        elapsed = self._clock() - self._started
        self.producer.dispatch(ServeStepped(
            step=self.scheduler.steps, active=tick.active,
            queue_depth=tick.queue_depth, emitted=step_tokens,
            tokens_per_sec=self._emitted / elapsed if elapsed else 0.0,
            sampled=self.engine.sampled_rows))

    def run_until_idle(self, max_steps: int = 10_000) -> dict:
        """Step until every request completes; returns request id ->
        :class:`~tpusystem.serve.Completion`."""
        for _ in range(max_steps):
            if self.scheduler.idle:
                return self.scheduler.results
            self.step()
        raise RuntimeError(f'serving did not drain in {max_steps} steps')

    @property
    def results(self) -> dict:
        return self.scheduler.results


class FleetClient:
    """A fleet client that survives router death (warm-standby redial).

    ``resolve() -> Router`` answers "who is serving right now" — after a
    takeover that is a *different* router object (or process); while the
    standby is still fencing it may raise the same dead signatures a
    direct call would. Every operation resolves fresh, and any
    dead-router signature (``ConnectionError`` / ``OSError`` — the
    socket death of a killed router — or :exc:`~tpusystem.serve.fleet.
    RouterFenced` from a not-yet-deposed zombie) retries with capped
    exponential backoff + jitter (seeded, so drills replay identically
    and a herd of clients decorrelates instead of redialing in phase).

    Retrying is safe because submission is **request-id idempotent** at
    the router: a resubmit of a settled request returns ``'settled'``
    (read :meth:`result`), an in-flight one returns its current
    placement, and the router journal carries both tables across the
    takeover — a client can never double-run a request by redialing.

    ``sleep`` is injectable (the tier-1 drills run zero real sleeps);
    redials exhausted raises ``ConnectionError`` — the typed "no router
    ever came back" verdict.
    """

    _DEAD = (ConnectionError, OSError, RouterFenced)

    def __init__(self, resolve, *, max_redials: int = 8,
                 backoff_base: float = 0.05, backoff_cap: float = 2.0,
                 jitter: float = 0.25, seed: int = 0,
                 sleep=time.sleep) -> None:
        if max_redials < 0 or backoff_base <= 0 or backoff_cap < backoff_base:
            raise ValueError('need max_redials >= 0 and 0 < backoff_base '
                             '<= backoff_cap')
        self._resolve = resolve
        self.max_redials = max_redials
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.jitter = jitter
        self._rng = random.Random(seed)
        self._sleep = sleep
        self.redials = 0             # takeover-visibility counter

    def _backoff(self, attempt: int) -> float:
        delay = min(self.backoff_cap, self.backoff_base * 2 ** attempt)
        return delay * (1.0 + self.jitter * self._rng.random())

    def _call(self, op):
        last = None
        for attempt in range(self.max_redials + 1):
            if attempt:
                self.redials += 1
                self._sleep(self._backoff(attempt - 1))
            try:
                return op(self._resolve())
            except self._DEAD as error:
                last = error
        raise ConnectionError(
            f'router unreachable after {self.max_redials} redials — no '
            f'standby took over') from last

    def submit(self, request) -> str:
        """Route the request on the current router; returns its
        placement, or ``'settled'`` when a redial finds it already
        completed (read :meth:`result`)."""
        return self._call(lambda router: router.submit(request))

    def cancel(self, request_id: str):
        return self._call(lambda router: router.cancel(request_id))

    def result(self, request_id: str):
        """The request's Completion once settled, None while in flight
        — served from the idempotency table the router journal carries
        across takeovers."""
        return self._call(lambda router: router.results.get(request_id))
