"""Paged KV-cache management: the free-list, block tables, and the
device-side admission writes.

The device layout lives in :func:`tpusystem.ops.attention.paged_attention`
(one shared pool of ``num_blocks * block_size`` token slots per layer,
per-row block tables mapping logical blocks to physical ones). This module
is the **host-side authority** over that layout: which physical blocks are
free, which row owns which blocks, and what every row's table says —
:class:`PagedKVCache` — plus the two jitted cache edits the engine uses to
change batch membership without retracing its decode step:

* :func:`adopt_prefill` scatters a prefilled contiguous KV strip into a
  row's allocated blocks (one program total — admission is a pair of
  device calls, never a reshape of the pool);
* :func:`write_tables` replaces every layer's ``table`` cache leaf with
  the host authority's current map (evictions and admissions both reduce
  to this table edit).

Physical block 0 is the reserved **trash block**: unmapped table entries
point there, so a retired row's dead writes (the fixed-shape step keeps
computing every row) land in trash instead of a live row's blocks.

``share_prefix=True`` adds SGLang/RadixAttention-style **prefix
sharing** on top of the same pool: every allocated block carries a
refcount, and each block whose span is fully covered by its row's
prompt registers in a radix index keyed by the exact token prefix it
caches. A later admission whose prompt starts with the same tokens
adopts those blocks by reference (refcount increment, no copy, no
recompute) and allocates fresh blocks only for its uncached suffix.
Retirement decrements refcounts; a block leaves the live set only at
refcount zero — and a zero-ref block that still holds registered prefix
content parks in a warm LRU cache (it counts as free capacity and is
reclaimed, content dropped, when the free list runs dry) so back-to-back
traffic on one system prompt keeps hitting. The aliasing contract
tightens rather than weakens: two rows may share a physical block ONLY
when their prompts agree on every token the block caches, shared blocks
are never written (prompt KV is write-once; decode writes always start
past the shared region because sharing stops at whole prompt-covered
blocks), and the trash-block discipline is unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

TRASH_BLOCK = 0


class PagedKVCache:
    """Host-side free-list + block-table authority for the paged pool.

    Pure bookkeeping (numpy only — unit-testable without a device):
    ``admit`` allocates the blocks covering a sequence's whole token
    budget up front (prompt + generation, so decode never stalls on a
    mid-stream allocation), ``evict`` returns them to the free list and
    resets the row's table to trash. The device copies of the tables are
    refreshed from :attr:`table` via :func:`write_tables`.

    ``share_prefix=True`` enables the refcounted radix index (module
    docstring): ``admit`` then takes the row's prompt tokens, reuses
    every cached whole-block prefix match by reference, and
    :meth:`shared_tokens` tells the engine how many leading positions
    arrived pre-filled (so it can skip recomputing them and must NOT
    scatter over them). Default off — the unshared accounting below is a
    pinned contract of its own.
    """

    def __init__(self, rows: int, blocks: int, block_size: int,
                 max_seq: int, share_prefix: bool = False) -> None:
        if max_seq % block_size:
            raise ValueError(f'max_seq ({max_seq}) must be a multiple of '
                             f'block_size ({block_size})')
        if blocks < 2:
            raise ValueError('need at least 2 blocks (block 0 is the '
                             'reserved trash block)')
        self.rows, self.blocks, self.block_size = rows, blocks, block_size
        self.max_blocks = max_seq // block_size
        self.max_seq = max_seq
        self.share_prefix = share_prefix
        # LIFO free list over blocks 1..blocks-1 (0 is trash)
        self._free = list(range(blocks - 1, 0, -1))
        self._owned: dict[int, list[int]] = {}
        self.table = np.full((rows, self.max_blocks), TRASH_BLOCK, np.int32)
        # --- sharing state (unused when share_prefix is False) ---
        self._refs: dict[int, int] = {}        # live block -> refcount >= 1
        self._cached: dict[int, tuple] = {}    # zero-ref warm block -> key
        #                                        (insertion order = LRU)
        self._keys: dict[tuple, int] = {}      # prefix tokens -> block
        self._block_key: dict[int, tuple] = {} # block -> its registered key
        self._shared_len: dict[int, int] = {}  # row -> adopted prefix tokens

    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: the free list plus (under sharing) warm
        zero-ref prefix blocks, which are reclaimed on demand."""
        return len(self._free) + len(self._cached)

    @property
    def live_blocks(self) -> int:
        """Blocks currently referenced by at least one seated row."""
        if self.share_prefix:
            return len(self._refs)
        return sum(len(ids) for ids in self._owned.values())

    def blocks_for(self, tokens: int) -> int:
        """Physical blocks covering ``tokens`` cache slots."""
        return -(-tokens // self.block_size)

    def can_admit(self, tokens: int, prompt=None) -> bool:
        needed = self.blocks_for(tokens)
        if needed > self.max_blocks:
            return False
        if self.share_prefix and prompt is not None:
            cached, _ = self.adoptable_prefix(prompt)
            needed -= cached // self.block_size
        return needed <= self.free_blocks

    # ------------------------------------------------------- radix index

    def match_prefix(self, prompt) -> tuple[int, list[int]]:
        """Longest cached whole-block prefix of ``prompt``:
        ``(cached_tokens, block_ids)``. A block matches only when the
        index holds its EXACT token prefix (the radix key is the tokens
        themselves — no hash collisions, no partial blocks), so two rows
        can alias a block only through identical prompt prefixes."""
        if not self.share_prefix:
            return 0, []
        prompt = [int(t) for t in prompt]
        ids = []
        for k in range(min(len(prompt) // self.block_size, self.max_blocks)):
            key = tuple(prompt[:(k + 1) * self.block_size])
            block = self._keys.get(key)
            if block is None:
                break
            ids.append(block)
        return len(ids) * self.block_size, ids

    def adoptable_prefix(self, prompt) -> tuple[int, list[int]]:
        """:meth:`match_prefix` capped so at least ONE prompt token stays
        uncached: admission always prefills a non-empty suffix (its
        last-token logits are the request's first emitted token, and that
        token's KV write must land in a private block, never a shared
        one), so the match runs against ``prompt[:-1]``."""
        prompt = list(prompt)
        if len(prompt) < 2:
            return 0, []
        return self.match_prefix(prompt[:len(prompt) - 1])

    def shared_tokens(self, row: int) -> int:
        """How many leading positions of ``row`` were adopted from the
        radix index at admission (0 without sharing)."""
        return self._shared_len.get(row, 0)

    def _allocate(self) -> int:
        """One fresh block: the free list first, else reclaim the
        least-recently-parked warm prefix block (its content — and its
        radix key — are dropped; refcounted LIVE blocks are never
        touched)."""
        if self._free:
            return self._free.pop()
        block, key = next(iter(self._cached.items()))
        del self._cached[block]
        del self._keys[key]
        del self._block_key[block]
        return block

    def _acquire(self, block: int) -> None:
        """Take one reference on a matched block (reviving it from the
        warm cache if it sat at refcount zero)."""
        if block in self._cached:
            del self._cached[block]
        self._refs[block] = self._refs.get(block, 0) + 1

    def _register(self, ids: list, prompt) -> None:
        """Index every block whose span the prompt fully covers. Those
        blocks are write-once by construction: decode writes start at
        ``len(prompt)``, which lies past every fully-covered block. A
        chunk whose key is already indexed keeps the existing holder
        (one canonical copy per prefix)."""
        prompt = [int(t) for t in prompt]
        for k, block in enumerate(ids):
            if (k + 1) * self.block_size > len(prompt):
                break
            key = tuple(prompt[:(k + 1) * self.block_size])
            if key not in self._keys and block not in self._block_key:
                self._keys[key] = block
                self._block_key[block] = key

    # --------------------------------------------------------- admission

    def admit(self, row: int, tokens: int, prompt=None) -> np.ndarray:
        """Allocate ``tokens`` worth of blocks to ``row`` and return the
        ``[max_seq]`` physical token-slot map of the row (positions past
        the allocation map to trash) — the scatter index
        :func:`adopt_prefill` writes the prefilled KV through.

        With ``share_prefix`` and a ``prompt``, the leading blocks come
        from the radix index where it matches (refcount increment — the
        caller must then mask its adoption scatter below
        :meth:`shared_tokens` so shared blocks stay write-once) and the
        prompt's own fully-covered blocks are registered for future
        admissions."""
        if row in self._owned:
            raise ValueError(f'row {row} already owns blocks — evict first')
        needed = self.blocks_for(tokens)
        if needed > self.max_blocks:
            raise ValueError(f'{tokens} tokens need {needed} blocks, over '
                             f'the per-row table width {self.max_blocks}')
        shared_ids: list[int] = []
        if self.share_prefix and prompt is not None:
            _, shared_ids = self.adoptable_prefix(prompt)
            shared_ids = shared_ids[:needed]
        if needed - len(shared_ids) > self.free_blocks:
            raise ValueError(
                f'{needed - len(shared_ids)} blocks needed, '
                f'{self.free_blocks} free — admission must wait (queue, '
                f'do not crash)')
        for block in shared_ids:
            self._acquire(block)
        fresh = [self._allocate() for _ in range(needed - len(shared_ids))]
        if self.share_prefix:
            for block in fresh:
                self._refs[block] = 1
        ids = shared_ids + fresh
        self._owned[row] = ids
        self.table[row, :needed] = ids
        self.table[row, needed:] = TRASH_BLOCK
        if self.share_prefix and prompt is not None:
            self._shared_len[row] = len(shared_ids) * self.block_size
            self._register(ids, prompt)
        return self.slots(row)

    def slots(self, row: int) -> np.ndarray:
        """``[max_seq]`` physical token slot of each logical position of
        ``row`` under its current table (trash wherever unmapped)."""
        positions = np.arange(self.max_seq)
        physical = self.table[row, positions // self.block_size]
        return (physical * self.block_size
                + positions % self.block_size).astype(np.int32)

    def adoption_slots(self, row: int) -> np.ndarray:
        """:meth:`slots` with the shared prefix redirected to trash: the
        adoption scatter's index for a row admitted through the radix
        index. Shared blocks already hold the prefix KV and are
        write-once, so the positions they cache must scatter their
        (identical, or resume-zeroed) strip values into the trash block
        instead."""
        slots = self.slots(row)
        shared = self.shared_tokens(row)
        if shared:
            positions = np.arange(self.max_seq)
            slots = np.where(
                positions < shared,
                (positions % self.block_size).astype(np.int32), slots)
        return slots

    def evict(self, row: int) -> int:
        """Retire ``row``: every owned block drops one reference, and a
        block leaves the live set only at refcount zero — then to the
        warm cache if it still holds registered prefix content, else to
        the free list. Returns how many blocks the row released."""
        freed = self._owned.pop(row, [])
        self._shared_len.pop(row, None)
        if not self.share_prefix:
            self._free.extend(reversed(freed))
        else:
            for block in reversed(freed):
                self._refs[block] -= 1
                if self._refs[block]:
                    continue
                del self._refs[block]
                key = self._block_key.get(block)
                if key is not None:
                    self._cached[block] = key      # warm, LRU-ordered
                else:
                    self._free.append(block)
        self.table[row] = TRASH_BLOCK
        return len(freed)

    def audit(self) -> dict:
        """Invariant check for the churn tests: every non-trash block is
        in exactly one of {free, warm-cached, live}; refcounts equal the
        number of owning rows; tables agree with ownership; the radix
        index is consistent. Returns summary counts."""
        if self.share_prefix:
            owners: dict[int, int] = {}
            for ids in self._owned.values():
                for block in ids:
                    owners[block] = owners.get(block, 0) + 1
            assert owners == self._refs, (owners, self._refs)
            states = [set(self._free), set(self._cached), set(self._refs)]
            everything: set[int] = set()
            for state in states:
                assert not (state & everything), 'block in two states'
                everything |= state
            assert everything == set(range(1, self.blocks))
            assert set(self._keys.values()) == set(self._block_key)
            for block, key in self._block_key.items():
                assert self._keys[key] == block
        else:
            live = [b for ids in self._owned.values() for b in ids]
            assert len(live) == len(set(live)), 'unshared pool aliased a block'
            assert sorted(live + self._free) == list(range(1, self.blocks))
        for row in range(self.rows):
            ids = self._owned.get(row, [])
            mapped = [int(b) for b in self.table[row] if b != TRASH_BLOCK]
            assert mapped == ids, (row, mapped, ids)
        return {'free': len(self._free), 'cached': len(self._cached),
                'live': self.live_blocks}


def _is_kv(path) -> bool:
    return path[-1] in (jax.tree_util.DictKey('key'),
                        jax.tree_util.DictKey('value'))


@functools.partial(jax.jit, donate_argnums=(0,))
def adopt_prefill(cache, prefill_cache, slots, row, length):
    """Admit a prefilled sequence into ``row`` of the paged cache.

    ``prefill_cache`` is the contiguous decode cache a plain (non-paged)
    prefill apply left behind — per-layer KV strips ``[1, max_seq, heads,
    head_dim]``; ``slots`` is the row's ``[max_seq]`` physical token-slot
    map (:meth:`PagedKVCache.slots`, trash-padded past the allocation, so
    pad-bucket junk beyond the prompt scatters into trash or into
    positions the decode write overwrites before the mask ever exposes
    them); ``row``/``length`` set the row's cursors to the prompt length.
    Tables are not touched here — :func:`write_tables` is the one table
    authority. One compiled program for every admission (prefill strips
    share one shape across buckets: the cache is allocated ``max_seq``
    wide regardless of prompt length)."""
    from tpusystem.train.cursors import is_cursor
    source = {jax.tree_util.keystr(path): leaf for path, leaf
              in jax.tree_util.tree_leaves_with_path(prefill_cache)}

    def fix(path, leaf):
        if _is_kv(path):
            strip = source[jax.tree_util.keystr(path)][0]  # [max_seq, h, d]
            return leaf.at[slots].set(strip.astype(leaf.dtype))
        if is_cursor(path):
            return leaf.at[row].set(jnp.asarray(length, leaf.dtype))
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.partial(jax.jit, donate_argnums=(0,))
def write_tables(cache, tables):
    """Replace every layer's ``table`` cache leaf with the host
    authority's ``[rows, max_blocks]`` map (broadcast over a scanned
    stack's leading layer dim). Admission maps a row's logical blocks to
    its fresh allocation; eviction resets them to trash — either way the
    whole membership change is this table edit plus (for admissions)
    :func:`adopt_prefill`'s block writes."""
    def fix(path, leaf):
        if path[-1] == jax.tree_util.DictKey('table'):
            return jnp.broadcast_to(jnp.asarray(tables, leaf.dtype),
                                    leaf.shape)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


def pool_shardings(cache, mesh):
    """Per-leaf :class:`~jax.sharding.NamedSharding` for a paged pool
    under a TP mesh — the mesh-aware half of the pool contract.

    KV leaves ``[..., slots, heads, head_dim]`` shard over *heads* on the
    ``model`` axis (each device holds its attention heads' blocks for
    every slot — the same head split the TP matmuls already use, so
    decode reads its KV locally). Heads that don't divide the axis fall
    back replicated, the same divisibility discipline as
    :meth:`~tpusystem.parallel.sharding.ShardingPolicy.spec`. Everything
    else — block tables, cursors, masks — replicates: the host-side
    :class:`PagedKVCache` stays the ONE block-table authority and
    ``adopt_prefill``/``write_tables`` keep their contracts unchanged.
    """
    from jax.sharding import NamedSharding, PartitionSpec
    from tpusystem.parallel.mesh import MODEL
    model = dict(mesh.shape).get(MODEL, 1)

    def spec(path, leaf):
        if _is_kv(path) and leaf.ndim >= 2 and leaf.shape[-2] % model == 0:
            axes = [None] * leaf.ndim
            axes[-2] = MODEL
            return NamedSharding(mesh, PartitionSpec(*axes))
        return NamedSharding(mesh, PartitionSpec())
    return jax.tree_util.tree_map_with_path(spec, cache)
