"""Paged KV-cache management: the free-list, block tables, and the
device-side admission writes.

The device layout lives in :func:`tpusystem.ops.attention.paged_attention`
(one shared pool of ``num_blocks * block_size`` token slots per layer,
per-row block tables mapping logical blocks to physical ones). This module
is the **host-side authority** over that layout: which physical blocks are
free, which row owns which blocks, and what every row's table says —
:class:`PagedKVCache` — plus the two jitted cache edits the engine uses to
change batch membership without retracing its decode step:

* :func:`adopt_prefill` scatters a prefilled contiguous KV strip into a
  row's allocated blocks (one program total — admission is a pair of
  device calls, never a reshape of the pool);
* :func:`write_tables` replaces every layer's ``table`` cache leaf with
  the host authority's current map (evictions and admissions both reduce
  to this table edit).

Physical block 0 is the reserved **trash block**: unmapped table entries
point there, so a retired row's dead writes (the fixed-shape step keeps
computing every row) land in trash instead of a live row's blocks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

TRASH_BLOCK = 0


class PagedKVCache:
    """Host-side free-list + block-table authority for the paged pool.

    Pure bookkeeping (numpy only — unit-testable without a device):
    ``admit`` allocates the blocks covering a sequence's whole token
    budget up front (prompt + generation, so decode never stalls on a
    mid-stream allocation), ``evict`` returns them to the free list and
    resets the row's table to trash. The device copies of the tables are
    refreshed from :attr:`table` via :func:`write_tables`.
    """

    def __init__(self, rows: int, blocks: int, block_size: int,
                 max_seq: int) -> None:
        if max_seq % block_size:
            raise ValueError(f'max_seq ({max_seq}) must be a multiple of '
                             f'block_size ({block_size})')
        if blocks < 2:
            raise ValueError('need at least 2 blocks (block 0 is the '
                             'reserved trash block)')
        self.rows, self.blocks, self.block_size = rows, blocks, block_size
        self.max_blocks = max_seq // block_size
        self.max_seq = max_seq
        # LIFO free list over blocks 1..blocks-1 (0 is trash)
        self._free = list(range(blocks - 1, 0, -1))
        self._owned: dict[int, list[int]] = {}
        self.table = np.full((rows, self.max_blocks), TRASH_BLOCK, np.int32)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def blocks_for(self, tokens: int) -> int:
        """Physical blocks covering ``tokens`` cache slots."""
        return -(-tokens // self.block_size)

    def can_admit(self, tokens: int) -> bool:
        needed = self.blocks_for(tokens)
        return needed <= len(self._free) and needed <= self.max_blocks

    def admit(self, row: int, tokens: int) -> np.ndarray:
        """Allocate ``tokens`` worth of blocks to ``row`` and return the
        ``[max_seq]`` physical token-slot map of the row (positions past
        the allocation map to trash) — the scatter index
        :func:`adopt_prefill` writes the prefilled KV through."""
        if row in self._owned:
            raise ValueError(f'row {row} already owns blocks — evict first')
        needed = self.blocks_for(tokens)
        if needed > self.max_blocks:
            raise ValueError(f'{tokens} tokens need {needed} blocks, over '
                             f'the per-row table width {self.max_blocks}')
        if needed > len(self._free):
            raise ValueError(f'{needed} blocks needed, {len(self._free)} '
                             'free — admission must wait (queue, do not '
                             'crash)')
        ids = [self._free.pop() for _ in range(needed)]
        self._owned[row] = ids
        self.table[row, :needed] = ids
        self.table[row, needed:] = TRASH_BLOCK
        return self.slots(row)

    def slots(self, row: int) -> np.ndarray:
        """``[max_seq]`` physical token slot of each logical position of
        ``row`` under its current table (trash wherever unmapped)."""
        positions = np.arange(self.max_seq)
        physical = self.table[row, positions // self.block_size]
        return (physical * self.block_size
                + positions % self.block_size).astype(np.int32)

    def evict(self, row: int) -> int:
        """Free ``row``'s blocks back to the pool; returns how many."""
        freed = self._owned.pop(row, [])
        self._free.extend(reversed(freed))
        self.table[row] = TRASH_BLOCK
        return len(freed)


def _is_kv(path) -> bool:
    return path[-1] in (jax.tree_util.DictKey('key'),
                        jax.tree_util.DictKey('value'))


@functools.partial(jax.jit, donate_argnums=(0,))
def adopt_prefill(cache, prefill_cache, slots, row, length):
    """Admit a prefilled sequence into ``row`` of the paged cache.

    ``prefill_cache`` is the contiguous decode cache a plain (non-paged)
    prefill apply left behind — per-layer KV strips ``[1, max_seq, heads,
    head_dim]``; ``slots`` is the row's ``[max_seq]`` physical token-slot
    map (:meth:`PagedKVCache.slots`, trash-padded past the allocation, so
    pad-bucket junk beyond the prompt scatters into trash or into
    positions the decode write overwrites before the mask ever exposes
    them); ``row``/``length`` set the row's cursors to the prompt length.
    Tables are not touched here — :func:`write_tables` is the one table
    authority. One compiled program for every admission (prefill strips
    share one shape across buckets: the cache is allocated ``max_seq``
    wide regardless of prompt length)."""
    from tpusystem.train.cursors import is_cursor
    source = {jax.tree_util.keystr(path): leaf for path, leaf
              in jax.tree_util.tree_leaves_with_path(prefill_cache)}

    def fix(path, leaf):
        if _is_kv(path):
            strip = source[jax.tree_util.keystr(path)][0]  # [max_seq, h, d]
            return leaf.at[slots].set(strip.astype(leaf.dtype))
        if is_cursor(path):
            return leaf.at[row].set(jnp.asarray(length, leaf.dtype))
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.partial(jax.jit, donate_argnums=(0,))
def write_tables(cache, tables):
    """Replace every layer's ``table`` cache leaf with the host
    authority's ``[rows, max_blocks]`` map (broadcast over a scanned
    stack's leading layer dim). Admission maps a row's logical blocks to
    its fresh allocation; eviction resets them to trash — either way the
    whole membership change is this table edit plus (for admissions)
    :func:`adopt_prefill`'s block writes."""
    def fix(path, leaf):
        if path[-1] == jax.tree_util.DictKey('table'):
            return jnp.broadcast_to(jnp.asarray(tables, leaf.dtype),
                                    leaf.shape)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)
