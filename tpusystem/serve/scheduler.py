"""Iteration-level scheduler: prefill/decode phase packing over the engine.

Orca-style continuous batching as host policy over
:class:`tpusystem.serve.Engine`: each :meth:`Scheduler.step` first
**admits** queued requests into free rows — FIFO, within a prefill token
budget so a burst of long prompts cannot starve the decode phase — then
runs **one decode step** for every seated row, then maps the engine's
retirements back to requests. A request the free-list cannot seat stays
queued (never crashes — the ``Saturated`` contract), and drains in as
rows and blocks free up.

Overload is bounded and typed: ``max_queued`` rejects at submit with
:exc:`QueueFull` once the backlog is full (unbounded by default — the
pre-existing contract), and :class:`~tpusystem.serve.failover.Watermarks`
sheds queued requests by deadline slack past the high watermark (the
request that will expire anyway goes first; active rows are never shed).
Wall time enters ONLY through the injectable ``clock`` — deadline
expiry, shedding slack, and every Completion's latency run on a fake
clock in tier-1 with zero real sleeps (the ``Supervisor``
injectable-clock discipline).

The engine keeps the PR-7 serving levers (``stream_dtype`` weight
streaming); :func:`serve_levers` picks the fastest defaults for the
current backend so serving rides the quantized streaming path on HBM-
bound chips without per-deployment tuning. An attached
:class:`~tpusystem.serve.failover.RequestJournal` (``scheduler.journal``)
witnesses every lifecycle transition for the kill/replay drill —
docs/serving.md "Surviving engine failure".
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable

import jax

from tpusystem.serve.engine import Engine, SamplingParams  # noqa: F401
from tpusystem.serve.failover import RequestJournal, Watermarks  # noqa: F401


def serve_levers() -> dict:
    """The default engine levers for serving on this backend: int8
    weight streaming on TPU (decode there is weight-streaming bound —
    half the bytes per step vs bf16, ``benchmarks/decode_roofline.py``),
    'auto' elsewhere (CPU decode is compute-bound and f32 keeps the
    engine token-exact against the f32 reference). The engine now
    carries the whole PR-7 lever set natively: ``decode_impl='auto'``
    rides the fused Pallas paged step on TPU-class backends,
    ``share_prefix=True`` turns on radix prefix sharing, and
    ``draft_module=`` switches to speculative rows — all composable
    with these streaming defaults (docs/serving.md records the
    composition matrix)."""
    if jax.default_backend() in ('tpu', 'axon'):
        return {'stream_dtype': 'int8'}
    return {'stream_dtype': 'auto'}


class QueueFull(RuntimeError):
    """The backlog is at ``max_queued`` — a typed rejection the caller
    (or a fronting router) handles by retrying elsewhere or later.
    Distinct from ``ValueError`` (a request that could never run) and
    from silent queueing (unbounded RAM under sustained overload)."""


@dataclasses.dataclass
class Request:
    """One user request: a prompt and a generation budget.

    ``sampling`` (a :class:`~tpusystem.serve.engine.SamplingParams`,
    None = greedy) selects seeded temperature/top-k/top-p sampling and
    the grammar ``mask_fn`` hook — deterministic by construction (each
    token's RNG key is a pure function of ``(seed, position)``), so
    journal replay, reroute, and hedging stay token-exact for sampled
    requests too; a ``temperature > 0`` request without a seed is
    refused typed (:class:`~tpusystem.serve.engine.UnseededSampling`)
    at submit. ``stop_token`` ends the request early, with the stop
    token included in the output. ``deadline`` (seconds from
    submission, None = forever) bounds the request's whole life: a
    queued request that cannot be seated before it — the starvation
    case under saturation — or an active one still decoding past it
    expires with a typed ``RequestExpired`` event and reason
    ``'expired'`` instead of waiting silently forever."""
    id: str
    prompt: object                   # int sequence
    max_new: int
    stop_token: int | None = None
    deadline: float | None = None
    sampling: SamplingParams | None = None
    trace: object = None
    # the request's causal identity (tpusystem.observe.TraceContext),
    # assigned by the first traced component that sees it (router or
    # scheduler) and carried THROUGH the journal's pack/unpack — so a
    # row replayed or rerouted onto a different engine still parents its
    # spans to the original submission's trace. None when tracing is off.


@dataclasses.dataclass
class _Pending:
    request: Request
    submitted: float
    # tokens already emitted before an engine relaunch (the journal
    # replay path): the engine re-prefills prompt + prefix and the final
    # Completion is prefix + resumed tokens — token-exact for greedy AND
    # seeded sampled decode (the prefix length restarts the sampling
    # position counter exactly where the stream left off)
    prefix: list = dataclasses.field(default_factory=list)
    # a KVHandoff when the prefill already ran on ANOTHER replica
    # (disaggregated ingest): admission adopts the shipped strips via
    # Engine.admit_prefilled instead of running a prefill program
    handoff: object = None


@dataclasses.dataclass
class Completion:
    request: Request
    tokens: list
    reason: str          # 'length' | 'stop' | 'cancelled' | 'expired' | 'shed'
    seconds: float                   # submit -> completion


@dataclasses.dataclass
class Tick:
    """One scheduler step's outcome."""
    admitted: list                   # [(Request, Admission, ttft_s), ...]
    emitted: dict                    # request id -> list of tokens emitted
    # this step (one for the plain engine step, up to speculate+1 when
    # the engine runs speculative rows)
    completed: list                  # [Completion, ...]
    queue_depth: int
    active: int
    expired: list = dataclasses.field(default_factory=list)
    # [(Completion, 'queued' | 'active'), ...] — deadline expiries this step
    shed: list = dataclasses.field(default_factory=list)
    # [(Completion, slack_seconds | None), ...] — watermark sheds this step
    shed_depth: int | None = None
    # the queue depth that TRIGGERED the shed (pre-shed, post-expiry) —
    # the final queue_depth is post-admission and would misreport the
    # overload the LoadShed/Backpressure events narrate


class Scheduler:
    """FIFO continuous-batching scheduler over one engine.

    Args:
        engine: the :class:`~tpusystem.serve.Engine` to pack.
        prefill_budget: max prompt tokens (bucket-padded) prefilled per
            step. At least one admission always proceeds when capacity
            exists, so a prompt wider than the whole budget cannot
            starve. With prefix sharing the budget counts only the
            UNCACHED suffix (``Engine.admit_cost``) — cached prefix
            tokens are adopted, not recomputed, so they shouldn't spend
            prefill budget. ``admit_cost`` floors at one bucket even
            for a fully-cached prompt, so admissions always charge a
            nonzero cost and the one-admission rule cannot degenerate
            into an unbounded zero-cost admission spin.
        clock: wall-time source (``time.monotonic``). Injectable so
            deadline-expiry, shedding and watchdog tests run on a fake
            clock with zero real sleeps.
        max_queued: backlog bound — submissions past it raise
            :exc:`QueueFull`. None (default) keeps the pre-existing
            unbounded behavior.
        watermarks: a :class:`~tpusystem.serve.failover.Watermarks`
            high/low pair for deadline-slack load shedding, or None
            (default: never shed).
        prefill_only: the disaggregated prefill role — admission runs
            :meth:`~tpusystem.serve.Engine.export_prefill` instead of
            seating rows, finished strips land in :attr:`outbox` as
            :class:`~tpusystem.serve.disagg.KVHandoff`\\ s (the router
            ships them to a decode replica and acks with
            :meth:`shipped`), and the decode phase never runs here.
    """

    def __init__(self, engine: Engine, *, prefill_budget: int = 512,
                 clock: Callable[[], float] = time.monotonic,
                 max_queued: int | None = None,
                 watermarks: Watermarks | None = None,
                 tracer=None, prefill_only: bool = False) -> None:
        if max_queued is not None and max_queued < 1:
            raise ValueError(f'max_queued must be >= 1 (or None for '
                             f'unbounded), got {max_queued}')
        self.engine = engine
        self.prefill_budget = prefill_budget
        self.max_queued = max_queued
        self.watermarks = watermarks
        self.prefill_only = prefill_only
        self.journal: RequestJournal | None = None
        self.backpressure = False
        self.tracer = tracer         # observe.Tracer | None (None = zero
        self._clock = clock          # tracing work on every path below)
        self._queue: deque[_Pending] = deque()
        self._seated: dict[int, _Pending] = {}      # row -> pending
        self.outbox: deque = deque()  # KVHandoffs awaiting shipment
        self._shipping: dict[str, Request] = {}     # shipped, not yet acked
        self.results: dict[str, Completion] = {}
        self.steps = 0
        self._trace_open: dict[str, object] = {}    # request id -> Span
        self._trace_roots: dict[str, object] = {}   # roots THIS end owns

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def active(self) -> int:
        return len(self._seated)

    @property
    def idle(self) -> bool:
        # a prefill replica with exported-but-unshipped strips is NOT
        # idle, or the autoscaler could shrink it mid-handoff
        return (not self._queue and not self._seated and not self.outbox
                and not self._shipping)

    def submit(self, request: Request) -> None:
        """Queue a request. Requests that could NEVER fit (prompt +
        max_new over the cache capacity) are refused immediately with a
        ``ValueError`` instead of clogging the queue forever; a full
        backlog (``max_queued``) refuses with :exc:`QueueFull`."""
        prompt_len = len(request.prompt)
        if prompt_len < 1 or request.max_new < 1:
            raise ValueError('a request needs a non-empty prompt and '
                             'max_new >= 1')
        if request.deadline is not None and request.deadline <= 0:
            raise ValueError(
                f'request {request.id!r}: deadline must be positive seconds '
                f'from submission, got {request.deadline!r}')
        # refuse non-reproducible sampling at the door (UnseededSampling,
        # a ValueError): once queued, every downstream guarantee —
        # journal replay, reroute, hedging — would silently break
        self.engine._validate_sampling(getattr(request, 'sampling', None))
        if prompt_len + request.max_new > self.engine.max_seq:
            raise ValueError(
                f'request {request.id!r}: prompt ({prompt_len}) + max_new '
                f'({request.max_new}) exceeds the engine capacity '
                f'max_seq={self.engine.max_seq}')
        needed = self.engine.pool.blocks_for(prompt_len + request.max_new)
        if needed > self.engine.pool.blocks - 1:
            # even a fully drained pool could not back it — refusing now
            # beats queueing it forever behind requests that CAN run
            raise ValueError(
                f'request {request.id!r} needs {needed} blocks but the '
                f'pool has {self.engine.pool.blocks - 1} allocatable')
        if (self.max_queued is not None
                and len(self._queue) >= self.max_queued):
            raise QueueFull(
                f'request {request.id!r} rejected: backlog is at '
                f'max_queued={self.max_queued} — retry later or on '
                f'another replica')
        pending = _Pending(request, self._clock())
        self._queue.append(pending)
        if self.journal is not None:
            self.journal.record(request, pending.submitted)
        if self.tracer is not None:
            self._trace_enqueue(request)

    def restore(self, request: Request, *, waited: float = 0.0,
                prefix=()) -> None:
        """Re-queue a journaled request after an engine relaunch (the
        :func:`tpusystem.serve.failover.replay` entry): ``prefix`` is the
        tokens already emitted before the failure — admission re-prefills
        ``prompt + prefix`` and decodes the remaining budget, and the
        final Completion is ``prefix + resumed tokens`` (token-exact for
        greedy and seeded sampled decode alike — the sampling counter is
        a pure function of position, and the prefix IS the position).
        ``waited`` backdates the submission so
        deadline and latency accounting stay truthful across the
        relaunch (outage time between the last journal push and the
        relaunch is not counted — the journal packs waited-seconds)."""
        prefix = [int(token) for token in prefix]
        if len(prefix) >= request.max_new:
            raise ValueError(
                f'request {request.id!r} already emitted {len(prefix)} of '
                f'max_new={request.max_new} tokens — a finished request '
                f'has no business in the journal')
        if self.prefill_only and prefix:
            from tpusystem.serve.disagg import RoleMismatch
            raise RoleMismatch(
                f'request {request.id!r} carries a {len(prefix)}-token '
                'decode prefix but this scheduler is prefill-only — a hot '
                'restore needs a decode-capable replica (the router '
                'places by role; this raise is the safety net, not a '
                'silent drop)')
        pending = _Pending(request, self._clock() - waited, prefix)
        self._queue.append(pending)
        if self.journal is not None:
            self.journal.restored(request, pending.submitted, prefix)
        if self.tracer is not None:
            self._trace_enqueue(request, prefix=len(prefix))

    # ------------------------------------------------ disaggregated roles

    def take_handoffs(self) -> list:
        """Drain the prefill outbox — every
        :class:`~tpusystem.serve.disagg.KVHandoff` exported since the
        last call, in FIFO order. The caller (router or test harness)
        ships each to a decode replica and acks with :meth:`shipped`;
        until the ack the request counts as in flight here (journal row
        live, :attr:`idle` false), so a crash between export and ack
        recovers it."""
        handoffs = list(self.outbox)
        self.outbox.clear()
        for handoff in handoffs:
            self._shipping[handoff.request.id] = handoff.request
        return handoffs

    def shipped(self, request_id: str) -> None:
        """Ack one handoff: the decode replica seated (or journaled) it,
        so ownership transferred — this side's journal row closes and
        its trace spans end with reason ``'handoff'``. Unknown ids are
        ignored (the ack can race a local crash-recovery resubmit)."""
        request = self._shipping.pop(request_id, None)
        if self.journal is not None:
            self.journal.finished(request_id)
        if self.tracer is not None and request is not None:
            self._trace_finish(request, 'handoff', 0)

    def ingest(self, handoff, *, waited: float = 0.0) -> None:
        """Decode-side entry: queue a request whose prefill ran on a
        prefill-role replica. Admission seats it through
        ``Engine.admit_prefilled`` (adopt-only — no prefill program
        runs here). ``waited`` backdates the submission by the time the
        request already spent on the prefill side, so deadlines and
        latency accounting span the whole disaggregated path."""
        request = handoff.request
        prefix = [int(token) for token in handoff.prefix]
        pending = _Pending(request, self._clock() - waited, prefix,
                           handoff=handoff)
        self._queue.append(pending)
        if self.journal is not None:
            if prefix:
                self.journal.restored(request, pending.submitted, prefix)
            else:
                self.journal.record(request, pending.submitted)
        if self.tracer is not None:
            self._trace_enqueue(request,
                                prefix=len(prefix) if prefix else None)

    # ------------------------------------------------------------ tracing
    # (every call below is guarded by `self.tracer is not None` at the
    # call site — tracing off means NO extra work on the serving path)

    def _trace_enqueue(self, request: Request, prefix: int | None = None):
        """Open the request's 'queued' span. The FIRST traced component
        that sees a request roots its trace (a fronting Router usually
        did already — then ``request.trace`` carries its context and the
        spans here parent into it, which is exactly how a replayed row
        on a different engine stays in the original trace)."""
        if request.trace is None:
            root = self.tracer.begin(f'request {request.id}', cat='request',
                                     args={'request': request.id})
            request.trace = root.context
            self._trace_roots[request.id] = root
        args = {'request': request.id}
        if prefix is not None:       # a journal replay / reroute re-entry
            args['prefix'] = prefix
            args['replayed'] = True
        self._trace_open[request.id] = self.tracer.begin(
            'queued', cat='serve', trace=request.trace, args=args)

    def _trace_seated(self, request: Request, row: int) -> None:
        self.tracer.end(self._trace_open.pop(request.id, None))
        self._trace_open[request.id] = self.tracer.begin(
            'decode', cat='serve', trace=request.trace,
            args={'request': request.id, 'row': row})

    def _trace_exported(self, request: Request) -> None:
        """Close 'queued', open 'handoff' — ended by :meth:`shipped`'s
        ack. Parented into ``request.trace`` like every serve span, so
        the decode replica's spans and these share one trace."""
        self.tracer.end(self._trace_open.pop(request.id, None))
        self._trace_open[request.id] = self.tracer.begin(
            'handoff', cat='serve', trace=request.trace,
            args={'request': request.id})

    def _trace_finish(self, request: Request, reason: str,
                      produced: int) -> None:
        self.tracer.end(self._trace_open.pop(request.id, None),
                        reason=reason, produced=produced)
        root = self._trace_roots.pop(request.id, None)
        if root is not None:         # this scheduler rooted the trace
            self.tracer.end(root, reason=reason, produced=produced)

    def cancel(self, request_id: str) -> str | None:
        """Cancel a request wherever it is: ``'queued'`` (silently
        dropped), ``'active'`` (evicted mid-decode; partial tokens land
        in :attr:`results` with reason ``'cancelled'``), or ``None``
        when unknown/already completed."""
        for pending in list(self._queue):
            if pending.request.id == request_id:
                self._queue.remove(pending)
                if self.journal is not None:
                    self.journal.finished(request_id)
                if self.tracer is not None:
                    self._trace_finish(pending.request, 'cancelled', 0)
                return 'queued'
        for row, pending in list(self._seated.items()):
            if pending.request.id == request_id:
                state = self.engine.evict(row)
                del self._seated[row]
                self._complete(pending, list(state.tokens), 'cancelled')
                return 'active'
        for handoff in list(self.outbox):
            if handoff.request.id == request_id:
                self.outbox.remove(handoff)
                if self.journal is not None:
                    self.journal.finished(request_id)
                if self.tracer is not None:
                    self._trace_finish(handoff.request, 'cancelled', 0)
                return 'queued'
        return None

    def _expire(self) -> list:
        """Retire every request whose deadline passed: queued ones are
        dropped (never seated — saturation starvation made visible);
        active ones are evicted mid-decode, partial tokens kept. Returns
        ``[(Completion, where), ...]`` for the tick."""
        now = self._clock()
        expired = []
        for pending in list(self._queue):
            deadline = pending.request.deadline
            if deadline is not None and now - pending.submitted >= deadline:
                self._queue.remove(pending)
                expired.append((self._complete(pending, [], 'expired'),
                                'queued'))
        for row, pending in list(self._seated.items()):
            deadline = pending.request.deadline
            if deadline is not None and now - pending.submitted >= deadline:
                state = self.engine.evict(row)
                del self._seated[row]
                expired.append((self._complete(pending, list(state.tokens),
                                               'expired'), 'active'))
        return expired

    def _slack(self, pending: _Pending, now: float) -> float | None:
        """Seconds until the request's deadline (negative = already
        past); None when it has no deadline."""
        deadline = pending.request.deadline
        if deadline is None:
            return None
        return deadline - (now - pending.submitted)

    def shed_candidates(self) -> list:
        """Every queued request as ``(request_id, slack_seconds | None,
        waited_seconds)`` — the shed-ordering input, exposed so a fleet
        router can rank victims GLOBALLY across many replicas' queues
        with the same contract the local shed uses: ascending slack
        first (the request that will expire anyway), then no-deadline
        requests newest-first (ascending waited). Active rows never
        appear — they are never shed."""
        now = self._clock()
        return [(pending.request.id, self._slack(pending, now),
                 now - pending.submitted) for pending in self._queue]

    def shed(self, request_id: str) -> Completion | None:
        """Shed ONE queued request by id (reason ``'shed'``; the victim
        lands in :attr:`results` like any completion) — the fleet
        router's victim hook. Returns None when the id is not queued
        here (already admitted, completed, or somebody else's)."""
        for pending in self._queue:
            if pending.request.id == request_id:
                self._queue.remove(pending)
                return self._complete(pending, [], 'shed')
        return None

    def _shed(self) -> list:
        """Past the high watermark, shed queued requests down to the low
        one by deadline slack — the request that will expire anyway goes
        first; no-deadline requests shed last, newest-first, so the
        oldest waiters keep their FIFO claim. Active rows are never shed
        (sunk prefill, closest to done). Returns
        ``[(Completion, slack), ...]`` and maintains the backpressure
        flag (engaged past high, released at/below low)."""
        if self.watermarks is None:
            return []
        depth = len(self._queue)
        excess = self.watermarks.excess(depth)
        if not excess:
            if self.backpressure and depth <= self.watermarks.low:
                self.backpressure = False
            return []
        self.backpressure = True
        now = self._clock()
        # same ordering contract as shed_candidates() documents — kept
        # over the pending objects directly so the overload path removes
        # each victim once instead of rescanning the queue per shed
        order = sorted(
            self._queue,
            key=lambda pending: (
                (0, self._slack(pending, now))
                if pending.request.deadline is not None
                else (1, now - pending.submitted)))
        shed = []
        for pending in order[:excess]:
            self._queue.remove(pending)
            shed.append((self._complete(pending, [], 'shed'),
                         self._slack(pending, now)))
        return shed

    def step(self) -> Tick:
        """One serving iteration: expire past-deadline requests, shed
        past the watermark, admit within the prefill budget, then decode
        every seated row once."""
        self.steps += 1
        expired = self._expire()
        depth_at_shed = len(self._queue)
        shed = self._shed()
        admitted, completed = [], []
        budget = self.prefill_budget
        while self._queue:
            pending = self._queue[0]
            request = pending.request
            prompt = list(request.prompt) + pending.prefix
            remaining = request.max_new - len(pending.prefix)
            if pending.handoff is not None:
                # adopt-only admission: the prefill already ran on the
                # prefill-role replica — charge the floor, not the
                # prompt bucket (the whole point of the split)
                cost = self.engine.bucket(1)
            else:
                cost = self.engine.admit_cost(prompt)
            if cost > budget and budget < self.prefill_budget:
                break                    # budget spent this step
            if self.prefill_only:
                self._queue.popleft()
                first, kv = self.engine.export_prefill(
                    prompt, sampling=getattr(request, 'sampling', None),
                    emitted=pending.prefix)
                budget -= cost
                from tpusystem.serve.disagg import KVHandoff
                self.outbox.append(KVHandoff(
                    request=request, first=first, kv=kv,
                    prefix=list(pending.prefix),
                    waited=self._clock() - pending.submitted))
                if self.tracer is not None:
                    self._trace_exported(request)
                continue
            if not self.engine.can_admit(len(prompt), remaining,
                                         prompt=prompt):
                break                    # FIFO: wait for rows/blocks
            self._queue.popleft()
            sampling = getattr(request, 'sampling', None)
            if pending.handoff is not None:
                handoff, pending.handoff = pending.handoff, None
                admission = self.engine.admit_prefilled(
                    prompt, remaining, handoff.first, handoff.kv,
                    stop_token=request.stop_token, tag=request.id,
                    sampling=sampling, emitted=pending.prefix)
            else:
                admission = self.engine.admit(
                    prompt, remaining,
                    stop_token=request.stop_token, tag=request.id,
                    sampling=sampling, emitted=pending.prefix)
            budget -= cost
            ttft = self._clock() - pending.submitted
            admitted.append((request, admission, ttft))
            if self.journal is not None:
                self.journal.seated(request.id, admission.token)
            if self.tracer is not None:
                self._trace_seated(request, admission.row)
            if admission.finished:
                completed.append(self._complete(
                    pending, [admission.token], admission.reason))
            else:
                self._seated[admission.row] = pending

        report = self.engine.step()
        emitted = {}
        for row, tokens in report.emitted.items():
            if row in self._seated:
                request_id = self._seated[row].request.id
                emitted[request_id] = list(tokens)
                if self.journal is not None:
                    for token in tokens:
                        self.journal.append(request_id, token)
        for row, reason, tokens in report.finished:
            # rows admitted directly on the engine (not through this
            # scheduler) retire without a seat here — their caller got
            # the tokens via the engine's StepReport
            pending = self._seated.pop(row, None)
            if pending is not None:
                completed.append(self._complete(pending, list(tokens),
                                                reason))
        if self.journal is not None:
            self.journal.observe_tick()
        return Tick(admitted, emitted, completed, len(self._queue),
                    len(self._seated), expired, shed,
                    depth_at_shed if shed else None)

    def _complete(self, pending: _Pending, tokens: list,
                  reason: str) -> Completion:
        completion = Completion(pending.request, pending.prefix + list(tokens),
                                reason, self._clock() - pending.submitted)
        self.results[pending.request.id] = completion
        if self.journal is not None:
            self.journal.finished(pending.request.id)
        if self.tracer is not None:
            self._trace_finish(pending.request, reason,
                               len(completion.tokens))
        return completion

    def run(self, max_steps: int = 10_000) -> dict:
        """Step until every queued and seated request completes; returns
        :attr:`results` (request id -> :class:`Completion`)."""
        for _ in range(max_steps):
            if self.idle:
                return self.results
            self.step()
        raise RuntimeError(f'scheduler did not drain in {max_steps} steps '
                           f'(queue {self.queue_depth}, active '
                           f'{self.active})')
