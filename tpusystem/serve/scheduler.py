"""Iteration-level scheduler: prefill/decode phase packing over the engine.

Orca-style continuous batching as host policy over
:class:`tpusystem.serve.Engine`: each :meth:`Scheduler.step` first
**admits** queued requests into free rows — FIFO, within a prefill token
budget so a burst of long prompts cannot starve the decode phase — then
runs **one decode step** for every seated row, then maps the engine's
retirements back to requests. A request the free-list cannot seat stays
queued (never crashes — the ``Saturated`` contract), and drains in as
rows and blocks free up.

The engine keeps the PR-7 serving levers (``stream_dtype`` weight
streaming); :func:`serve_levers` picks the fastest defaults for the
current backend so serving rides the quantized streaming path on HBM-
bound chips without per-deployment tuning.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax

from tpusystem.serve.engine import Engine


def serve_levers() -> dict:
    """The default engine levers for serving on this backend: int8
    weight streaming on TPU (decode there is weight-streaming bound —
    half the bytes per step vs bf16, ``benchmarks/decode_roofline.py``),
    'auto' elsewhere (CPU decode is compute-bound and f32 keeps the
    engine token-exact against the f32 reference). The fused Pallas
    decode chain and speculative drafts compose with ``generate()``
    today; the paged step is its own implementation (docs/serving.md
    records the composition matrix)."""
    if jax.default_backend() in ('tpu', 'axon'):
        return {'stream_dtype': 'int8'}
    return {'stream_dtype': 'auto'}


@dataclasses.dataclass
class Request:
    """One user request: a prompt and a generation budget.

    Greedy decoding only (temperature sampling needs per-row rng
    plumbing the engine does not carry yet); ``stop_token`` ends the
    request early, with the stop token included in the output.
    ``deadline`` (seconds from submission, None = forever) bounds the
    request's whole life: a queued request that cannot be seated before
    it — the starvation case under saturation — or an active one still
    decoding past it expires with a typed ``RequestExpired`` event and
    reason ``'expired'`` instead of waiting silently forever."""
    id: str
    prompt: object                   # int sequence
    max_new: int
    stop_token: int | None = None
    deadline: float | None = None


@dataclasses.dataclass
class _Pending:
    request: Request
    submitted: float


@dataclasses.dataclass
class Completion:
    request: Request
    tokens: list
    reason: str                      # 'length' | 'stop' | 'cancelled' | 'expired'
    seconds: float                   # submit -> completion


@dataclasses.dataclass
class Tick:
    """One scheduler step's outcome."""
    admitted: list                   # [(Request, Admission, ttft_s), ...]
    emitted: dict                    # request id -> token
    completed: list                  # [Completion, ...]
    queue_depth: int
    active: int
    expired: list = dataclasses.field(default_factory=list)
    # [(Completion, 'queued' | 'active'), ...] — deadline expiries this step


class Scheduler:
    """FIFO continuous-batching scheduler over one engine.

    Args:
        engine: the :class:`~tpusystem.serve.Engine` to pack.
        prefill_budget: max prompt tokens (bucket-padded) prefilled per
            step. At least one admission always proceeds when capacity
            exists, so a prompt wider than the whole budget cannot
            starve.
    """

    def __init__(self, engine: Engine, *, prefill_budget: int = 512) -> None:
        self.engine = engine
        self.prefill_budget = prefill_budget
        self._queue: deque[_Pending] = deque()
        self._seated: dict[int, _Pending] = {}      # row -> pending
        self.results: dict[str, Completion] = {}
        self.steps = 0

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def active(self) -> int:
        return len(self._seated)

    @property
    def idle(self) -> bool:
        return not self._queue and not self._seated

    def submit(self, request: Request) -> None:
        """Queue a request. Requests that could NEVER fit (prompt +
        max_new over the cache capacity) are refused immediately with a
        ``ValueError`` instead of clogging the queue forever."""
        prompt_len = len(request.prompt)
        if prompt_len < 1 or request.max_new < 1:
            raise ValueError('a request needs a non-empty prompt and '
                             'max_new >= 1')
        if request.deadline is not None and request.deadline <= 0:
            raise ValueError(
                f'request {request.id!r}: deadline must be positive seconds '
                f'from submission, got {request.deadline!r}')
        if prompt_len + request.max_new > self.engine.max_seq:
            raise ValueError(
                f'request {request.id!r}: prompt ({prompt_len}) + max_new '
                f'({request.max_new}) exceeds the engine capacity '
                f'max_seq={self.engine.max_seq}')
        needed = self.engine.pool.blocks_for(prompt_len + request.max_new)
        if needed > self.engine.pool.blocks - 1:
            # even a fully drained pool could not back it — refusing now
            # beats queueing it forever behind requests that CAN run
            raise ValueError(
                f'request {request.id!r} needs {needed} blocks but the '
                f'pool has {self.engine.pool.blocks - 1} allocatable')
        self._queue.append(_Pending(request, time.monotonic()))

    def cancel(self, request_id: str) -> str | None:
        """Cancel a request wherever it is: ``'queued'`` (silently
        dropped), ``'active'`` (evicted mid-decode; partial tokens land
        in :attr:`results` with reason ``'cancelled'``), or ``None``
        when unknown/already completed."""
        for pending in list(self._queue):
            if pending.request.id == request_id:
                self._queue.remove(pending)
                return 'queued'
        for row, pending in list(self._seated.items()):
            if pending.request.id == request_id:
                state = self.engine.evict(row)
                del self._seated[row]
                self.results[request_id] = Completion(
                    pending.request, list(state.tokens), 'cancelled',
                    time.monotonic() - pending.submitted)
                return 'active'
        return None

    def _expire(self) -> list:
        """Retire every request whose deadline passed: queued ones are
        dropped (never seated — saturation starvation made visible);
        active ones are evicted mid-decode, partial tokens kept. Returns
        ``[(Completion, where), ...]`` for the tick."""
        now = time.monotonic()
        expired = []
        for pending in list(self._queue):
            deadline = pending.request.deadline
            if deadline is not None and now - pending.submitted >= deadline:
                self._queue.remove(pending)
                expired.append((self._complete(pending, [], 'expired'),
                                'queued'))
        for row, pending in list(self._seated.items()):
            deadline = pending.request.deadline
            if deadline is not None and now - pending.submitted >= deadline:
                state = self.engine.evict(row)
                del self._seated[row]
                expired.append((self._complete(pending, list(state.tokens),
                                               'expired'), 'active'))
        return expired

    def step(self) -> Tick:
        """One serving iteration: expire past-deadline requests, admit
        within the prefill budget, then decode every seated row once."""
        self.steps += 1
        expired = self._expire()
        admitted, completed = [], []
        budget = self.prefill_budget
        while self._queue:
            pending = self._queue[0]
            request = pending.request
            cost = self.engine.bucket(len(request.prompt))
            if cost > budget and budget < self.prefill_budget:
                break                    # budget spent this step
            if not self.engine.can_admit(len(request.prompt),
                                         request.max_new):
                break                    # FIFO: wait for rows/blocks
            self._queue.popleft()
            admission = self.engine.admit(
                request.prompt, request.max_new,
                stop_token=request.stop_token, tag=request.id)
            budget -= cost
            ttft = time.monotonic() - pending.submitted
            admitted.append((request, admission, ttft))
            if admission.finished:
                completed.append(self._complete(
                    pending, [admission.token], admission.reason))
            else:
                self._seated[admission.row] = pending

        report = self.engine.step()
        emitted = {}
        for row, token in report.emitted.items():
            if row in self._seated:
                emitted[self._seated[row].request.id] = token
        for row, reason, tokens in report.finished:
            # rows admitted directly on the engine (not through this
            # scheduler) retire without a seat here — their caller got
            # the tokens via the engine's StepReport
            pending = self._seated.pop(row, None)
            if pending is not None:
                completed.append(self._complete(pending, list(tokens),
                                                reason))
        return Tick(admitted, emitted, completed, len(self._queue),
                    len(self._seated), expired)

    def _complete(self, pending: _Pending, tokens: list,
                  reason: str) -> Completion:
        completion = Completion(pending.request, tokens, reason,
                                time.monotonic() - pending.submitted)
        self.results[pending.request.id] = completion
        return completion

    def run(self, max_steps: int = 10_000) -> dict:
        """Step until every queued and seated request completes; returns
        :attr:`results` (request id -> :class:`Completion`)."""
        for _ in range(max_steps):
            if self.idle:
                return self.results
            self.step()
        raise RuntimeError(f'scheduler did not drain in {max_steps} steps '
                           f'(queue {self.queue_depth}, active '
                           f'{self.active})')
