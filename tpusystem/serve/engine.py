"""Continuous-batching decode engine: one compiled step, churning rows.

The engine runs a fixed-shape ``[rows, 1]`` greedy token-step under
``jit`` — the ``per_row_decode`` discipline from the speculative path
(:mod:`tpusystem.train.generate`), extended to independent user
sequences over the paged KV cache
(:func:`tpusystem.ops.attention.paged_attention`). Batch membership
changes every step **without retracing**:

* **admit** — the prompt prefills through a plain contiguous decode
  apply (one compiled prefill program per pad bucket —
  :func:`prefill_bucket`), the resulting KV strip scatters into
  free-list blocks (:func:`tpusystem.serve.kvcache.adopt_prefill`), and
  the row's block table and cursor are edited host-side. The prefill
  logits' argmax is the request's first token.
* **step** — every row advances one token in one dispatch; retired rows
  idle at the trash block behind an active mask.
* **evict** — blocks return to the free list and the row's table resets
  to trash; the decode program never sees a shape change.

Greedy outputs are **token-exact against standalone**
:func:`tpusystem.train.generate.generate` for every request, regardless
of co-batched traffic, in window-length-invariant arithmetic (f32
modules; masked attention positions contribute exact zeros, so a row
never observes its neighbors — pinned by ``tests/test_serve.py``).

``stream_dtype`` applies :func:`generate`'s weight-streaming levers to
the engine's param tree ('int8' halves the per-step streamed weight
bytes vs bf16; dequantization stays inside the compiled step so the
narrow leaves remain the HBM-resident operand).
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.serve.kvcache import (PagedKVCache, adopt_prefill,
                                     write_tables)
from tpusystem.train.cursors import read_cursor, rewind
from tpusystem.train.generate import _decoder, _dequant, _stream_params


class Saturated(RuntimeError):
    """No free row or not enough free blocks — the request must stay
    queued (the scheduler's job), never crash the engine."""


def engine_unsupported_reason(module) -> str | None:
    """None when the paged engine can serve this module, else why not
    (the ``fused_unsupported_reason`` capability-gate discipline)."""
    for field in ('decode', 'max_seq', 'per_row_decode', 'decode_pages'):
        if not hasattr(module, field):
            return (f'module {type(module).__name__} has no {field!r} '
                    'field — the engine needs the family decode '
                    'conventions (GPT2 / Llama)')
    if getattr(module, 'scan_layers', False):
        return ('scan_layers stacks the per-layer caches at a leading '
                'layer dim; the engine admission writes are unrolled-'
                'layout only — serve the unrolled module')
    if getattr(module, 'moe_experts', 0):
        return ('MoE expert capacity derives from the step\'s batch '
                'token count, so a shared-batch decode step is not '
                'token-exact against per-request decode')
    return None


def prefill_bucket(length: int, block_size: int, max_seq: int) -> int:
    """Pad-to-bucket width for a prompt: the smallest power-of-2 at
    least ``max(length, block_size)``, capped at ``max_seq`` — so a
    stream of varied prompt lengths compiles a **bounded** set of
    prefill programs (the retrace-trap discipline) instead of one per
    length."""
    bucket = max(length, block_size)
    bucket = 1 << (bucket - 1).bit_length()
    return min(bucket, max_seq)


@functools.cache
def _compiled_prefill(decoder, bucket: int):
    """One compiled prefill program per (decode clone, pad bucket) —
    ``cache_info()`` is the compile-count witness the bucketing tests
    pin."""
    return _build_prefill(decoder, bucket)


def _build_prefill(decoder, bucket: int):
    del bucket          # part of the cache key; shapes key the jit cache

    @jax.jit
    def run(params, padded, length):
        # plain contiguous prefill: one causal pass over the padded
        # prompt builds every layer's [1, max_seq, ...] KV strip; the
        # right-pad junk is causally invisible to the real positions
        logits, state = decoder.apply(
            {'params': _dequant(params, decoder)}, padded,
            mutable=['cache'])
        first = jnp.argmax(logits[0, length - 1], axis=-1).astype(jnp.int32)
        return first, state['cache']

    return run


@dataclasses.dataclass
class Admission:
    """What :meth:`Engine.admit` hands back: the row the request landed
    in, its first token (from the prefill logits), and whether that
    token already completed it (``max_new == 1`` or a stop hit)."""
    row: int
    token: int
    finished: bool
    reason: str | None = None       # 'length' | 'stop' when finished


@dataclasses.dataclass
class StepReport:
    """One engine step: ``emitted`` maps row -> new token for every row
    that was active, ``finished`` lists the rows retired this step —
    ``(row, reason, tokens)`` triples, already evicted by the time the
    report returns (the tokens ride out with the report because eviction
    frees the row's state)."""
    emitted: dict
    finished: list                   # [(row, reason, tokens), ...]


@dataclasses.dataclass
class _RowState:
    tokens: list
    max_new: int
    stop: int | None
    tag: object = None               # opaque caller handle (request id)


class Engine:
    """The continuous-batching engine over one model's param tree.

    Args:
        module: a family LM module (GPT2 / Llama conventions; see
            :func:`engine_unsupported_reason` for the scope gate).
        params: trained parameters.
        rows: fixed decode batch width — the compiled step's shape.
        block_size: tokens per KV block.
        blocks: physical blocks in the pool (including the reserved
            trash block 0). Default sizes the pool to back every row at
            full ``max_seq`` depth; smaller pools oversubscribe capacity
            and rely on the scheduler to queue.
        stream_dtype: :func:`tpusystem.train.generate.generate`'s
            weight-streaming lever, applied to the engine's param tree
            ('int8' for the serving default on HBM-bound chips).

    The decode step traces exactly once per engine (``trace_count`` is
    the witness); admissions and evictions are host-side table edits
    plus fixed-shape device writes.
    """

    def __init__(self, module, params, *, rows: int = 4,
                 block_size: int = 16, blocks: int | None = None,
                 stream_dtype: str = 'auto') -> None:
        reason = engine_unsupported_reason(module)
        if reason is not None:
            raise ValueError(f'the serving engine cannot run this module: '
                             f'{reason}')
        self.rows, self.block_size = rows, block_size
        self.max_seq = module.max_seq
        if blocks is None:
            blocks = rows * (self.max_seq // block_size) + 1
        self.stream_dtype = stream_dtype
        self._prefiller = _decoder(module)     # contiguous, shared-cursor
        self._decoder = dataclasses.replace(
            _decoder(module, per_row=True),
            decode_pages=(blocks, block_size))
        self._params = _stream_params(self._decoder, params, stream_dtype)
        self.pool = PagedKVCache(rows, blocks, block_size, self.max_seq)
        shapes = jax.eval_shape(
            functools.partial(self._decoder.init, jax.random.PRNGKey(0)),
            jnp.zeros((rows, 1), jnp.int32))['cache']
        self._cache = jax.tree.map(
            lambda leaf: jnp.zeros(leaf.shape, leaf.dtype), shapes)
        self._free_rows = list(range(rows - 1, -1, -1))
        # host mirrors for bookkeeping; the device copies are what the
        # step consumes (tokens feed back device-to-device — the per-
        # step host round trip is ONLY the emitted-token read)
        self._tokens = np.zeros(rows, np.int32)
        self._active = np.zeros(rows, bool)
        self._tokens_dev = jnp.zeros(rows, jnp.int32)
        self._active_dev = jnp.zeros(rows, bool)
        self._rowstate: dict[int, _RowState] = {}
        self._prefills: dict[int, object] = {}   # unhashable-module path
        self.trace_count = 0
        self.timings = {'prefill': 0.0, 'admit': 0.0, 'step': 0.0}
        # wall seconds of the most recent decode dispatch (admission and
        # prefill excluded) — the decode-only probe for a custom serving
        # loop that wants to feed failover.StepWatchdog.observe the step
        # alone (ServingReplica's built-in watchdog watches the whole
        # tick on its injectable clock instead)
        self.last_step_seconds = 0.0

        def step_fn(params, cache, tokens, active):
            self.trace_count += 1            # runs at trace time only
            logits, updated = self._decoder.apply(
                {'params': _dequant(params, self._decoder), 'cache': cache},
                tokens[:, None], mutable=['cache'])
            token = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            # park retired rows' cursors at 0 so their dead writes stay
            # in the trash block's first slots instead of walking off the
            # table; active rows keep the cursor cached_attention advanced
            cursor = read_cursor(cache)
            return token, rewind(updated['cache'],
                                 jnp.where(active, cursor + 1, 0))

        self._step = jax.jit(step_fn, donate_argnums=(1,))

    # ------------------------------------------------------------ admission

    @property
    def free_rows(self) -> int:
        return len(self._free_rows)

    @property
    def active_rows(self) -> int:
        return int(self._active.sum())

    def can_admit(self, prompt_len: int, max_new: int) -> bool:
        return (bool(self._free_rows)
                and self.pool.can_admit(prompt_len + max_new))

    def bucket(self, prompt_len: int) -> int:
        return prefill_bucket(prompt_len, self.block_size, self.max_seq)

    def admit(self, prompt, max_new: int, *, stop_token: int | None = None,
              tag=None) -> Admission:
        """Prefill ``prompt`` and seat it in a free row. Raises
        :class:`Saturated` when no row or not enough blocks are free
        (the scheduler queues on this), ``ValueError`` on requests that
        could never fit."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError('empty prompt')
        if max_new < 1:
            raise ValueError(f'max_new must be >= 1, got {max_new}')
        if prompt.size + max_new > self.max_seq:
            raise ValueError(
                f'prompt ({prompt.size}) + max_new ({max_new}) exceeds the '
                f'cache capacity max_seq={self.max_seq}')
        if not self._free_rows:
            raise Saturated('no free row')
        if not self.pool.can_admit(prompt.size + max_new):
            raise Saturated(
                f'{self.pool.blocks_for(prompt.size + max_new)} blocks '
                f'needed, {self.pool.free_blocks} free')

        bucket = self.bucket(prompt.size)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :prompt.size] = prompt
        started = time.perf_counter()
        try:
            run = _compiled_prefill(self._prefiller, bucket)
        except TypeError:        # unhashable module field (e.g. live mesh)
            run = self._prefills.setdefault(
                bucket, _build_prefill(self._prefiller, bucket))
        first, prefill_cache = run(self._params, jnp.asarray(padded),
                                   prompt.size)
        first = int(first)
        self.timings['prefill'] += time.perf_counter() - started

        started = time.perf_counter()
        row = self._free_rows.pop()
        slots = self.pool.admit(row, prompt.size + max_new)
        self._cache = adopt_prefill(self._cache, prefill_cache,
                                    jnp.asarray(slots), row, prompt.size)
        self._cache = write_tables(self._cache, self.pool.table)
        self.timings['admit'] += time.perf_counter() - started

        self._tokens[row] = first
        self._active[row] = True
        self._tokens_dev = self._tokens_dev.at[row].set(first)
        self._active_dev = self._active_dev.at[row].set(True)
        self._rowstate[row] = _RowState(tokens=[first], max_new=max_new,
                                        stop=stop_token, tag=tag)
        reason = self._finish_reason(row)
        if reason is not None:
            self.evict(row)
            return Admission(row, first, True, reason)
        return Admission(row, first, False)

    def _finish_reason(self, row: int) -> str | None:
        state = self._rowstate[row]
        if state.stop is not None and state.tokens[-1] == state.stop:
            return 'stop'
        if len(state.tokens) >= state.max_new:
            return 'length'
        return None

    # ------------------------------------------------------------- decoding

    def step(self) -> StepReport:
        """Advance every active row by one greedy token (one fixed-shape
        dispatch), retire rows that hit their length or stop token."""
        if not self._active.any():
            return StepReport({}, [])
        started = time.perf_counter()
        token_dev, self._cache = self._step(self._params, self._cache,
                                            self._tokens_dev,
                                            self._active_dev)
        token = np.asarray(token_dev)
        # retired rows' stale device token stays as-is (in-vocab junk an
        # inactive row may keep embedding — masked, never emitted)
        self._tokens_dev = token_dev
        self.last_step_seconds = time.perf_counter() - started
        self.timings['step'] += self.last_step_seconds
        emitted, finished = {}, []
        for row in np.flatnonzero(self._active):
            row = int(row)
            self._tokens[row] = emitted[row] = int(token[row])
            self._rowstate[row].tokens.append(int(token[row]))
            reason = self._finish_reason(row)
            if reason is not None:
                state = self.evict(row)
                finished.append((row, reason, list(state.tokens)))
        return StepReport(emitted, finished)

    # ------------------------------------------------------------- eviction

    def evict(self, row: int) -> _RowState:
        """Retire ``row`` (finished or cancelled): its blocks return to
        the free list, its table resets to trash — a host-side edit plus
        one fixed-shape table write, never a retrace."""
        if row not in self._rowstate:
            raise ValueError(f'row {row} is not seated')
        self.pool.evict(row)
        self._cache = write_tables(self._cache, self.pool.table)
        self._active[row] = False
        self._tokens[row] = 0
        self._active_dev = self._active_dev.at[row].set(False)
        self._free_rows.append(row)
        return self._rowstate.pop(row)

    def tokens(self, row: int) -> list:
        """Tokens emitted so far for a seated row."""
        return list(self._rowstate[row].tokens)
