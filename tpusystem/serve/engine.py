"""Continuous-batching decode engine: one compiled step, churning rows.

The engine runs a fixed-shape ``[rows, 1]`` token-step under
``jit`` — the ``per_row_decode`` discipline from the speculative path
(:mod:`tpusystem.train.generate`), extended to independent user
sequences over the paged KV cache
(:func:`tpusystem.ops.attention.paged_attention`). Batch membership
changes every step **without retracing**:

* **admit** — the prompt prefills through a plain contiguous decode
  apply (one compiled prefill program per pad bucket —
  :func:`prefill_bucket`), the resulting KV strip scatters into
  free-list blocks (:func:`tpusystem.serve.kvcache.adopt_prefill`), and
  the row's block table and cursor are edited host-side. The prefill
  logits' argmax is the request's first token.
* **step** — every row advances one token in one dispatch; retired rows
  idle at the trash block behind an active mask.
* **evict** — blocks return to the free list and the row's table resets
  to trash; the decode program never sees a shape change.

Greedy outputs are **token-exact against standalone**
:func:`tpusystem.train.generate.generate` for every request, regardless
of co-batched traffic, in window-length-invariant arithmetic (f32
modules; masked attention positions contribute exact zeros, so a row
never observes its neighbors — pinned by ``tests/test_serve.py``).

The decode-roofline levers compose on top of that contract:

* ``stream_dtype`` applies :func:`generate`'s weight-streaming levers to
  the engine's param tree ('int8' halves the per-step streamed weight
  bytes vs bf16; dequantization stays inside the compiled step so the
  narrow leaves remain the HBM-resident operand).
* ``decode_impl='fused'`` routes the one jitted step through the Pallas
  fused decode chain
  (:func:`tpusystem.train.decode_fused.build_fused_paged_step` — the
  ``[rows, dim]`` activation VMEM-resident, the fc→gelu→proj pair one
  kernel, int8/fp8 tiles dequantized in-kernel), gated by
  :func:`tpusystem.train.decode_fused.fused_paged_reason` and
  token-exact vs the flax step.
* ``share_prefix=True`` turns on the radix prefix index
  (:class:`tpusystem.serve.kvcache.PagedKVCache`): admissions whose
  prompt starts with an already-cached block-aligned prefix adopt those
  blocks by reference and prefill **only the uncached suffix** (the
  resume prefill seeds a contiguous cache from pool gathers and applies
  the suffix down the decode path — window-invariant, so tokens don't
  move).
* ``draft_module`` switches the step to **speculative rows**: each
  request owns ``tree_fanout`` adjacent branch rows of the same paged
  pool; the draft fans/extends each branch ``speculate`` tokens and ONE
  target forward verifies every branch window, emitting the longest
  target-accepted prefix plus one corrected token per request —
  between 1 and ``speculate + 1`` tokens per step, still exactly the
  target's sequential decode (greedy or seeded-sampled — the verify
  samples each window position at its own ``(seed, position)``
  counter, so acceptance-against-greedy-drafts only changes speed,
  never the stream). Losing branches' blocks never leave the pool
  accounting: block membership is fixed per request; the winner's
  verify window is copied across siblings inside the step.
* ``sampling=`` on admission turns a row sampled: per-request
  :class:`SamplingParams` (seed / temperature / top-k / top-p and the
  grammar ``mask_fn`` hook) live as batched DEVICE arrays the one
  compiled step reads — param churn never retraces (``trace_count``
  stays 1). Every sampled token's threefry key is a pure function of
  ``(seed, position)`` (:func:`tpusystem.train.generate.sampling_key`),
  so the journal's emitted prefix is the ONLY replay state: a replayed,
  rerouted, or hedged row reproduces the identical sample stream
  bitwise on any engine. ``temperature == 0`` (the default) is the
  plain greedy argmax, bitwise-unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from tpusystem.serve.kvcache import (PagedKVCache, _is_kv, adopt_prefill,
                                     pool_shardings, write_tables)
from tpusystem.train.cursors import gather_rows, is_cursor, read_cursor, rewind
from tpusystem.train.decode_fused import (build_fused_paged_step,
                                          fused_paged_reason)
from tpusystem.train.generate import (_decoder, _dequant, _stream_params,
                                      sample_token)


class Saturated(RuntimeError):
    """No free row or not enough free blocks — the request must stay
    queued (the scheduler's job), never crash the engine."""


class UnseededSampling(ValueError):
    """A ``temperature > 0`` request with no seed: its stream would be
    non-reproducible, which vacates every replay/reroute/hedging
    guarantee this stack makes — refused typed at the front door
    (router, scheduler, AND engine) instead of silently degrading to a
    divergent duplicate. Subclasses ``ValueError`` so existing
    caller-error handling (trace closed ``'invalid'``, re-raise)
    applies unchanged."""


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request decode-sampling controls, journal-replayable.

    Rides the request through scheduler, journal, handoff, and hedging:
    a seeded request's token at stream position ``p`` is a pure
    function of ``(seed, p)`` plus the emitted prefix, so replay needs
    no RNG state beyond what the journal already records.

    Attributes:
        seed: threefry counter seed. Required when ``temperature > 0``
            (an unseeded sampled request raises
            :class:`UnseededSampling` at submit); ignored at
            ``temperature == 0``.
        temperature: 0 (default) is greedy argmax — bitwise the
            engine's classic path; > 0 scales logits before sampling.
        top_k: keep only the k highest logits (0 = no top-k filter).
        top_p: nucleus filter — keep the smallest sorted prefix whose
            cumulative mass reaches ``top_p`` (1.0 = no filter).
        mask_fn: the structured-output hook — a picklable
            **module-level** callable ``(emitted: list[int]) ->
            bool[vocab]`` (journal replay re-imports it) evaluated
            host-side before every sampled position; ``False`` tokens
            are excluded before temperature/top-k/top-p. Must allow at
            least one token (an all-False mask is a typed caller
            error — give the grammar an escape hatch such as EOS).
            Composes with greedy too (masked argmax). Does NOT compose
            with speculative rows (the mask cannot update inside a
            multi-token verify window — typed at admit).
    """
    seed: int | None = None
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    mask_fn: object = None

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(
                f'temperature must be >= 0, got {self.temperature}')
        if self.top_k < 0:
            raise ValueError(f'top_k must be >= 0, got {self.top_k}')
        if not 0 < self.top_p <= 1:
            raise ValueError(
                f'top_p must be in (0, 1], got {self.top_p}')

    @property
    def sampled(self) -> bool:
        """True when this request actually samples (``temperature > 0``)."""
        return self.temperature > 0


def engine_unsupported_reason(module) -> str | None:
    """None when the paged engine can serve this module, else why not
    (the ``fused_unsupported_reason`` capability-gate discipline).

    Served today: both family LMs (GPT2 / Llama, unrolled), including
    **MoE** stacks — decode-mode expert dispatch runs at full capacity
    (capacity = the step's token count, so routing never drops a token
    and each token's expert mix is independent of co-batched traffic;
    :class:`tpusystem.ops.moe.MoEMLP` ``full_capacity``). The remaining
    gate is layout, not architecture."""
    for field in ('decode', 'max_seq', 'per_row_decode', 'decode_pages'):
        if not hasattr(module, field):
            return (f'module {type(module).__name__} has no {field!r} '
                    'field — the engine needs the family decode '
                    'conventions (GPT2 / Llama)')
    if getattr(module, 'scan_layers', False):
        return ('scan_layers stacks the per-layer caches at a leading '
                'layer dim; the engine admission writes are unrolled-'
                'layout only — serve the unrolled module')
    return None


def prefill_bucket(length: int, block_size: int, max_seq: int) -> int:
    """Pad-to-bucket width for a prompt: the smallest power-of-2 at
    least ``max(length, block_size)``, capped at ``max_seq`` — so a
    stream of varied prompt lengths compiles a **bounded** set of
    prefill programs (the retrace-trap discipline) instead of one per
    length."""
    bucket = max(length, block_size)
    bucket = 1 << (bucket - 1).bit_length()
    return min(bucket, max_seq)


@functools.cache
def _compiled_prefill(decoder, bucket: int):
    """One compiled prefill program per (decode clone, pad bucket) —
    ``cache_info()`` is the compile-count witness the bucketing tests
    pin."""
    return _build_prefill(decoder, bucket)


def _build_prefill(decoder, bucket: int):
    del bucket          # part of the cache key; shapes key the jit cache

    @jax.jit
    def run(params, padded, length, seed, position, temp, topk, topp, mask):
        # plain contiguous prefill: one causal pass over the padded
        # prompt builds every layer's [1, max_seq, ...] KV strip; the
        # right-pad junk is causally invisible to the real positions
        logits, state = decoder.apply(
            {'params': _dequant(params, decoder)}, padded,
            mutable=['cache'])
        # the first token samples at the row's own (seed, position)
        # counter — greedy defaults reproduce the classic argmax bitwise
        first = sample_token(logits[0, length - 1], seed, position, temp,
                             topk, topp, mask)
        return first, state['cache']

    return run


@functools.cache
def _compiled_resume(decoder, bucket: int):
    return _build_resume(decoder, bucket)


def _build_resume(decoder, bucket: int):
    """The shared-prefix **resume prefill**: seed a contiguous decode
    cache with the row's already-cached prefix KV (gathered from the
    paged pool through the row's slot map) and its cursors at the cached
    depth, then apply only the padded SUFFIX — ``cached_attention``
    takes its decode path (the cache variables pre-exist), whose
    bucketed masked read equals the full causal prefill in
    window-length-invariant arithmetic, so the suffix logits — and the
    request's first token — are exactly the full prefill's. One program
    per suffix pad bucket. (Caveat, documented in docs/serving.md:
    prompts whose FULL prefill would route the flash kernel — length >=
    512 — mix flash-era prefix KV with the einsum decode read, exact
    only up to the platform's near-tie argmax tolerance.)"""
    del bucket          # part of the cache key; shapes key the jit cache
    shapes = jax.eval_shape(
        functools.partial(decoder.init, jax.random.PRNGKey(0)),
        jnp.zeros((1, 1), jnp.int32))['cache']

    @jax.jit
    def run(params, cache, slots, padded, cached_len, suffix_len,
            seed, position, temp, topk, topp, mask):
        source = {jax.tree_util.keystr(path): leaf for path, leaf
                  in jax.tree_util.tree_leaves_with_path(cache)}
        keep = jnp.arange(decoder.max_seq) < cached_len

        def seed_leaf(path, leaf):
            if _is_kv(path):
                pool = source[jax.tree_util.keystr(path)]
                strip = jnp.take(pool, slots, axis=0)    # [max_seq, h, d]
                strip = jnp.where(keep[:, None, None], strip, 0)
                return strip[None].astype(leaf.dtype)
            if is_cursor(path):
                return jnp.full(leaf.shape, cached_len, leaf.dtype)
            return jnp.zeros(leaf.shape, leaf.dtype)

        resumed = jax.tree_util.tree_map_with_path(seed_leaf, shapes)
        logits, state = decoder.apply(
            {'params': _dequant(params, decoder), 'cache': resumed},
            padded, mutable=['cache'])
        first = sample_token(logits[0, suffix_len - 1], seed, position,
                             temp, topk, topp, mask)
        return first, state['cache']

    return run


@functools.partial(jax.jit, donate_argnums=(0,))
def _adopt_draft_rows(dcache, prefill_cache, rows, length):
    """Seat a draft prefill strip in ``rows`` of the contiguous per-row
    draft cache (every branch row of one speculative group gets the same
    prompt KV): KV leaves overwrite whole row strips, cursor leaves set
    to the prompt length. Fixed shapes — one compiled program."""
    source = {jax.tree_util.keystr(path): leaf for path, leaf
              in jax.tree_util.tree_leaves_with_path(prefill_cache)}

    def fix(path, leaf):
        if _is_kv(path):
            strip = source[jax.tree_util.keystr(path)]   # [1, S, h, d]
            wide = jnp.broadcast_to(strip,
                                    (rows.shape[0],) + strip.shape[1:])
            return leaf.at[rows].set(wide.astype(leaf.dtype))
        if is_cursor(path):
            return leaf.at[rows].set(jnp.asarray(length, leaf.dtype))
        return leaf
    return jax.tree_util.tree_map_with_path(fix, dcache)


def _copy_winner_windows(cache, win_rows_wide, cursor, speculate: int,
                         block: int, max_blocks: int):
    """Token-tree verify's winner-copy, paged-pool flavored: every
    branch row's verify window (positions ``cursor .. cursor +
    speculate``, all past the shared prompt region) is overwritten from
    its group winner's window — a pool gather + scatter through each
    row's OWN block table, so losers' private decode blocks inherit the
    winning branch's KV and block membership never changes (no free-list
    traffic inside the step). Past-allocation positions map to trash on
    both sides (dead copies)."""
    positions = cursor[:, None] + jnp.arange(speculate + 1)[None, :]
    logical = jnp.minimum(positions // block, max_blocks - 1)

    def walk(node):
        if isinstance(node, dict) and 'table' in node and 'key' in node:
            table = node['table']
            dst_phys = jnp.take_along_axis(table, logical, axis=1)
            src_phys = jnp.take_along_axis(
                jnp.take(table, win_rows_wide, axis=0), logical, axis=1)
            dst = (dst_phys * block + positions % block).reshape(-1)
            src = (src_phys * block + positions % block).reshape(-1)
            out = dict(node)
            for name in ('key', 'value'):
                pool = node[name]
                out[name] = pool.at[dst].set(jnp.take(pool, src, axis=0))
            return out
        if isinstance(node, dict):
            return {name: walk(child) for name, child in node.items()}
        return node
    return walk(cache)


@dataclasses.dataclass
class Admission:
    """What :meth:`Engine.admit` hands back: the row the request landed
    in, its first token (from the prefill logits), and whether that
    token already completed it (``max_new == 1`` or a stop hit)."""
    row: int
    token: int
    finished: bool
    reason: str | None = None       # 'length' | 'stop' when finished


@dataclasses.dataclass
class StepReport:
    """One engine step: ``emitted`` maps row -> the LIST of new tokens
    for every row that was active (one token on the plain step; up to
    ``speculate + 1`` on a speculative step), ``finished`` lists the
    rows retired this step — ``(row, reason, tokens)`` triples, already
    evicted by the time the report returns (the tokens ride out with the
    report because eviction frees the row's state)."""
    emitted: dict
    finished: list                   # [(row, reason, tokens), ...]


@dataclasses.dataclass
class _RowState:
    tokens: list
    max_new: int
    stop: int | None
    tag: object = None               # opaque caller handle (request id)
    sampling: object = None          # SamplingParams | None (greedy)
    prior: tuple = ()                # tokens emitted in a previous life
    #                                  (replay prefix) — position and
    #                                  mask_fn both see prior + tokens


class Engine:
    """The continuous-batching engine over one model's param tree.

    Args:
        module: a family LM module (GPT2 / Llama conventions, MoE
            included; see :func:`engine_unsupported_reason` for the
            scope gate).
        params: trained parameters.
        rows: fixed decode batch width — the compiled step's shape.
        block_size: tokens per KV block.
        blocks: physical blocks in the pool (including the reserved
            trash block 0). Default sizes the pool to back every row at
            full ``max_seq`` depth; smaller pools oversubscribe capacity
            and rely on the scheduler to queue.
        stream_dtype: :func:`tpusystem.train.generate.generate`'s
            weight-streaming lever, applied to the engine's param tree
            ('int8' for the serving default on HBM-bound chips).
        decode_impl: ``'flax'`` | ``'fused'`` | ``'auto'`` — the step
            implementation. ``'fused'`` is the Pallas fused paged step
            (module docstring; raises where
            :func:`tpusystem.train.decode_fused.fused_paged_reason`
            names a gate); ``'auto'`` picks fused on TPU-class backends
            when supported, flax otherwise.
        share_prefix: enable the radix prefix index — co-batched (and
            successive) requests sharing a prompt prefix share KV blocks
            and prefill only their uncached suffix.
        draft_module / draft_params: a cheap draft LM switches the step
            to speculative rows (module docstring) — the output stays
            exactly the target's sequential decode, greedy and
            seeded-sampled alike (``mask_fn`` does not compose;
            a grammar mask cannot update inside a multi-token verify
            window). ``decode_impl='fused'`` does not compose (the
            verify forward is the flax paged step).
        speculate: draft tokens proposed per speculative step.
        tree_fanout: branch rows per request (token-tree verify);
            ``rows`` must be a multiple.
        mesh: a :class:`~tpusystem.parallel.mesh.MeshSpec` or built
            :class:`jax.sharding.Mesh` to TP-shard the compiled steps
            over — params placed by the module's ``partition_rules()``,
            the paged KV pool sharded over heads
            (:func:`~tpusystem.serve.kvcache.pool_shardings`), block
            tables replicated so the host pool stays the one authority.
            Only the ``model`` axis may exceed 1
            (:func:`~tpusystem.parallel.schedule.decode_tp_plan` is the
            gate); ``decode_impl='fused'`` raises under TP (no ring arms
            yet — ``'auto'`` serves the sharded flax step, token-exact
            vs single-device).
        schedule: an :class:`~tpusystem.parallel.schedule.OverlapSchedule`
            threaded onto the decode/prefill clones — per-shape
            ``schedule_applicable`` gating decides whether any program
            takes the manual shard_map path (decode's ``[rows, 1]``
            shapes typically fall back to GSPMD; prefill buckets may
            qualify).

    The decode step traces exactly once per engine (``trace_count`` is
    the witness); admissions and evictions are host-side table edits
    plus fixed-shape device writes.
    """

    def __init__(self, module, params, *, rows: int = 4,
                 block_size: int = 16, blocks: int | None = None,
                 stream_dtype: str = 'auto', decode_impl: str = 'auto',
                 share_prefix: bool = False, draft_module=None,
                 draft_params=None, speculate: int = 4,
                 tree_fanout: int = 1, mesh=None, schedule=None) -> None:
        reason = engine_unsupported_reason(module)
        if reason is not None:
            raise ValueError(f'the serving engine cannot run this module: '
                             f'{reason}')
        self.rows, self.block_size = rows, block_size
        self.max_seq = module.max_seq
        if blocks is None:
            blocks = rows * (self.max_seq // block_size) + 1
        self.stream_dtype = stream_dtype
        self.share_prefix = share_prefix
        self.speculate, self.tree_fanout = speculate, tree_fanout
        self._spec = draft_module is not None
        self.mesh, self.tp_plan = self._resolve_mesh(mesh)
        if self._spec and self.tp_plan.path == 'gspmd':
            raise ValueError(
                'mesh= does not compose with speculative rows yet — the '
                'draft cache has no sharding contract; serve the plain '
                'engine under TP')
        if self._spec:
            if speculate < 1:
                raise ValueError(f'speculate must be >= 1, got {speculate}')
            if tree_fanout < 1:
                raise ValueError(
                    f'tree_fanout must be >= 1, got {tree_fanout}')
            if tree_fanout > draft_module.vocab_size:
                raise ValueError(f'tree_fanout ({tree_fanout}) exceeds the '
                                 f'draft vocab ({draft_module.vocab_size})')
            if rows % tree_fanout:
                raise ValueError(f'rows ({rows}) must be a multiple of '
                                 f'tree_fanout ({tree_fanout}) — each '
                                 'request owns fanout adjacent branch rows')
        self._prefiller = _decoder(module)     # contiguous, shared-cursor
        self._decoder = dataclasses.replace(
            _decoder(module, per_row=True),
            decode_pages=(blocks, block_size))
        if self.tp_plan.path == 'gspmd':
            # re-attach what _decoder deliberately dropped: the live mesh
            # (unhashable — the compile caches' TypeError fallback absorbs
            # it) and the overlap schedule, on BOTH clones so prefill and
            # decode shard identically
            self._prefiller = dataclasses.replace(
                self._prefiller, mesh=self.mesh, schedule=schedule)
            self._decoder = dataclasses.replace(
                self._decoder, mesh=self.mesh, schedule=schedule)
        self._params = _stream_params(self._decoder, params, stream_dtype)
        if self.tp_plan.path == 'gspmd':
            from tpusystem.parallel.sharding import TensorParallel
            self._params = TensorParallel(module.partition_rules()).place(
                self._params, self.mesh)
        self.decode_impl = self._resolve_decode_impl(decode_impl)
        self.pool = PagedKVCache(rows, blocks, block_size, self.max_seq,
                                 share_prefix=share_prefix)
        shapes = jax.eval_shape(
            functools.partial(self._decoder.init, jax.random.PRNGKey(0)),
            jnp.zeros((rows, 1), jnp.int32))['cache']
        self._cache = jax.tree.map(
            lambda leaf: jnp.zeros(leaf.shape, leaf.dtype), shapes)
        if self.tp_plan.path == 'gspmd':
            self._cache = jax.device_put(
                self._cache, pool_shardings(self._cache, self.mesh))
        # free seats: representative rows — every row when linear, the
        # first row of each fanout-wide adjacent group when speculative
        stride = self.tree_fanout if self._spec else 1
        self._free_rows = list(range(rows - stride, -1, -stride))
        # host mirrors for bookkeeping; the device copies are what the
        # step consumes (tokens feed back device-to-device — the per-
        # step host round trip is ONLY the emitted-token read)
        self._tokens = np.zeros(rows, np.int32)
        self._active = np.zeros(rows, bool)
        self._tokens_dev = jnp.zeros(rows, jnp.int32)
        self._active_dev = jnp.zeros(rows, bool)
        # per-row sampling params as batched device arrays: the one
        # compiled step reads them, admission/eviction edit them with
        # fixed-shape .at[] writes — param churn never retraces. Greedy
        # defaults (temp 0, no filters, all-True mask) make an idle or
        # unsampled row bitwise the classic argmax path.
        self.vocab = module.vocab_size
        self._seed_dev = jnp.zeros(rows, jnp.uint32)
        self._pos_dev = jnp.zeros(rows, jnp.int32)
        self._temp_dev = jnp.zeros(rows, jnp.float32)
        self._topk_dev = jnp.zeros(rows, jnp.int32)
        self._topp_dev = jnp.ones(rows, jnp.float32)
        self._mask_dev = jnp.ones((rows, self.vocab), bool)
        if self.tp_plan.path == 'gspmd':
            from jax.sharding import NamedSharding, PartitionSpec
            everywhere = NamedSharding(self.mesh, PartitionSpec())
            self._tokens_dev = jax.device_put(self._tokens_dev, everywhere)
            self._active_dev = jax.device_put(self._active_dev, everywhere)
            for name in ('_seed_dev', '_pos_dev', '_temp_dev', '_topk_dev',
                         '_topp_dev', '_mask_dev'):
                setattr(self, name,
                        jax.device_put(getattr(self, name), everywhere))
        self._rowstate: dict[int, _RowState] = {}
        self._prefills: dict[object, object] = {}  # unhashable-module path
        self._resumes: dict[int, object] = {}
        self.trace_count = 0
        self.timings = {'prefill': 0.0, 'admit': 0.0, 'step': 0.0}
        # prefix-sharing effectiveness counters (the bench's
        # prefix_hit_rate reads these)
        self.sharing = {'admissions': 0, 'prefix_hits': 0,
                        'prompt_tokens': 0, 'shared_tokens': 0,
                        'resumed_prefills': 0}
        # wall seconds of the most recent decode dispatch (admission and
        # prefill excluded) — the decode-only probe for a custom serving
        # loop that wants to feed failover.StepWatchdog.observe the step
        # alone (ServingReplica's built-in watchdog watches the whole
        # tick on its injectable clock instead)
        self.last_step_seconds = 0.0

        if self._spec:
            self._drafter = _decoder(draft_module, per_row=True)
            self._draft_prefiller = _decoder(draft_module)
            self._dparams = _stream_params(self._drafter, draft_params,
                                           stream_dtype)
            dshapes = jax.eval_shape(
                functools.partial(self._drafter.init,
                                  jax.random.PRNGKey(0)),
                jnp.zeros((rows, 1), jnp.int32))['cache']
            self._dcache = jax.tree.map(
                lambda leaf: jnp.zeros(leaf.shape, leaf.dtype), dshapes)
            self._spec_step = jax.jit(self._build_spec_step(),
                                      donate_argnums=(2, 3))
            self._step = None
            return

        # every row samples at its own (seed, position) counter; greedy
        # rows (temp 0) take the argmax branch of the same program
        sample_rows = jax.vmap(sample_token)

        if self.decode_impl == 'fused':
            fused = build_fused_paged_step(self._decoder)

            def step_fn(params, cache, tokens, active, seed, pos, temp,
                        topk, topp, mask):
                self.trace_count += 1        # runs at trace time only
                logits, updated = fused(params, cache, tokens)
                token = sample_rows(logits, seed, pos, temp, topk, topp,
                                    mask)
                cursor = read_cursor(cache)
                return (token,
                        rewind(updated, jnp.where(active, cursor + 1, 0)),
                        jnp.where(active, pos + 1, pos))
        else:
            def step_fn(params, cache, tokens, active, seed, pos, temp,
                        topk, topp, mask):
                self.trace_count += 1        # runs at trace time only
                logits, updated = self._decoder.apply(
                    {'params': _dequant(params, self._decoder),
                     'cache': cache},
                    tokens[:, None], mutable=['cache'])
                token = sample_rows(logits[:, -1], seed, pos, temp, topk,
                                    topp, mask)
                # park retired rows' cursors at 0 so their dead writes
                # stay in the trash block's first slots instead of
                # walking off the table; active rows keep the cursor
                # cached_attention advanced
                cursor = read_cursor(cache)
                return (token,
                        rewind(updated['cache'],
                               jnp.where(active, cursor + 1, 0)),
                        jnp.where(active, pos + 1, pos))

        self._step = jax.jit(step_fn, donate_argnums=(1,))

    @staticmethod
    def _resolve_mesh(mesh):
        """Build a MeshSpec, pass a live Mesh through, and gate via
        :func:`~tpusystem.parallel.schedule.decode_tp_plan` — the typed
        'unsupported' plan (any non-``model`` axis > 1) raises here, so
        an engine that constructs is an engine whose sharding works."""
        from tpusystem.parallel.schedule import decode_tp_plan
        if mesh is not None and hasattr(mesh, 'build'):
            mesh = mesh.build()
        plan = decode_tp_plan(mesh)
        if plan.path == 'unsupported':
            raise ValueError(
                f'the serving engine cannot shard over this mesh: '
                f'{plan.reason}')
        return (mesh if plan.path == 'gspmd' else None), plan

    def _resolve_decode_impl(self, decode_impl: str) -> str:
        if decode_impl not in ('auto', 'flax', 'fused'):
            raise ValueError(f"decode_impl must be 'auto', 'flax' or "
                             f"'fused', got {decode_impl!r}")
        if decode_impl == 'flax':
            return 'flax'
        reason = fused_paged_reason(self._decoder)
        if decode_impl == 'fused':
            if self._spec:
                raise ValueError(
                    "decode_impl='fused' does not compose with "
                    'speculative rows — the tree-verify forward is the '
                    'flax paged step (fused composes with share_prefix '
                    'and int8/fp8 streaming)')
            if reason is not None:
                raise ValueError(f"decode_impl='fused' unsupported: "
                                 f'{reason}')
            return 'fused'
        if self._spec or reason is not None:
            return 'flax'
        return ('fused' if jax.default_backend() in ('tpu', 'axon')
                else 'flax')

    # ---------------------------------------------------------- speculative

    def _build_spec_step(self):
        """The speculative-rows step: K+1 fanning draft steps on the
        contiguous per-row draft cache, ONE flax paged verify forward
        over every branch's ``[K+1]`` window, winner selection per
        adjacent fanout group, in-pool winner-window copy, and both
        caches rewound to the accepted depth. Emits ``[groups, K+1]``
        tokens (accepted prefix + correction, zero-padded) plus the
        per-group acceptance count."""
        decoder, drafter = self._decoder, self._drafter
        K, F = self.speculate, self.tree_fanout
        rows, groups = self.rows, self.rows // self.tree_fanout
        block = self.block_size
        max_blocks = self.max_seq // block
        branch = jnp.arange(rows) % F

        def spec_step(params, dparams, cache, dcache, tokens, active,
                      seed, pos, temp, topk, topp, mask):
            self.trace_count += 1            # runs at trace time only
            cursor0 = read_cursor(cache)

            def draft_step(state, step_index):
                dc, tok = state
                logits, updated = drafter.apply(
                    {'params': _dequant(dparams, drafter), 'cache': dc},
                    tok[:, None], mutable=['cache'])
                logits = logits[:, -1]
                # step 0 fans the tree out: sibling rows see identical
                # logits, branch f takes the f-th most probable token;
                # later steps continue each branch greedily
                _, top = jax.lax.top_k(logits, F)
                fanned = jnp.take_along_axis(
                    top, branch[:, None], axis=1)[:, 0]
                greedy = jnp.argmax(logits, axis=-1)
                nxt = jnp.where(step_index == 0, fanned,
                                greedy).astype(jnp.int32)
                return (updated['cache'], nxt), nxt

            # K+1 draft steps (not K): a fully accepted winner's draft
            # cache must already hold d_K's KV for the next round
            (dcache, _), drafts = jax.lax.scan(
                draft_step, (dcache, tokens), jnp.arange(K + 1))
            drafts = jnp.moveaxis(drafts, 0, 1)[:, :K]   # [rows, K]

            # one target forward verifies every branch of every request
            window = jnp.concatenate([tokens[:, None], drafts], axis=1)
            vlogits, tupdated = decoder.apply(
                {'params': _dequant(params, decoder), 'cache': cache},
                window, mutable=['cache'])

            # verify SAMPLES each window slot j at its own counter
            # (seed, pos + j): the accepted prefix + correction is then
            # exactly the sequential sampled stream — a greedy draft
            # token is accepted iff it equals the sampled target choice,
            # so mismatched drafts cost speed, never the stream. Greedy
            # rows (temp 0) reduce to the classic argmax verify bitwise.
            def sample_window(logits_w, seed_r, pos_r, temp_r, topk_r,
                              topp_r, mask_r):
                offsets = pos_r + jnp.arange(K + 1)
                return jax.vmap(
                    lambda logits_j, pos_j: sample_token(
                        logits_j, seed_r, pos_j, temp_r, topk_r, topp_r,
                        mask_r))(logits_w, offsets)

            candidates = jax.vmap(sample_window)(vlogits, seed, pos, temp,
                                                 topk, topp, mask)
            matches = (drafts == candidates[:, :K]).astype(jnp.int32)
            accepted = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)

            # the longest accepted prefix wins its group; argmax ties
            # resolve to the lowest branch id = the draft's most
            # probable branch
            per_group = accepted.reshape(groups, F)
            winner = jnp.argmax(per_group, axis=1).astype(jnp.int32)
            accepted_w = jnp.max(per_group, axis=1)      # [G]
            win_rows = jnp.arange(groups) * F + winner
            drafts_w = jnp.take(drafts, win_rows, axis=0)
            correction = jnp.take_along_axis(
                jnp.take(candidates, win_rows, axis=0),
                accepted_w[:, None], axis=1)[:, 0]
            positions = jnp.arange(K + 1)[None, :]
            emitted = jnp.where(
                positions < accepted_w[:, None],
                jnp.pad(drafts_w, ((0, 0), (0, 1))),
                jnp.where(positions == accepted_w[:, None],
                          correction[:, None], 0))       # [G, K+1]
            next_token = jnp.take_along_axis(
                emitted, accepted_w[:, None], axis=1)[:, 0]

            advance = jnp.where(active[::F], accepted_w + 1, 0)
            new_cursor = jnp.where(active,
                                   cursor0 + jnp.repeat(advance, F), 0)
            tcache = tupdated['cache']
            rowmap = jnp.repeat(win_rows, F)
            if F > 1:
                # losing branches inherit the winner's verify window
                # through their OWN tables (private decode blocks; block
                # membership is fixed — no in-step free-list traffic)
                tcache = _copy_winner_windows(tcache, rowmap, cursor0, K,
                                              block, max_blocks)
            tcache = rewind(tcache, new_cursor)
            dcache = rewind(gather_rows(dcache, rowmap), new_cursor)
            wide_next = jnp.repeat(next_token, F)
            new_tokens = jnp.where(active, wide_next, tokens)
            new_pos = jnp.where(active, pos + jnp.repeat(advance, F), pos)
            return emitted, accepted_w, new_tokens, tcache, dcache, new_pos

        return spec_step

    # ------------------------------------------------------------ admission

    @property
    def free_rows(self) -> int:
        return len(self._free_rows)

    @property
    def active_rows(self) -> int:
        return int(self._active.sum())

    @property
    def sampled_rows(self) -> int:
        """Seated requests currently decoding with ``temperature > 0``
        (the observability plane's sampled-traffic gauge)."""
        return sum(1 for state in self._rowstate.values()
                   if state.sampling is not None and state.sampling.sampled)

    def can_admit(self, prompt_len: int, max_new: int,
                  prompt=None) -> bool:
        """Whether an admission of this shape would seat right now.
        Pass the prompt tokens to account for prefix sharing (matched
        blocks don't need allocating); without them the estimate is
        conservative. Optimism is safe either way — :meth:`admit` rolls
        a mid-flight shortfall back into :class:`Saturated`."""
        if not self._free_rows:
            return False
        tokens = prompt_len + max_new
        needed = self.pool.blocks_for(tokens)
        if needed > self.pool.max_blocks:
            return False
        fanout = self.tree_fanout if self._spec else 1
        if self.share_prefix and prompt is not None:
            matched = (self.pool.adoptable_prefix(prompt)[0]
                       // self.block_size)
            # later branches also match the blocks the first branch
            # registers (every fully-prompt-covered block)
            sibling = max(matched, (prompt_len - 1) // self.block_size)
            total = (needed - matched) + (fanout - 1) * (needed - sibling)
        else:
            total = fanout * needed
        return total <= self.pool.free_blocks

    def bucket(self, prompt_len: int) -> int:
        return prefill_bucket(prompt_len, self.block_size, self.max_seq)

    def prefix_cached_len(self, prompt) -> int:
        """How many leading prompt tokens the radix index would serve
        from cache if this prompt were admitted now (0 without
        sharing) — the scheduler's suffix-budget and the router's
        prefix-affinity probe."""
        if not self.share_prefix:
            return 0
        return self.pool.adoptable_prefix(prompt)[0]

    def admit_cost(self, prompt) -> int:
        """Prefill pad-bucket cost of admitting ``prompt``: the bucket
        of its UNCACHED suffix under prefix sharing, of the whole prompt
        otherwise. Never zero — a fully-cached prompt still prefills at
        least one token (its first-token logits), so suffix-budgeted
        admission can't spin on free admissions."""
        suffix = max(len(prompt) - self.prefix_cached_len(prompt), 1)
        return self.bucket(suffix)

    def _greedy_ops(self, vocab: int):
        """The greedy-default sampling operands: what an unsampled (or
        draft) prefill passes so its first-token choice is bitwise the
        classic argmax."""
        return (jnp.uint32(0), jnp.int32(0), jnp.float32(0.0),
                jnp.int32(0), jnp.float32(1.0), jnp.ones(vocab, bool))

    def _grammar_mask(self, sampling, stream: list):
        """Evaluate ``mask_fn`` over the emitted stream so far and
        validate its contract (bool ``[vocab]``, at least one token
        allowed) — all-True when the request has no mask."""
        if sampling is None or sampling.mask_fn is None:
            return jnp.ones(self.vocab, bool)
        mask = np.asarray(sampling.mask_fn(list(stream)), bool).reshape(-1)
        if mask.shape[0] != self.vocab:
            raise ValueError(
                f'mask_fn returned {mask.shape[0]} entries, the vocab is '
                f'{self.vocab}')
        if not mask.any():
            raise ValueError(
                'mask_fn allowed no token after '
                f'{len(stream)} emitted — a grammar must always leave an '
                'escape hatch (e.g. its stop token)')
        return jnp.asarray(mask)

    def _sampling_ops(self, sampling, emitted):
        """jnp-typed per-request sampling operands for the FIRST token —
        position ``len(emitted)`` (the stream slots already journaled in
        a previous life), scalars typed so jitted programs never retrace
        on Python weak types."""
        position = len(emitted)
        if sampling is None:
            return (jnp.uint32(0), jnp.int32(position), jnp.float32(0.0),
                    jnp.int32(0), jnp.float32(1.0),
                    jnp.ones(self.vocab, bool))
        return (jnp.uint32(sampling.seed or 0), jnp.int32(position),
                jnp.float32(sampling.temperature),
                jnp.int32(sampling.top_k), jnp.float32(sampling.top_p),
                self._grammar_mask(sampling, list(emitted)))

    def _run_prefill(self, decoder, bucket: int, padded, length: int,
                     ops=None):
        try:
            run = _compiled_prefill(decoder, bucket)
        except TypeError:        # unhashable module field (e.g. live mesh)
            run = self._prefills.setdefault(
                (decoder is self._prefiller, bucket),
                _build_prefill(decoder, bucket))
        if ops is None:
            ops = self._greedy_ops(decoder.vocab_size)
        return run(self._params if decoder is self._prefiller
                   else self._dparams, jnp.asarray(padded), length, *ops)

    def _prefill_rows(self, prompt, rows: list[int], ops):
        """Target prefill for an admission already seated in the pool:
        the resume program over the uncached suffix when the first row
        adopted a shareable prefix (and the suffix window fits), the
        plain full-prompt program otherwise. Returns the first token and
        the contiguous strip to adopt (valid at every prompt position at
        or past each row's own shared depth)."""
        shared = self.pool.shared_tokens(rows[0])
        suffix = prompt.size - shared
        if shared and shared + self.bucket(suffix) <= self.max_seq:
            sbucket = self.bucket(suffix)
            padded = np.zeros((1, sbucket), np.int32)
            padded[0, :suffix] = prompt[shared:]
            try:
                run = _compiled_resume(self._prefiller, sbucket)
            except TypeError:    # unhashable module field (e.g. live mesh)
                run = self._resumes.setdefault(
                    sbucket, _build_resume(self._prefiller, sbucket))
            first, prefill_cache = run(
                self._params, self._cache,
                jnp.asarray(self.pool.slots(rows[0])),
                jnp.asarray(padded), shared, suffix, *ops)
            self.sharing['resumed_prefills'] += 1
            return first, prefill_cache
        bucket = self.bucket(prompt.size)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :prompt.size] = prompt
        return self._run_prefill(self._prefiller, bucket, padded,
                                 prompt.size, ops)

    def _validate(self, prompt, max_new: int, sampling=None) -> None:
        if prompt.size < 1:
            raise ValueError('empty prompt')
        if max_new < 1:
            raise ValueError(f'max_new must be >= 1, got {max_new}')
        if prompt.size + max_new > self.max_seq:
            raise ValueError(
                f'prompt ({prompt.size}) + max_new ({max_new}) exceeds the '
                f'cache capacity max_seq={self.max_seq}')
        self._validate_sampling(sampling)
        if self._spec:
            needed = prompt.size + max_new + self.speculate + 1
            if needed > self._drafter.max_seq:
                raise ValueError(
                    f'prompt + max_new + speculate + 1 = {needed} exceeds '
                    f'the draft cache capacity max_seq='
                    f'{self._drafter.max_seq} (the draft overshoots by up '
                    'to speculate tokens before rewinding)')

    def _validate_sampling(self, sampling) -> None:
        if sampling is None:
            return
        if sampling.sampled and sampling.seed is None:
            raise UnseededSampling(
                f'temperature {sampling.temperature} with no seed: the '
                'stream would not be reproducible, so journal replay, '
                'reroute, and hedging could not keep their token-exact '
                'contract — pass SamplingParams(seed=...)')
        if self._spec and sampling.mask_fn is not None:
            raise ValueError(
                'mask_fn does not compose with speculative rows — a '
                'grammar mask cannot update inside a multi-token verify '
                'window; serve structured requests on the plain engine')

    def _seat(self, prompt, max_new: int) -> tuple[int, list[int]]:
        """Claim a free row group and seat it in the pool (rolled back
        whole on a mid-flight block shortfall) — the Saturated half of
        admission, shared by :meth:`admit` and :meth:`admit_prefilled`."""
        if not self._free_rows:
            raise Saturated('no free row')
        if not self.can_admit(prompt.size, max_new, prompt=prompt):
            raise Saturated(
                f'{self.pool.blocks_for(prompt.size + max_new)} blocks '
                f'needed per row, {self.pool.free_blocks} free')

        fanout = self.tree_fanout if self._spec else 1
        rep = self._free_rows.pop()
        rows = list(range(rep, rep + fanout))
        tokens = prompt.size + max_new
        seated = []
        try:
            for row in rows:
                self.pool.admit(row, tokens,
                                prompt=prompt if self.share_prefix
                                else None)
                seated.append(row)
        except ValueError:
            for row in seated:
                self.pool.evict(row)
            self._free_rows.append(rep)
            raise Saturated(
                f'{self.pool.blocks_for(tokens)} blocks needed per row, '
                f'{self.pool.free_blocks} free') from None
        return rep, rows

    def _register(self, rep: int, rows: list[int], prompt, first: int,
                  max_new: int, stop_token: int | None, tag,
                  sampling=None, emitted=()) -> Admission:
        """The host-side admission tail: sharing counters, row state,
        sampling device arrays, token/active mirrors, and the
        admitted-already-finished check."""
        fanout = self.tree_fanout if self._spec else 1
        self.sharing['admissions'] += 1
        self.sharing['prompt_tokens'] += int(prompt.size) * fanout
        shared_total = sum(self.pool.shared_tokens(row) for row in rows)
        self.sharing['shared_tokens'] += shared_total
        self.sharing['prefix_hits'] += bool(shared_total)

        seed = 0 if sampling is None or sampling.seed is None \
            else sampling.seed
        temp = 0.0 if sampling is None else sampling.temperature
        topk = 0 if sampling is None else sampling.top_k
        topp = 1.0 if sampling is None else sampling.top_p
        # the NEXT token's stream position: `first` just landed at
        # position len(emitted), so the step samples at len(emitted) + 1
        start = len(emitted) + 1
        for row in rows:
            self._tokens[row] = first
            self._active[row] = True
            self._tokens_dev = self._tokens_dev.at[row].set(first)
            self._active_dev = self._active_dev.at[row].set(True)
            self._seed_dev = self._seed_dev.at[row].set(np.uint32(seed))
            self._pos_dev = self._pos_dev.at[row].set(start)
            self._temp_dev = self._temp_dev.at[row].set(temp)
            self._topk_dev = self._topk_dev.at[row].set(topk)
            self._topp_dev = self._topp_dev.at[row].set(topp)
        self._rowstate[rep] = _RowState(tokens=[first], max_new=max_new,
                                        stop=stop_token, tag=tag,
                                        sampling=sampling,
                                        prior=tuple(emitted))
        reason = self._finish_reason(rep)
        if reason is not None:
            self.evict(rep)
            return Admission(rep, first, True, reason)
        if sampling is not None and sampling.mask_fn is not None:
            mask = self._grammar_mask(sampling, list(emitted) + [first])
            for row in rows:
                self._mask_dev = self._mask_dev.at[row].set(mask)
        return Admission(rep, first, False)

    def admit(self, prompt, max_new: int, *, stop_token: int | None = None,
              tag=None, sampling=None, emitted=()) -> Admission:
        """Prefill ``prompt`` and seat it in a free row (a free GROUP of
        ``tree_fanout`` adjacent rows when speculative). Raises
        :class:`Saturated` when no row or not enough blocks are free
        (the scheduler queues on this), ``ValueError`` on requests that
        could never fit — :class:`UnseededSampling` among them.

        ``sampling`` is the request's :class:`SamplingParams` (None =
        greedy); ``emitted`` the tokens a previous life already emitted
        for this request (journal replay passes its prefix here so
        sampling positions continue where the stream left off — the
        prompt must already include those tokens)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self._validate(prompt, max_new, sampling)
        ops = self._sampling_ops(sampling, emitted)
        rep, rows = self._seat(prompt, max_new)

        started = time.perf_counter()
        first, prefill_cache = self._prefill_rows(prompt, rows, ops)
        first = int(first)
        self.timings['prefill'] += time.perf_counter() - started

        started = time.perf_counter()
        for row in rows:
            self._cache = adopt_prefill(
                self._cache, prefill_cache,
                jnp.asarray(self.pool.adoption_slots(row)), row,
                prompt.size)
        self._cache = write_tables(self._cache, self.pool.table)
        if self._spec:
            dbucket = prefill_bucket(prompt.size, self.block_size,
                                     self._drafter.max_seq)
            padded = np.zeros((1, dbucket), np.int32)
            padded[0, :prompt.size] = prompt
            _, draft_cache = self._run_prefill(self._draft_prefiller,
                                               dbucket, padded,
                                               prompt.size)
            self._dcache = _adopt_draft_rows(self._dcache, draft_cache,
                                             jnp.asarray(rows, jnp.int32),
                                             prompt.size)
        self.timings['admit'] += time.perf_counter() - started
        return self._register(rep, rows, prompt, first, max_new,
                              stop_token, tag, sampling, emitted)

    # ------------------------------------------------- disaggregated prefill

    def export_prefill(self, prompt, *, sampling=None,
                       emitted=()) -> tuple[int, dict]:
        """Run the admission prefill WITHOUT seating a row — the
        prefill-role half of disaggregated serving. Returns ``(first,
        kv)``: the prompt's first token and every layer's contiguous KV
        strip (``keystr path -> [1, max_seq, heads, head_dim]`` numpy,
        host-side so the blob plane can ship it). The decode-role
        replica seats it with :meth:`admit_prefilled`; this engine's
        pool, rows and sharing index are untouched. A sampled request's
        first token samples at its ``(seed, len(emitted))`` counter —
        a pure function, so the prefill replica's choice is exactly
        what the decode replica would have computed itself."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError('empty prompt')
        if prompt.size >= self.max_seq:
            raise ValueError(
                f'prompt ({prompt.size}) leaves no decode room under '
                f'max_seq={self.max_seq}')
        self._validate_sampling(sampling)
        ops = self._sampling_ops(sampling, emitted)
        bucket = self.bucket(prompt.size)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :prompt.size] = prompt
        started = time.perf_counter()
        first, prefill_cache = self._run_prefill(self._prefiller, bucket,
                                                 padded, prompt.size, ops)
        first = int(first)
        self.timings['prefill'] += time.perf_counter() - started
        kv = {jax.tree_util.keystr(path): np.asarray(leaf)
              for path, leaf
              in jax.tree_util.tree_leaves_with_path(prefill_cache)
              if _is_kv(path)}
        return first, kv

    def _strip_cache(self, kv: dict):
        """Rebuild a contiguous prefill cache pytree from exported KV
        strips — the receiving half of :meth:`export_prefill`. Missing
        or misshapen strips raise ``ValueError`` (prefill and decode
        replicas must serve the same module geometry)."""
        shapes = jax.eval_shape(
            functools.partial(self._prefiller.init, jax.random.PRNGKey(0)),
            jnp.zeros((1, 1), jnp.int32))['cache']

        def fill(path, leaf):
            if not _is_kv(path):
                return jnp.zeros(leaf.shape, leaf.dtype)
            name = jax.tree_util.keystr(path)
            if name not in kv:
                raise ValueError(
                    f'handoff strip missing KV leaf {name} — prefill and '
                    'decode replicas must serve the same module')
            strip = kv[name]
            if tuple(strip.shape) != tuple(leaf.shape):
                raise ValueError(
                    f'handoff strip {name} is {tuple(strip.shape)}, this '
                    f'engine expects {tuple(leaf.shape)} — prefill and '
                    'decode replicas must serve the same module geometry')
            return jnp.asarray(strip, leaf.dtype)
        return jax.tree_util.tree_map_with_path(fill, shapes)

    def admit_prefilled(self, prompt, max_new: int, first: int, kv: dict,
                        *, stop_token: int | None = None, tag=None,
                        sampling=None, emitted=()) -> Admission:
        """Seat a request whose prefill ran on ANOTHER engine
        (:meth:`export_prefill` strips, shipped over the blob plane).
        Same contract as :meth:`admit` — Saturated when nothing is free,
        ValueError on requests that could never fit — but the only
        device work is the existing ``adopt_prefill``/``write_tables``
        admission seam: no prefill program runs here, which is the whole
        point of the disaggregated split."""
        if self._spec:
            raise ValueError(
                'admit_prefilled does not compose with speculative rows — '
                'the draft cache has no handoff strip; disaggregate the '
                'plain engine')
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self._validate(prompt, max_new, sampling)
        prefill_cache = self._strip_cache(kv)     # validate BEFORE seating
        rep, rows = self._seat(prompt, max_new)

        started = time.perf_counter()
        for row in rows:
            self._cache = adopt_prefill(
                self._cache, prefill_cache,
                jnp.asarray(self.pool.adoption_slots(row)), row,
                prompt.size)
        self._cache = write_tables(self._cache, self.pool.table)
        self.timings['admit'] += time.perf_counter() - started
        return self._register(rep, rows, prompt, int(first), max_new,
                              stop_token, tag, sampling, emitted)

    def prefix_hit_rate(self) -> float:
        """Fraction of admitted prompt tokens served from the radix
        index (0.0 before any admission)."""
        total = self.sharing['prompt_tokens']
        return self.sharing['shared_tokens'] / total if total else 0.0

    def _finish_reason(self, row: int) -> str | None:
        state = self._rowstate[row]
        if state.stop is not None and state.tokens[-1] == state.stop:
            return 'stop'
        if len(state.tokens) >= state.max_new:
            return 'length'
        return None

    # ------------------------------------------------------------- decoding

    def step(self) -> StepReport:
        """Advance every active row (one fixed-shape dispatch): one
        token per request on the plain step (greedy or sampled, per the
        row's :class:`SamplingParams`), up to ``speculate + 1`` on the
        speculative step. Retires rows that hit their length or stop
        token."""
        if not self._active.any():
            return StepReport({}, [])
        if self._spec:
            return self._spec_tick()
        started = time.perf_counter()
        token_dev, self._cache, self._pos_dev = self._step(
            self._params, self._cache, self._tokens_dev, self._active_dev,
            self._seed_dev, self._pos_dev, self._temp_dev, self._topk_dev,
            self._topp_dev, self._mask_dev)
        token = np.asarray(token_dev)
        # retired rows' stale device token stays as-is (in-vocab junk an
        # inactive row may keep embedding — masked, never emitted)
        self._tokens_dev = token_dev
        self.last_step_seconds = time.perf_counter() - started
        self.timings['step'] += self.last_step_seconds
        emitted, finished = {}, []
        for row in np.flatnonzero(self._active):
            row = int(row)
            self._tokens[row] = int(token[row])
            emitted[row] = [int(token[row])]
            state = self._rowstate[row]
            state.tokens.append(int(token[row]))
            reason = self._finish_reason(row)
            if reason is not None:
                state = self.evict(row)
                finished.append((row, reason, list(state.tokens)))
            elif (state.sampling is not None
                  and state.sampling.mask_fn is not None):
                # the grammar hook: re-evaluate the mask over the full
                # stream so the NEXT position sees it — a host-side
                # fixed-shape row write, never a retrace
                mask = self._grammar_mask(
                    state.sampling, list(state.prior) + list(state.tokens))
                self._mask_dev = self._mask_dev.at[row].set(mask)
        return StepReport(emitted, finished)

    def _spec_tick(self) -> StepReport:
        started = time.perf_counter()
        emitted_dev, accepted_dev, self._tokens_dev, self._cache, \
            self._dcache, self._pos_dev = self._spec_step(
                self._params, self._dparams, self._cache, self._dcache,
                self._tokens_dev, self._active_dev, self._seed_dev,
                self._pos_dev, self._temp_dev, self._topk_dev,
                self._topp_dev, self._mask_dev)
        window = np.asarray(emitted_dev)             # [groups, K+1]
        accepted = np.asarray(accepted_dev)
        self.last_step_seconds = time.perf_counter() - started
        self.timings['step'] += self.last_step_seconds
        fanout = self.tree_fanout
        emitted, finished = {}, []
        for rep in sorted(self._rowstate):
            if not self._active[rep]:
                continue
            state = self._rowstate[rep]
            group = rep // fanout
            count = int(accepted[group]) + 1
            toks = [int(t) for t in window[group, :count]]
            # host truncation happens only at a finish (budget or stop),
            # so the device cursors' extra advance dies with the evict
            toks = toks[:state.max_new - len(state.tokens)]
            if state.stop is not None and state.stop in toks:
                toks = toks[:toks.index(state.stop) + 1]
            state.tokens.extend(toks)
            for row in range(rep, rep + fanout):
                self._tokens[row] = toks[-1]
            emitted[rep] = toks
            reason = self._finish_reason(rep)
            if reason is not None:
                state = self.evict(rep)
                finished.append((rep, reason, list(state.tokens)))
        return StepReport(emitted, finished)

    # ------------------------------------------------------------- eviction

    def evict(self, row: int) -> _RowState:
        """Retire ``row`` (finished or cancelled; the representative row
        when speculative — its whole branch group retires): its blocks
        return to the free list, its table resets to trash — a host-side
        edit plus one fixed-shape table write, never a retrace."""
        if row not in self._rowstate:
            raise ValueError(f'row {row} is not seated')
        fanout = self.tree_fanout if self._spec else 1
        state = self._rowstate[row]
        for member in range(row, row + fanout):
            self.pool.evict(member)
            self._active[member] = False
            self._tokens[member] = 0
            self._active_dev = self._active_dev.at[member].set(False)
            # temp 0 + all-True mask return the row to the greedy
            # default; stale seed/pos/topk/topp are inert under temp 0
            if state.sampling is not None:
                self._temp_dev = self._temp_dev.at[member].set(0.0)
                if state.sampling.mask_fn is not None:
                    self._mask_dev = self._mask_dev.at[member].set(True)
        self._cache = write_tables(self._cache, self.pool.table)
        self._free_rows.append(row)
        return self._rowstate.pop(row)

    def tokens(self, row: int) -> list:
        """Tokens emitted so far for a seated row."""
        return list(self._rowstate[row].tokens)
