"""Disaggregated prefill/decode: KV handoff over the blob plane.

The DistServe/vLLM split, on machinery this repo already had: a
prefill-role replica runs ONLY the admission prefill (the compute-bound
phase that stalls co-batched decoders), exports every layer's contiguous
KV strip (:meth:`~tpusystem.serve.Engine.export_prefill`), and ships it
to a decode-role replica over the existing chunked digest-verified blob
plane (``send_blob``/``fetch_blob``) under the ``kv:{request}``
namespace — the :func:`~tpusystem.serve.failover.journal_identity`
naming discipline. The decode replica seats the strip through
``adopt_prefill``/``write_tables``
(:meth:`~tpusystem.serve.Engine.admit_prefilled`), which were the
admission seam all along — disaggregation only moves where the strip
comes FROM.

The payload is a :class:`KVHandoff`: the :class:`Request` itself (its
``TraceContext`` rides along, so the decode replica's spans parent into
the submission's trace — one connected trace across the role hop), the
replayed prefix if any (journal recovery composes), the prefill's first
token, and the strips. :func:`pack_handoff` prefixes a BLAKE2b digest so
the transfer is end-to-end verified even on transports that do not
chunk-verify (the in-process :class:`~tpusystem.parallel.multihost.Loopback`);
:exc:`HandoffCorrupt` is the typed failure.

docs/serving.md "Disaggregated prefill/decode" records the protocol and
the head-of-line-blocking measurement (``benchmarks/serve_disagg.py``).
"""

from __future__ import annotations

import dataclasses
import pickle

from tpusystem.parallel.multihost import _blob_digest


class HandoffCorrupt(RuntimeError):
    """A KV handoff payload failed its digest or would not unpickle —
    the receiving replica must NOT seat it (a half-written strip decodes
    garbage silently). The router re-exports or fails the request."""


class RoleMismatch(RuntimeError):
    """A request needing engine work this replica's role does not do
    landed here (e.g. a hot restore-with-prefix on a prefill-only
    scheduler). Typed — and deliberately NOT a ``ValueError``, which the
    router's replay path treats as 'request already finished' and
    swallows silently."""


def kv_namespace(request_id: str) -> str:
    """The blob-plane key for one request's KV handoff — mirrors
    :func:`~tpusystem.serve.failover.journal_identity` so every sidecar
    plane namespaces the same way (``journal:{identity}``,
    ``trace:{process}``, ``kv:{request}``)."""
    return f'kv:{request_id}'


@dataclasses.dataclass
class KVHandoff:
    """One finished prefill, ready to decode somewhere else.

    ``request`` is the original :class:`~tpusystem.serve.Request`
    (trace context included); ``prefix`` the tokens already emitted
    before a replay (the exported strips cover ``prompt + prefix``);
    ``first`` the prefill's argmax token; ``kv`` the
    ``keystr path -> [1, max_seq, heads, head_dim]`` numpy strips;
    ``waited`` seconds already spent queued on the prefill side, so
    decode-side deadline and latency accounting stay truthful."""
    request: object
    first: int
    kv: dict
    prefix: list = dataclasses.field(default_factory=list)
    waited: float = 0.0


def pack_handoff(handoff: KVHandoff) -> bytes:
    """Serialize with an end-to-end digest prefix (the journal's
    ``digest:payload`` framing). The TCP blob plane already verifies
    per-transfer digests, but the handoff must survive ANY transport —
    the digest travels inside the payload."""
    payload = pickle.dumps(handoff, protocol=pickle.HIGHEST_PROTOCOL)
    return _blob_digest(payload).encode('ascii') + b':' + payload


def unpack_handoff(data: bytes) -> KVHandoff:
    """Verify and deserialize :func:`pack_handoff`'s payload; raises
    :exc:`HandoffCorrupt` on digest mismatch or a payload that will not
    unpickle."""
    digest, sep, payload = bytes(data).partition(b':')
    if not sep or _blob_digest(payload).encode('ascii') != digest:
        raise HandoffCorrupt(
            'handoff payload failed its digest — truncated or corrupted '
            'in flight; refusing to seat a half-written KV strip')
    try:
        handoff = pickle.loads(payload)
    except Exception as error:
        raise HandoffCorrupt(
            f'handoff payload would not deserialize: {error}') from error
    if not isinstance(handoff, KVHandoff):
        raise HandoffCorrupt(
            f'kv: blob decoded to {type(handoff).__name__}, not KVHandoff')
    return handoff


class KVStripStore:
    """The prefill side's outbox on the blob-request plane.

    Packed handoffs are :meth:`offer`'d under their ``kv:{request}``
    key; :meth:`attach` chains :meth:`answer` into a transport's
    ``on_blob_request`` (the :meth:`~tpusystem.observe.Tracer.accept_blob`
    chainable-receiver discipline — keys that are not ours fall through
    to whatever hook was installed before). Entries live until
    :meth:`release` (the decode side's ack), so a fetch that died
    mid-flight can simply retry."""

    def __init__(self) -> None:
        self._blobs: dict[str, bytes] = {}
        self._chained = None

    def offer(self, request_id: str, data: bytes) -> None:
        self._blobs[kv_namespace(request_id)] = bytes(data)

    def release(self, request_id: str) -> None:
        self._blobs.pop(kv_namespace(request_id), None)

    def __len__(self) -> int:
        return len(self._blobs)

    def attach(self, transport) -> None:
        self._chained = transport.on_blob_request
        transport.on_blob_request = self.answer

    def answer(self, key: str):
        data = self._blobs.get(key)
        if data is not None:
            return data
        return self._chained(key) if self._chained is not None else None


def fetch_handoff(transport, peer: int, request_id: str,
                  timeout: float = 30.0) -> KVHandoff:
    """Decode-side pull: fetch ``kv:{request}`` from ``peer`` over the
    chunked digest-verified blob plane and unpack it. Raises
    :class:`~tpusystem.parallel.multihost.BlobError` when the peer has
    no such strip (not exported yet, or already released) and
    :exc:`HandoffCorrupt` on a payload that fails verification."""
    return unpack_handoff(
        transport.fetch_blob(peer, kv_namespace(request_id),
                             timeout=timeout))
