"""Randomized fleet chaos certification: kill anything, lose nothing.

PRs 12–19 each drilled ONE failure mode at a time — a replica SIGKILL, a
wedged step, a torn handoff, a router crash. This module is the closing
argument: :func:`certify_fleet` runs a mixed greedy / seeded-sampled /
streamed workload against a full fleet while killing a **uniformly-chosen
component at a uniformly-chosen tick** (router, warm standby, prefill
replica, decode replica, supervisor plane — whatever the harness wires),
then checks the one invariant every robustness PR has been building
toward:

    every accepted request either completes **bitwise-token-exact**
    against an undisturbed reference fleet, or fails **typed** within
    its own deadline — no hung requests, no duplicate completions, no
    silent drops, and ``trace_count == 1`` on every surviving engine
    (chaos never buys a retrace).

Both draws come from one ``random.Random(seed)``
(:func:`~tpusystem.parallel.chaos.pick_chaos`), so a seed IS the
scenario: tier-1 pins a handful of seeds, the dryrun stage adds more,
and a red run replays exactly from the seed in its failure message —
the :class:`~tpusystem.parallel.chaos.Faults` discipline lifted to the
whole fleet.

The harness seam (:class:`FleetHarness`) keeps the certifier
environment-agnostic: the same protocol certifies scripted fake
replicas on a fake clock (tier-1, zero sleeps) and real engines under
real process kills (the dryrun). ``kills['router']`` is the takeover
thunk — it abandons the incumbent and returns the standby Router that
fenced the lease and :meth:`~tpusystem.serve.fleet.Router.recover`\\ ed
the journal; every other component's thunk returns None and the
incumbent keeps serving around the wound.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable

from tpusystem.parallel.chaos import ChaosPick, pick_chaos
from tpusystem.serve.engine import Saturated, UnseededSampling
from tpusystem.serve.fleet import FleetSaturated, NoHealthyReplica, Router
from tpusystem.serve.scheduler import QueueFull

logger = logging.getLogger('tpusystem.serve.certify')

__all__ = ['CertifyReport', 'FleetHarness', 'certify_fleet']

# the front door's typed refusals: a request turned away HERE was never
# accepted, so the completion invariant does not apply to it — but the
# refusal set itself must match the reference run (submission happens
# before the kill tick, against identical fleet state)
_TYPED_REFUSALS = (FleetSaturated, NoHealthyReplica, QueueFull, Saturated,
                   UnseededSampling)

# terminal reasons that are a typed degrade rather than a normal
# completion: a chaos run may downgrade a request to one of these (its
# deadline expired while the fleet healed, the brownout shed it, a
# client cancelled) without violating certification — the caller got a
# truthful typed verdict, not silence and not a wrong answer
_DEGRADED_REASONS = ('expired', 'shed', 'cancelled')


@dataclasses.dataclass
class FleetHarness:
    """One certifiable fleet: the router, the workload, and the kills.

    ``workload`` is a list of fresh :class:`~tpusystem.serve.Request`
    objects (mixed greedy / seeded-sampled; ids must be stable across
    :func:`certify_fleet`'s two builds — the reference run matches by
    id). ``kills`` maps component name -> kill thunk; the ``'router'``
    thunk performs the takeover (fence the lease, build the standby,
    :meth:`~tpusystem.serve.fleet.Router.recover`) and returns the
    successor ``Router`` — or ``(Router, takeover_report)`` to surface
    the recovery counts in the :class:`CertifyReport` — while every
    other thunk (kill a replica handle, wedge the journal plane, no-op
    the standby) returns None. ``advance`` runs once per drain tick
    (advance a fake clock so leases, deadlines and heartbeats breathe
    without real sleeps)."""

    router: Router
    workload: list
    kills: dict[str, Callable[[], Any]]
    advance: Callable[[], None] | None = None


@dataclasses.dataclass
class CertifyReport:
    """One certification verdict — everything needed to replay a red
    run is in the first two fields (the seed is the scenario)."""

    seed: int
    component: str                   # the victim pick_chaos chose
    step: int                        # the router tick it died after
    accepted: int                    # requests past the front door
    refused: dict                    # id -> typed refusal class name
    completed: int                   # bitwise-exact vs the reference
    degraded: list                   # ids that failed typed (allowed)
    takeover: dict | None            # RouterTakeover counts, router kills
    mismatches: list                 # (id, why) — MUST be empty
    duplicates: list                 # ids settled more than once
    hung: list                       # ids never settled in max_steps
    retraced: list                   # (replica, trace_count) != 1

    @property
    def ok(self) -> bool:
        return not (self.mismatches or self.duplicates or self.hung
                    or self.retraced)

    def summary(self) -> str:
        verdict = 'PASS' if self.ok else 'FAIL'
        return (f'[{verdict}] seed={self.seed} kill={self.component}'
                f'@tick{self.step}: {self.completed} exact, '
                f'{len(self.degraded)} typed-degraded, '
                f'{len(self.refused)} refused, '
                f'{len(self.mismatches)} mismatched, '
                f'{len(self.duplicates)} duplicated, {len(self.hung)} hung, '
                f'{len(self.retraced)} retraced')


def _submit_all(router: Router, workload: list) -> tuple[list, dict]:
    """Front-door pass: every request goes in before any kill (the
    harness floor ``lo >= 1`` guarantees it), so the refusal set is a
    pure function of the fleet's initial state — identical across the
    reference and chaos runs by construction."""
    accepted: list = []
    refused: dict = {}
    for request in workload:
        try:
            router.submit(request)
        except _TYPED_REFUSALS as refusal:
            refused[request.id] = type(refusal).__name__
        else:
            accepted.append(request)
    return accepted, refused


def _drain(harness: FleetHarness, pick: ChaosPick | None,
           max_steps: int) -> dict:
    """Run one fleet to idle, firing the pick's kill after its tick;
    returns the run's full observation record."""
    router = harness.router
    accepted, refused = _submit_all(router, harness.workload)
    settled: dict[str, int] = {}     # id -> times seen terminal
    streamed: dict[str, list] = {}   # id -> tokens off FleetTick.emitted
    takeover = None
    fired = pick is None
    for _ in range(max_steps):
        if router.idle and fired:
            break
        tick = router.step()
        for request_id, tokens in tick.emitted.items():
            bucket = streamed.setdefault(request_id, [])
            if isinstance(tokens, (list, tuple)):
                bucket.extend(int(token) for token in tokens)
            else:
                bucket.append(int(tokens))
        for request_id in tick.completed:
            settled[request_id] = settled.get(request_id, 0) + 1
        for completion, _slack in tick.shed:
            request_id = completion.request.id
            settled[request_id] = settled.get(request_id, 0) + 1
        if not fired and router.ticks >= pick.step:
            fired = True
            logger.info('chaos: killing %r after tick %d', pick.component,
                        router.ticks)
            successor = harness.kills[pick.component]()
            if isinstance(successor, tuple):
                successor, takeover = successor
            if isinstance(successor, Router):
                router = successor   # the standby is the fleet now
        if harness.advance is not None:
            harness.advance()
    hung = sorted(request.id for request in accepted
                  if request.id not in router.results)
    return dict(router=router, accepted=accepted, refused=refused,
                results=dict(router.results), settled=settled,
                streamed=streamed, takeover=takeover, hung=hung)


def _stream_ok(streamed: list, final: list) -> bool:
    """The streamed transcript must be an order-preserving subsequence
    of the final tokens (a hot reroute skips re-emitting its prefix, a
    takeover resumes mid-stream — but chaos may never stream a token
    the completion does not contain, in an order it does not)."""
    position = 0
    for token in streamed:
        try:
            position = final.index(token, position) + 1
        except ValueError:
            return False
    return True


def certify_fleet(build: Callable[[], FleetHarness], *, seed: int,
                  components: tuple[str, ...] | None = None,
                  lo: int = 1, hi: int = 8,
                  max_steps: int = 10_000) -> CertifyReport:
    """Certify one seeded chaos scenario against an undisturbed twin.

    ``build()`` constructs a fresh :class:`FleetHarness` — called twice,
    once for the reference fleet (never killed) and once for the chaos
    fleet, so the two runs start bit-identical. The victim and its kill
    tick come from :func:`~tpusystem.parallel.chaos.pick_chaos(seed)`
    over ``components`` (default: every key of the harness's ``kills``);
    ``lo >= 1`` keeps the kill after submission, so acceptance itself is
    never racy. Returns a :class:`CertifyReport`; red runs replay from
    ``seed`` alone.
    """
    if lo < 1:
        raise ValueError('lo must be >= 1: the kill lands after the '
                         'workload is accepted, or acceptance itself races')
    reference = _drain(build(), None, max_steps)
    if reference['hung']:
        raise RuntimeError(
            f'the UNDISTURBED reference fleet never drained '
            f'({reference["hung"]}) — fix the harness before certifying '
            f'chaos against it')
    harness = build()
    available = tuple(components) if components else tuple(harness.kills)
    missing = [name for name in available if name not in harness.kills]
    if missing:
        raise ValueError(f'harness has no kill thunk for {missing}; '
                         f'wired: {sorted(harness.kills)}')
    pick = pick_chaos(seed, available, lo=lo, hi=hi)
    chaos = _drain(harness, pick, max_steps)

    mismatches: list = []
    duplicates = sorted(request_id
                        for request_id, count in chaos['settled'].items()
                        if count > 1)
    if set(chaos['refused']) != set(reference['refused']):
        mismatches.append(('(front door)',
                           f'refusals diverged: chaos '
                           f'{sorted(chaos["refused"])} vs reference '
                           f'{sorted(reference["refused"])}'))
    completed = 0
    degraded: list = []
    for request in chaos['accepted']:
        request_id = request.id
        completion = chaos['results'].get(request_id)
        if completion is None:
            continue                 # already in hung
        expected = reference['results'].get(request_id)
        if expected is None:
            mismatches.append((request_id, 'settled under chaos but never '
                                           'in the reference'))
            continue
        if (completion.reason in _DEGRADED_REASONS
                and completion.reason != expected.reason):
            # a typed downgrade: allowed, but only truthfully — expiry
            # requires the request to actually carry a deadline
            if (completion.reason == 'expired'
                    and getattr(request, 'deadline', None) is None):
                mismatches.append((request_id,
                                   'expired without a deadline'))
                continue
            degraded.append(request_id)
            continue
        if completion.reason != expected.reason:
            mismatches.append((request_id,
                               f'reason {completion.reason!r} != reference '
                               f'{expected.reason!r}'))
            continue
        if list(completion.tokens) != list(expected.tokens):
            mismatches.append((request_id,
                               f'tokens diverged at length '
                               f'{len(completion.tokens)} vs '
                               f'{len(expected.tokens)}'))
            continue
        stream = chaos['streamed'].get(request_id, [])
        if not _stream_ok(stream, list(completion.tokens)):
            mismatches.append((request_id,
                               'streamed transcript is not a subsequence '
                               'of the completion'))
            continue
        completed += 1

    retraced: list = []
    for handle in chaos['router'].handles:
        if not handle.healthy:
            continue                 # the victim may hold a stale count
        engine = getattr(handle.scheduler, 'engine', None)
        count = getattr(engine, 'trace_count', None)
        if count is not None and count != 1:
            retraced.append((handle.name, count))

    report = CertifyReport(
        seed=seed, component=pick.component, step=pick.step,
        accepted=len(chaos['accepted']), refused=dict(chaos['refused']),
        completed=completed, degraded=degraded, takeover=chaos['takeover'],
        mismatches=mismatches, duplicates=duplicates, hung=chaos['hung'],
        retraced=retraced)
    logger.info('%s', report.summary())
    return report
