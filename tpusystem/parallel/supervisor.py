"""Supervising launcher: the restart contract, enforced by a process.

PR 3–4 defined the contract — 42 worker-lost / 43 preempted relaunch, 44
diverged halts (:mod:`tpusystem.parallel.recovery`) — but until now the
launcher side existed only as prose: nothing in the tree relaunched a
worker, detected a crash loop, or bounded a restart storm, and every
recovery paid a full disk restore. :class:`Supervisor` closes that loop
the way production systems do (MegaScale's driver-side fault recovery;
Gemini's redundant in-memory model-state copies):

* **spawn + verdict** — the worker runs as a subprocess; its exit code is
  mapped per the contract: :data:`~tpusystem.parallel.recovery.
  RESTART_EXITS` (and signal deaths — a SIGKILLed worker *is* the
  worker-lost case) relaunch with capped exponential backoff + jitter;
  :data:`~tpusystem.parallel.recovery.DIVERGED_EXIT` and every unknown
  code halt for triage (relaunching a deterministic failure replays it),
  and so do SIGINT/SIGQUIT deaths — those are *operator intent*, not a
  fault, and relaunching would fight the human holding ^C.
* **crash-loop containment** — ``crash_loop_k`` consecutive restartable
  exits, each within ``crash_loop_window`` seconds of the worker's
  first-step mark (or of launch, when it never got that far), end the
  loop with the distinct
  :data:`~tpusystem.parallel.recovery.CRASH_LOOP_EXIT` instead of
  relaunching forever.
* **clean preemption** — the scheduler SIGTERMs the *supervisor*;
  :meth:`terminate` (or the installed handler) forwards it to the worker
  and waits ``grace`` seconds so the worker's preemption path
  (``Runtime(preemption=True)`` → fence → exit 43) drains, escalating to
  SIGKILL only after the grace expires. The supervisor then exits with
  the worker's code — it is being evicted too, so no relaunch.
* **hot state** — the supervisor owns a :class:`~tpusystem.checkpoint.
  memstore.MemStore` served to the worker over a local socket
  (``TPUSYSTEM_SUPERVISOR``), so a relaunched worker restores from the
  supervisor's RAM in seconds instead of from disk; with a control-plane
  ``transport`` and a ``buddy`` rank each verified push is
  cross-replicated to the buddy host's supervisor
  (``TcpTransport.send_blob``) and a replaced host pulls its state back
  from its buddy. Disk remains the verified fallback at every rung
  (:func:`~tpusystem.checkpoint.memstore.hot_resume`).
* **recovery timeline** — every exit, relaunch and detect→first-step
  recovery is a domain event (:class:`~tpusystem.observe.events.
  WorkerExited` / ``WorkerRelaunched`` / ``RecoveryTimeline``) on the
  supervisor's producer, so the ledger orders an incident and TensorBoard
  charts MTTR with zero trainer code.

The loop is fully injectable (``popen``/``clock``/``sleep``), so backoff
and crash-loop policy are tier-1-testable without subprocesses or real
sleeps (``tests/test_supervisor.py``).

Typical launcher ``main()``::

    supervisor = Supervisor([sys.executable, 'train.py'], producer=bus)
    supervisor.install_signal_handler()     # SIGTERM -> forward + grace
    raise SystemExit(supervisor.run())
"""

from __future__ import annotations

import collections
import logging
import os
import random
import signal as signal_module
import subprocess
import threading
import time
from typing import TYPE_CHECKING, Any, Callable

from tpusystem.parallel.multihost import BlobError
from tpusystem.parallel.recovery import (CRASH_LOOP_EXIT, DIVERGED_EXIT,
                                         FAILURE_EXIT, PREEMPTED_EXIT,
                                         RESIZED_EXIT, RESTART_EXITS,
                                         ROUTER_FENCED_EXIT)

if TYPE_CHECKING:  # deferred at runtime: memstore pulls in the (orbax-
    # backed) checkpoint package, which must not tax `import
    # tpusystem.parallel` — the hot tier loads on first Supervisor(...)
    from tpusystem.checkpoint.memstore import MemStore, MemStoreServer

logger = logging.getLogger('tpusystem.supervisor')

__all__ = ['Supervisor']

_CODE_NAMES = {0: 'completed', FAILURE_EXIT: 'failure', 42: 'worker-lost',
               43: 'preempted', 44: 'diverged', CRASH_LOOP_EXIT: 'crash-loop',
               RESIZED_EXIT: 'resized', ROUTER_FENCED_EXIT: 'router-fenced'}

# signal deaths relaunch (a SIGKILLed worker IS the worker-lost case) —
# EXCEPT these: SIGINT (^C) and SIGQUIT (^\) are *operator intent*, a
# human asking this worker to stop. Relaunching would fight the operator
# forever; halt for triage like exit 1.
_HALT_SIGNALS = frozenset({signal_module.SIGINT, signal_module.SIGQUIT})

_UNSET = object()


def _describe(code: int) -> str:
    if code < 0:
        try:
            return f'signal {signal_module.Signals(-code).name}'
        except ValueError:
            return f'signal {-code}'
    return _CODE_NAMES.get(code, f'exit {code}')


class Supervisor:
    """Seconds-scale recovery control loop around one worker process.

    Args:
        argv: the worker command line (relaunched verbatim).
        rank: this host's rank — carried in events and used to pair with
            ``buddy`` for replication.
        memstore: ``True`` (default) serves a fresh
            :class:`~tpusystem.checkpoint.memstore.MemStore` to the
            worker; pass an existing store to share one, or ``False`` to
            disable the hot tier entirely (workers then restore from
            disk — the drill for the fallback path).
        transport: optional control-plane client
            (:class:`~tpusystem.parallel.multihost.TcpTransport`) of the
            *supervisor* pod — the channel hot state is cross-replicated
            over. Independent of the workers' control plane: it must
            survive worker death.
        buddy: peer rank this supervisor mirrors its hot state to (and
            pulls from when its own store is empty — the replaced-host
            path). Pairing is 1:1 by convention (e.g. ``rank ^ 1``).
        producer: event bus the supervisor narrates on (``dispatch`` is
            called on the supervising thread only).
        env: extra environment entries for the worker (on top of
            ``os.environ`` and the memstore address).
        backoff_base / backoff_cap / backoff_jitter / seed: relaunch
            backoff ``min(cap, base * 2**attempt)`` scaled by
            ``1 + jitter * U[0, 1)`` from a seeded RNG — capped
            exponential with deterministic jitter, reset by a productive
            run.
        crash_loop_k / crash_loop_window: give up (exit
            :data:`~tpusystem.parallel.recovery.CRASH_LOOP_EXIT`) after
            ``k`` consecutive restartable exits each within ``window``
            seconds of first-step (or launch).
        max_restarts: optional hard cap on total relaunches (``None`` =
            bounded by the crash-loop detector only).
        grace: seconds between forwarding SIGTERM and escalating to
            SIGKILL.
        popen / clock / sleep / poll_interval: injection seams — tests
            drive the whole policy with a fake clock and fake processes,
            no real sleeps in tier-1.
    """

    def __init__(self, argv: list[str], *, rank: int = 0,
                 memstore: MemStore | bool = True,
                 transport: Any = None, buddy: int | None = None,
                 producer: Any = None, tracer: Any = None,
                 flight_path: str | None = None,
                 env: dict[str, str] | None = None,
                 backoff_base: float = 1.0, backoff_cap: float = 30.0,
                 backoff_jitter: float = 0.25, seed: int = 0,
                 crash_loop_k: int = 3, crash_loop_window: float = 30.0,
                 max_restarts: int | None = None, grace: float = 15.0,
                 popen: Callable[..., Any] = subprocess.Popen,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 poll_interval: float = 0.05) -> None:
        self.argv = list(argv)
        self.rank = rank
        self.transport = transport
        self.buddy = buddy
        self.producer = producer
        # observe.Tracer | None: every detect→first-step recovery becomes
        # a parent span with one child span per stage transition — the
        # span form of RecoveryTimeline, on the same clock
        self.tracer = tracer
        # where the worker's flight recorder writes (exported to it as
        # TPUSYSTEM_FLIGHT); after every exit the supervisor reads the
        # post-mortem back and attaches it to WorkerExited
        self.flight_path = flight_path
        self.env = dict(env or {})
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.backoff_jitter = backoff_jitter
        self.crash_loop_k = crash_loop_k
        self.crash_loop_window = crash_loop_window
        self.max_restarts = max_restarts
        self.grace = grace
        self._popen = popen
        self._clock = clock
        self._sleep = sleep
        self._poll_interval = poll_interval
        self._rng = random.Random(seed)
        self._terminate = threading.Event()
        self._resize = threading.Event()
        self._resize_lock = threading.Lock()
        self._resize_env: dict[str, str] = {}
        self._resize_buddy: Any = _UNSET
        self._repl_lock = threading.Lock()
        self._repl_pending: dict[str, Any] = {}
        self._repl_wake = threading.Event()
        self._repl_stop = threading.Event()
        self._repl_thread: threading.Thread | None = None
        self._marks: collections.deque = collections.deque()
        self._timeline: dict[str, float] | None = None
        self._restore_info: dict | None = None
        self._first_step_at: float | None = None
        self.restarts = 0
        self.timelines: list[Any] = []    # emitted RecoveryTimeline events
        self.store: MemStore | None = None
        self.server: MemStoreServer | None = None
        if memstore:
            from tpusystem.checkpoint.memstore import MemStore, MemStoreServer
            self.store = (memstore if isinstance(memstore, MemStore)
                          else MemStore())
            self.server = MemStoreServer(
                self.store, on_put=self._replicate, on_mark=self._on_mark,
                fetch_fallback=self._pull_from_buddy)
        if transport is not None:
            transport.on_blob = self._accept_replica
            transport.on_blob_request = self._serve_replica

    # ------------------------------------------------------------------
    # hot-state replication (buddy pair over the control plane)

    # key discipline: pushes travel as 'replica:{identity}' and pulls ask
    # for 'hot:{identity}' — distinct keys, so a replaced host's pull can
    # never be satisfied by the buddy's own concurrent push of ITS state
    # (fetch_blob additionally pins the sender, but the key split keeps
    # the two flows unmistakable on the wire). 'own:{identity}' asks for
    # the peer's OWN local slot — the elastic reshard's survivor fetch
    # (tpusystem.parallel.elastic.collect_pieces), again key-distinct.
    # The serving engine's request journal rides this machinery unchanged
    # under the identity namespace 'journal:{identity}'
    # (tpusystem.serve.failover): its pushes replicate to the buddy and a
    # replaced host's fetch pulls it back exactly like hot training state
    # — the identity prefix keeps journal slots from ever colliding with
    # the same run's TrainState slots. The fleet router's failover
    # (tpusystem.serve.fleet) is a THIRD reader of the same keys: when a
    # serving replica dies for good, the router's recovery chain asks
    # the dead host's supervisor RAM first and then the buddy for
    # 'hot:journal:{identity}' — a DIFFERENT surviving replica then
    # adopts the rows, so no new key kind and no new wire flow is needed
    # for fleet-level handoff.

    def _replicate(self, identity: str, entry: Any) -> None:
        """Queue a verified push for cross-host replication.

        Runs on the memstore serve thread — the transfer itself must NOT:
        the worker's next ``push`` ack waits behind this thread, and a
        slow buddy link would inject the whole cross-host transfer into
        the training loop. A background worker drains the queue, and
        entries coalesce per identity (only the newest matters)."""
        if self.transport is None or self.buddy is None:
            return
        with self._repl_lock:
            self._repl_pending[identity] = entry
            if self._repl_thread is None:
                self._repl_thread = threading.Thread(
                    target=self._replication_loop, daemon=True)
                self._repl_thread.start()
        self._repl_wake.set()

    def _replication_loop(self) -> None:
        from tpusystem.checkpoint.memstore import pack_hot
        while not self._repl_stop.is_set():
            self._repl_wake.wait()
            self._repl_wake.clear()
            while True:
                with self._repl_lock:
                    if not self._repl_pending:
                        break
                    identity, entry = self._repl_pending.popitem()
                try:
                    self.transport.send_blob(self.buddy,
                                             f'replica:{identity}',
                                             pack_hot(entry))
                except OSError as error:
                    logger.warning('hot-state replication to buddy %d '
                                   'failed (%s); local copy and disk still '
                                   'stand', self.buddy, error)

    def _accept_replica(self, sender: int, key: str, data: bytes) -> None:
        if not key.startswith('replica:') or self.store is None:
            return
        from tpusystem.checkpoint.memstore import unpack_hot
        identity = key[len('replica:'):]
        try:
            entry = unpack_hot(data)
            self.store.put(identity, entry.step, entry.blob,
                           extras=entry.extras, digest=entry.digest,
                           replica=True)
        except Exception as error:        # torn replica: keep the old copy
            logger.warning('replica of %r from rank %d rejected (%s)',
                           identity, sender, error)

    @staticmethod
    def _strip_member(rest: str) -> str:
        # elastic fetch keys may carry a member-rank segment
        # ('own:{member}:{identity}') purely to keep concurrent fetches
        # of DIFFERENT peers' pieces key-distinct on the fetching
        # transport (fetch_blob allows one in-flight fetch per key); the
        # serving side answers from its own slots either way
        prefix, sep, remainder = rest.partition(':')
        return remainder if sep and prefix.isdigit() else rest

    def _serve_replica(self, key: str) -> bytes | None:
        if self.store is None:
            return None
        from tpusystem.checkpoint.memstore import pack_hot
        if key.startswith('hot:'):
            entry = self.store.newest(self._strip_member(key[4:]),
                                      replica=True)
        elif key.startswith('own:'):
            entry = self.store.newest(self._strip_member(key[4:]))
        else:
            return None
        return None if entry is None else pack_hot(entry)

    def _pull_from_buddy(self, identity: str) -> Any:
        """A local ``get`` missed (fresh supervisor on a replaced host):
        pull this identity's hot state back from the buddy's replica slot
        and cache it locally."""
        if self.transport is None or self.buddy is None:
            return None
        from tpusystem.checkpoint.memstore import unpack_hot
        try:
            data = self.transport.fetch_blob(self.buddy, f'hot:{identity}',
                                             timeout=10.0)
        except BlobError as error:
            logger.warning('buddy %d has no usable hot state for %r (%s); '
                           'disk is the fallback', self.buddy, identity, error)
            return None
        entry = unpack_hot(data)
        return self.store.put(identity, entry.step, entry.blob,
                              extras=entry.extras, digest=entry.digest)

    # ------------------------------------------------------------------
    # timeline plumbing (marks arrive on server threads; everything else
    # runs on the supervising thread)

    def _on_mark(self, stage: str, info: dict) -> None:
        self._marks.append((stage, dict(info or {}), self._clock()))

    def _drain_marks(self) -> None:
        while self._marks:
            stage, info, at = self._marks.popleft()
            if stage == 'first-step':
                self._first_step_at = at
            if stage == 'restore':
                self._restore_info = info
            if self._timeline is not None:
                self._timeline.setdefault(stage, at)
                if stage == 'first-step':
                    self._emit_timeline()

    def _emit_timeline(self) -> None:
        timeline, self._timeline = self._timeline, None
        detect = timeline.pop('detect')
        stages = {stage: at - detect for stage, at in timeline.items()}
        if self.tracer is not None and timeline:
            restored = self._restore_info or {}
            done = max(timeline.values())
            root = self.tracer.record(
                f'recovery rank{self.rank}', detect, done, cat='recovery',
                args={'rank': self.rank, 'source': restored.get('source'),
                      'step': restored.get('step')})
            previous = ('detect', detect)
            for stage, at in sorted(timeline.items(), key=lambda kv: kv[1]):
                self.tracer.record(f'{previous[0]}→{stage}',
                                   previous[1], at, cat='recovery',
                                   trace=root.context)
                previous = (stage, at)
        restore = self._restore_info or {}
        seconds = stages.get('first-step', 0.0)
        logger.info('recovery complete on rank %d: %.3fs detect->first-step '
                    '(source=%s, stages=%s)', self.rank, seconds,
                    restore.get('source'), {k: round(v, 3)
                                            for k, v in stages.items()})
        from tpusystem.observe.events import RecoveryTimeline
        event = RecoveryTimeline(rank=self.rank,
                                 step=restore.get('step'),
                                 source=restore.get('source'),
                                 seconds=seconds, stages=stages)
        self.timelines.append(event)
        self._dispatch(event)

    def _dispatch(self, event: Any) -> None:
        if self.producer is not None:
            self.producer.dispatch(event)

    def _postmortem(self) -> Any:
        """The worker's flight-recorder dump, read back after an exit —
        'what the worker saw' attached to the verdict about it. None
        when recording is off or the worker died before its first
        dump (the recorder's write-ahead cadence bounds that window)."""
        if self.flight_path is None:
            return None
        from tpusystem.observe.flight import FlightRecorder
        return FlightRecorder.read(self.flight_path)

    def _worker_exited(self, code: int, action: str, uptime: float,
                       reason: str | None) -> None:
        from tpusystem.observe.events import WorkerExited
        self._dispatch(WorkerExited(rank=self.rank, code=code, action=action,
                                    uptime=uptime, reason=reason,
                                    postmortem=self._postmortem()))

    # ------------------------------------------------------------------
    # the control loop

    def terminate(self) -> None:
        """Begin the preemption drain: forward SIGTERM to the worker, give
        it ``grace`` seconds to unwind (fence + exit 43), then SIGKILL.
        Safe from a signal handler or another thread."""
        self._terminate.set()

    def resize(self, env: dict[str, str] | None = None, *,
               buddy: int | None | object = _UNSET) -> None:
        """Restart the worker under a NEW world spec (the elastic commit
        hook — :class:`tpusystem.parallel.elastic.ElasticCoordinator`'s
        ``on_resize`` side).

        Unlike :meth:`terminate` this is not an eviction: the worker is
        SIGTERMed (same grace → SIGKILL ladder) so it drains and exits,
        and the supervisor relaunches it *immediately* — no backoff, no
        crash-loop accounting — with ``env`` merged into its environment
        (typically :meth:`~tpusystem.parallel.elastic.ResizeDecision.env`)
        and, when given, ``buddy`` re-pointed at the new pairing so hot
        replication resumes against the new rank set. Safe from another
        thread (the coordinator's poll thread calls it).
        """
        with self._resize_lock:
            if env:
                self._resize_env = {**self._resize_env, **env}
            if buddy is not _UNSET:
                self._resize_buddy = buddy
            self._resize.set()

    def _apply_resize(self) -> None:
        """Fold the pending resize spec into the relaunch environment and
        buddy pairing, clearing the request. The lock keeps a SECOND
        resize() (the coordinator's next epoch, on its own thread) from
        losing its spec between this method's read and reset."""
        with self._resize_lock:
            self._resize.clear()
            if self._resize_env:
                self.env.update(self._resize_env)
                self._resize_env = {}
            if self._resize_buddy is not _UNSET:
                self.buddy = self._resize_buddy
                self._resize_buddy = _UNSET

    def install_signal_handler(self, *signals: int) -> None:
        """Arm :meth:`terminate` on the given signals (default SIGTERM).
        Main thread only — same Python constraint as
        ``Runtime.install_preemption_handler``."""
        for signum in signals or (signal_module.SIGTERM,):
            signal_module.signal(signum, lambda *_: self.terminate())

    def run(self) -> int:
        """Supervise until the contract says stop; returns the exit code
        the *supervisor* should end with."""
        try:
            return self._supervise()
        finally:
            self.close()

    def _supervise(self) -> int:
        from tpusystem.observe.events import WorkerRelaunched
        attempt = 0          # backoff ladder position (reset by progress)
        rapid = 0            # consecutive crash-loop samples
        while True:
            if self._terminate.is_set():
                # eviction arrived during the backoff sleep: relaunching
                # now would spawn a worker only to SIGTERM it (likely
                # before its preemption handler is even installed) — the
                # last worker already drained/checkpointed, so report the
                # preemption itself
                logger.info('rank %d: termination requested before '
                            'relaunch; exiting %d', self.rank,
                            PREEMPTED_EXIT)
                return PREEMPTED_EXIT
            if self._resize.is_set():
                # the resize committed while no worker was running (a
                # backoff sleep, or between exit and relaunch): fold the
                # new spec in BEFORE launching — spawning under the stale
                # world just to SIGTERM it would waste a whole worker
                # start and dial the control plane at the old size
                self._apply_resize()
            env = {**os.environ, **self.env}
            if self.server is not None:
                env.update(self.server.env)
            if self.flight_path is not None:
                from tpusystem.observe.flight import ENV_FLIGHT
                env[ENV_FLIGHT] = str(self.flight_path)
                # clear the previous worker's post-mortem before launch: a
                # worker that dies before its FIRST dump must attach None,
                # not its predecessor's final ticks
                try:
                    os.unlink(self.flight_path)
                except OSError:
                    pass
            self._first_step_at = None
            self._restore_info = None
            launched = self._clock()
            if self._timeline is not None:
                self._timeline.setdefault('relaunch', launched)
            worker = self._popen(self.argv, env=env)
            logger.info('rank %d: launched worker pid %s', self.rank,
                        getattr(worker, 'pid', '?'))
            code = self._wait(worker)
            self._drain_marks()
            uptime = self._clock() - launched
            reason = _describe(code)

            if self._terminate.is_set():
                # our own eviction: the worker drained (or was killed after
                # the grace); pass its verdict through, never relaunch. A
                # signal death has no pass-through-able code — raising
                # SystemExit(-9) would surface as a meaningless 128+ shell
                # status — so it maps to the preemption code: the eviction
                # is the truth of what happened.
                if code < 0:
                    logger.warning(
                        'rank %d: worker died to %s without draining; '
                        'reporting the eviction as exit %d', self.rank,
                        reason, PREEMPTED_EXIT)
                    code = PREEMPTED_EXIT
                self._worker_exited(code, 'drain', uptime, reason)
                logger.info('rank %d: preemption drain done (%s)', self.rank,
                            reason)
                return code
            if code == 0:
                self._worker_exited(0, 'done', uptime, reason)
                return 0
            if self._resize.is_set() and (
                    code in RESTART_EXITS
                    or (code < 0 and -code not in _HALT_SIGNALS)):
                # a requested elastic resize: the exit (43 from our own
                # SIGTERM, 46 from the worker's drain, or a signal death
                # after the grace) is the handshake, not a fault — apply
                # the new world spec and relaunch NOW, outside the
                # backoff ladder and the crash-loop accounting. An
                # operator's SIGINT/SIGQUIT still halts below: a pending
                # resize does not outrank the human holding ^C.
                self._apply_resize()
                self._timeline = {'detect': self._clock()}
                self.restarts += 1
                self._worker_exited(code, 'resize', uptime, reason)
                logger.info('rank %d: worker exited %s for a world resize; '
                            'relaunching under the new spec', self.rank,
                            reason)
                continue
            restartable = code in RESTART_EXITS or (
                code < 0 and -code not in _HALT_SIGNALS)
            if not restartable:
                action = 'halt'
                self._worker_exited(code, action, uptime, reason)
                logger.error(
                    'rank %d: worker exited %d (%s) — not a restart code; '
                    'halting for triage%s', self.rank, code, reason,
                    ' (divergence: a blind relaunch would replay it)'
                    if code == DIVERGED_EXIT else
                    ' (operator signal: relaunching would fight the human)'
                    if code < 0 else '')
                # a signal death has no pass-through-able positive code
                # (SystemExit(-2) surfaces as a meaningless shell status):
                # operator-intent signals halt like the generic failure
                return code if code >= 0 else FAILURE_EXIT

            # crash-loop containment: a restartable exit within the window
            # of first-step (or of launch, if it never got that far) made
            # no progress; K of those in a row and relaunching is futile
            anchor = self._first_step_at or launched
            productive = (self._clock() - anchor) >= self.crash_loop_window
            rapid = 0 if productive else rapid + 1
            if productive:
                attempt = 0
            if rapid >= self.crash_loop_k or (
                    self.max_restarts is not None
                    and self.restarts >= self.max_restarts):
                self._worker_exited(code, 'crash-loop', uptime, reason)
                logger.error(
                    'rank %d: crash loop — %d consecutive restartable exits '
                    'within %.0fs of first-step; giving up with exit %d',
                    self.rank, rapid, self.crash_loop_window, CRASH_LOOP_EXIT)
                return CRASH_LOOP_EXIT

            self._timeline = {'detect': self._clock()}
            self._worker_exited(code, 'relaunch', uptime, reason)
            backoff = min(self.backoff_cap, self.backoff_base * 2 ** attempt)
            backoff *= 1.0 + self.backoff_jitter * self._rng.random()
            attempt += 1
            self.restarts += 1
            logger.warning(
                'rank %d: worker lost (%s) after %.1fs; relaunch #%d in '
                '%.2fs', self.rank, reason, uptime, self.restarts, backoff)
            self._dispatch(WorkerRelaunched(rank=self.rank, attempt=attempt,
                                            restarts=self.restarts,
                                            backoff=backoff))
            self._sleep(backoff)

    def _wait(self, worker: Any) -> int:
        """Poll the worker to completion, draining timeline marks and
        reacting to :meth:`terminate` (SIGTERM forward → grace → SIGKILL).
        Polling — not ``wait()`` — so a signal arriving between frames is
        honored within ``poll_interval``."""
        term_sent_at: float | None = None
        while True:
            code = worker.poll()
            if code is not None:
                return code
            self._drain_marks()
            if (self._terminate.is_set() or self._resize.is_set()) \
                    and term_sent_at is None:
                term_sent_at = self._clock()
                logger.info('rank %d: forwarding SIGTERM to worker '
                            '(%s, grace %.0fs)', self.rank,
                            'resize' if self._resize.is_set() else 'drain',
                            self.grace)
                try:
                    worker.send_signal(signal_module.SIGTERM)
                except (OSError, ValueError):
                    pass
            elif (term_sent_at is not None
                    and self._clock() - term_sent_at > self.grace):
                logger.warning('rank %d: grace expired; SIGKILLing worker',
                               self.rank)
                try:
                    worker.kill()
                except OSError:
                    pass
                term_sent_at = float('inf')   # kill once, keep polling
            self._sleep(self._poll_interval)

    def close(self) -> None:
        self._repl_stop.set()
        self._repl_wake.set()          # unblock the replication worker
        if self.server is not None:
            self.server.close()

    def __enter__(self) -> 'Supervisor':
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
