"""Device meshes — the TPU replacement for CUDA device strings.

The reference resolves ``Depends(device)`` to ``'cuda'``
(``examples/tinysys/main.py:36-37``); here the injected runtime fact is a
:class:`jax.sharding.Mesh` laid out over the chip topology. All parallelism
(DP/FSDP/TP/PP/SP/EP) is expressed as named mesh axes; GSPMD and
``shard_map`` insert the matching ICI/DCN collectives.

Axis vocabulary (used by every sharding policy and model in the framework):

======== ========================================================
``data``   pure data parallelism (gradient all-reduce)
``fsdp``   fully-sharded data parallelism (params/opt-state scatter)
``model``  tensor parallelism (weight-matrix column/row split)
``seq``    sequence/context parallelism (ring attention)
``expert`` expert parallelism (MoE all-to-all dispatch)
``stage``  pipeline parallelism (collective-permute between stages)
======== ========================================================

A :class:`MeshSpec` is a registered entity: its axis sizes capture into the
experiment identity hash, so checkpoints distinguish incompatible layouts
(SURVEY.md §7.3 "identity under sharding").
"""

from __future__ import annotations

import functools
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpusystem.registry import register

DATA, FSDP, MODEL, SEQ, EXPERT, STAGE = 'data', 'fsdp', 'model', 'seq', 'expert', 'stage'
AXES = (DATA, FSDP, MODEL, SEQ, EXPERT, STAGE)


def axis_size(axis) -> int:
    """Static size of a mapped mesh axis, inside ``shard_map``.

    ``jax.lax.axis_size`` where this install has it; the classic
    ``psum(1, axis)`` idiom (constant-folded to a Python int) where it
    predates it. The compat twin of :func:`shard_map` below.
    """
    if hasattr(jax.lax, 'axis_size'):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              axis_names=None):
    """``jax.shard_map`` with a fallback for jax installs that predate it.

    Every manual-collective path in the repo (MoE expert dispatch, ring
    attention, sharded flash, the pipeline schedule) routes through this
    one seam instead of ``jax.shard_map`` directly. On current jax it is
    a passthrough; on older installs (``jax.shard_map`` landed after
    0.4.x) it adapts ``jax.experimental.shard_map.shard_map``:
    ``check_vma`` maps to the old ``check_rep``, and ``axis_names`` (the
    axes handled *manually*; all, when omitted) maps to its complement,
    the old ``auto`` set. Caveat on the legacy path: partially-manual
    mappings (``axis_names`` smaller than the mesh — PP x TP) lower only
    where that jaxlib supports the PartitionId instruction under SPMD,
    which excludes the CPU test backend.
    """
    if hasattr(jax, 'shard_map'):
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        if axis_names is not None:
            kwargs['axis_names'] = axis_names
        return jax.shard_map(f, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy
    auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
            if axis_names is not None else frozenset())
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma, auto=auto)


# The miniature of the pipeline's PP x TP composition: a *partially
# manual* shard_map (stage manual, model auto) whose body ppermutes an
# activation that GSPMD partitions over the auto axis. jaxlibs that
# cannot lower the PartitionId instruction under SPMD on CPU fail here —
# some with a catchable UNIMPLEMENTED, some with a fatal
# spmd_partitioner.cc check abort — so the probe must run out-of-process.
_PARTIAL_MANUAL_PROBE = """
import numpy as np
import jax, jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from tpusystem.parallel.mesh import force_host_platform, shard_map
force_host_platform(4)
mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2), ('stage', 'model'))
x = jnp.ones((8, 8), jnp.float32)
w = jax.device_put(jnp.ones((8, 8), jnp.float32),
                   NamedSharding(mesh, P(None, 'model')))
body = lambda xs, ws: lax.ppermute(xs @ ws, 'stage', [(0, 1), (1, 0)])
mapped = shard_map(body, mesh=mesh,
                   in_specs=(P('stage', None), P(None, None)),
                   out_specs=P('stage', None), check_vma=False,
                   axis_names=frozenset({'stage'}))
print(float(jax.jit(mapped)(x, w).sum()))
"""


@functools.lru_cache(maxsize=None)
def partial_manual_skip_reason() -> str | None:
    """Capability probe: can this jaxlib lower a partially-manual
    ``shard_map`` (the PartitionId instruction under SPMD) on CPU?

    Returns ``None`` when it can, else a reason string carrying the
    probe's error line — made for ``pytest.mark.skipif`` on the PP x TP
    tests that exercise the pipeline's partial-manual path (see
    :func:`shard_map`'s legacy-path caveat). Runs the probe in a
    subprocess because failing jaxlibs may abort the whole process with
    a fatal ``spmd_partitioner.cc`` check rather than raise. The result
    is cached in-process (lru_cache) AND on disk keyed by the
    jax/jaxlib/python versions, so the ~6 s probe subprocess runs once
    per installation, not once per pytest invocation.
    """
    import pathlib
    import subprocess
    import sys
    import tempfile
    import jaxlib
    key = (f"{jax.__version__}-{getattr(jaxlib, '__version__', '?')}-"
           f'py{sys.version_info[0]}.{sys.version_info[1]}')
    cache = (pathlib.Path(tempfile.gettempdir())
             / f'tpusystem-partial-manual-{key}.txt')
    try:
        cached = cache.read_text()
        return None if cached == 'ok' else cached
    except OSError:
        pass
    repo_root = pathlib.Path(__file__).resolve().parents[2]
    try:
        probe = subprocess.run(
            [sys.executable, '-c', _PARTIAL_MANUAL_PROBE],
            capture_output=True, text=True, timeout=600,
            cwd=str(repo_root))
    except (OSError, subprocess.TimeoutExpired) as error:
        return f'partial-manual shard_map probe could not run: {error}'
    if probe.returncode == 0:
        reason = None
    else:
        lines = [line.strip() for line in
                 (probe.stderr + '\n' + probe.stdout).splitlines()
                 if line.strip()]
        detail = next((line for line in lines if 'PartitionId' in line
                       or 'spmd_partitioner' in line),
                      lines[-1] if lines else f'exit code {probe.returncode}')
        reason = ('this jaxlib cannot lower partial-manual shard_map '
                  f'(PartitionId under SPMD) on CPU: {detail[:200]}')
    try:
        cache.write_text('ok' if reason is None else reason)
    except OSError:
        pass
    return reason


def force_host_platform(n_devices: int = 8) -> None:
    """Force JAX onto the host (CPU) platform with ``n_devices`` virtual chips.

    The standard way to exercise mesh/collective code (DP/FSDP/TP/PP/SP/EP)
    without TPU hardware: the test suite and ``dryrun_multichip`` both run on
    a virtual CPU mesh set up by this call. Setting ``JAX_PLATFORMS=cpu`` in
    the environment is NOT enough when an accelerator plugin is installed
    (plugins prepend themselves to ``jax_platforms``); forcing the config
    after import wins.

    Must be called before the first JAX backend initialization in the
    process — XLA reads ``--xla_force_host_platform_device_count`` once, at
    backend creation. Raises RuntimeError (rather than leaving a silently
    single-device mesh) when called too late.
    """
    import os
    import re
    flag = '--xla_force_host_platform_device_count'
    flags = os.environ.get('XLA_FLAGS', '')
    if flag in flags:
        # Replace a stale preset count (e.g. from the caller's environment)
        # rather than silently keeping it when it is smaller than requested.
        current = re.search(rf'{flag}=(\d+)', flags)
        if current and int(current.group(1)) < n_devices:
            flags = re.sub(rf'{flag}=\d+', f'{flag}={n_devices}', flags)
            os.environ['XLA_FLAGS'] = flags
    else:
        os.environ['XLA_FLAGS'] = (flags + f' {flag}={n_devices}').strip()
    jax.config.update('jax_platforms', 'cpu')
    have = len(jax.devices('cpu'))
    if have < n_devices:
        raise RuntimeError(
            f'need {n_devices} virtual CPU devices but found {have}: a JAX '
            f'backend was already initialized in this process, so '
            f'{flag} cannot take effect. '
            f'Call force_host_platform() before any JAX operation, or run '
            f'in a fresh process with XLA_FLAGS={flag}={n_devices}.')


@register
class MeshSpec:
    """Declarative mesh layout: axis name -> size.

    Size ``-1`` on exactly one axis means "fill with all remaining devices".
    Axes of size 1 are kept in the mesh (they cost nothing and keep
    PartitionSpecs uniform across configurations).

    Example::

        MeshSpec(data=-1, model=4).build()   # v4-32: data=8 x model=4
        MeshSpec(fsdp=-1).build()            # pure FSDP over every chip
    """

    def __init__(self, data: int = 1, fsdp: int = 1, model: int = 1,
                 seq: int = 1, expert: int = 1, stage: int = 1):
        self.sizes = {DATA: data, FSDP: fsdp, MODEL: model,
                      SEQ: seq, EXPERT: expert, STAGE: stage}

    def resolved_sizes(self, device_count: int) -> dict[str, int]:
        sizes = dict(self.sizes)
        wildcards = [axis for axis, size in sizes.items() if size == -1]
        if len(wildcards) > 1:
            raise ValueError(f'only one axis may be -1, got {wildcards}')
        fixed = math.prod(size for size in sizes.values() if size != -1)
        if wildcards:
            if device_count % fixed:
                raise ValueError(
                    f'{device_count} devices not divisible by fixed axes {fixed}')
            sizes[wildcards[0]] = device_count // fixed
        elif fixed != device_count:
            raise ValueError(
                f'mesh wants {fixed} devices but {device_count} are available')
        return sizes

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        sizes = self.resolved_sizes(len(devices))
        shape = tuple(sizes[axis] for axis in AXES)
        return Mesh(np.asarray(devices).reshape(shape), AXES)

    def resized(self, device_count: int) -> 'MeshSpec':
        """The same layout policy scaled to a new device count — the mesh
        derivation of an elastic resize (:mod:`tpusystem.parallel.elastic`).

        A wildcard spec (one axis ``-1``) already scales: the wildcard
        re-fills over the new count. A fully pinned spec scales its
        ``data`` axis — or ``fsdp`` when the data axis cannot absorb the
        change — keeping ``model``/``seq``/``expert``/``stage`` fixed:
        those axis sizes encode kernel and memory-layout choices a resize
        must not silently change. Raises ``ValueError`` when no data-like
        axis divides the new count (resize to a compatible world or
        restart with a new spec deliberately).
        """
        sizes = dict(self.sizes)
        if any(size == -1 for size in sizes.values()):
            spec = MeshSpec(**sizes)
            spec.resolved_sizes(device_count)     # validate divisibility now
            return spec
        for axis in (DATA, FSDP):
            fixed = math.prod(size for name, size in sizes.items()
                              if name != axis)
            if device_count % fixed == 0:
                return MeshSpec(**{**sizes, axis: device_count // fixed})
        raise ValueError(
            f'cannot rescale mesh {sizes} to {device_count} devices: '
            f'neither the data nor the fsdp axis divides the new count')


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """A 1x1x1x1x1x1 mesh over one chip — the degenerate case that keeps
    every sharding annotation valid on a single device."""
    devices = [device] if device is not None else jax.devices()[:1]
    return MeshSpec().build(devices)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical global-batch sharding: the batch dimension splits over the
    combined (data, fsdp) axes — FSDP is data parallelism for activations."""
    return NamedSharding(mesh, PartitionSpec((DATA, FSDP)))


def scan_carry_constraint(mesh: Mesh | None):
    """Sharding pin for a scan-over-layers carry ``[batch, seq, dim]``
    in the TP x FSDP composition: batch over ``data``, hidden dim over
    ``fsdp``.

    Without a pin, GSPMD gives the scan carry a batch-over-(data, fsdp)
    layout at the loop boundary while the body's FSDP-scattered weight
    grads want the carry dim-sharded — an unplannable transition that
    falls back to an involuntary full rematerialization per layer
    (spmd_partitioner.cc 'last resort' replicate-then-repartition).
    Pinning the carry to P(data, None, fsdp) matches the layout the
    partitioner itself targets inside the body — measured 2 warnings ->
    0 on a 2x2x2 mesh, identical loss. Returns an identity function for
    ``mesh=None`` or meshes without both axes active (GSPMD's own choice
    is already transition-free there). Used by both LM families'
    ``scan_layers`` paths."""
    import jax

    if mesh is None:
        return lambda hidden: hidden
    shape = dict(mesh.shape)
    if shape.get(FSDP, 1) < 2 or shape.get(MODEL, 1) < 2:
        return lambda hidden: hidden
    sharding = NamedSharding(mesh, PartitionSpec(DATA, None, FSDP))
    return lambda hidden: jax.lax.with_sharding_constraint(hidden, sharding)


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for ``[steps, batch, ...]`` stacks (the
    :func:`tpusystem.train.build_multi_step` input): the steps axis stays
    whole on every device, the batch axis (dim 1) splits over
    (data, fsdp) like :func:`batch_sharding`."""
    return NamedSharding(mesh, PartitionSpec(None, (DATA, FSDP)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
