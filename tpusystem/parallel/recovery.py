"""Elastic recovery policy: worker loss as a domain event with a decision.

The reference has no failure machinery beyond exceptions-as-events
(SURVEY.md §5 "failure detection / elastic recovery — absent"). The TPU
build's recovery story composes three existing pieces:

1. **detect** — the control plane surfaces a crashed or silent host as a
   :class:`~tpusystem.parallel.multihost.WorkerLost` event on every other
   host (socket death immediately; heartbeat timeout otherwise).
2. **decide** — the :func:`recovery_consumer` here turns that event into
   an :class:`WorkerLostError` raised on the host loop at the next
   ``runtime.sync()`` (remote events dispatch at drain time, so the error
   unwinds the epoch loop, never a collective mid-step).
3. **resume** — a TPU mesh cannot be resized live: recovery *is* restart.
   The job exits, the scheduler relaunches it, and the compilation
   pipeline's ``bring_epoch``/``restore_weights`` steps resume from the
   last committed checkpoint by identity hash (SURVEY.md §3.5) — the same
   path as an ordinary preemption.

Typical wiring::

    runtime.producer.register(recovery_consumer())
    try:
        for epoch in range(model.epoch, epochs):
            service.handle('iterate', model, loaders, metrics)
            runtime.sync()                  # WorkerLostError raises here
    except WorkerLostError as loss:
        repository.wait()                   # keep the last good checkpoint
        raise SystemExit(LOST_WORKER_EXIT)  # scheduler restarts -> resume

``policy='observe'`` logs instead of raising — for jobs that prefer to
finish the epoch on the survivors' data shards and stop at the agreed
early-stop point.
"""

from __future__ import annotations

import logging

from tpusystem.parallel.multihost import WorkerJoined, WorkerLost
from tpusystem.services.prodcon import Consumer

logger = logging.getLogger('tpusystem.recovery')

# conventional exit code a launcher can map to "restart me"
LOST_WORKER_EXIT = 42


class WorkerLostError(RuntimeError):
    """A peer host died; the job should checkpoint-fence and restart."""

    def __init__(self, rank: int, last_seen: float):
        super().__init__(
            f'worker {rank} lost (last heartbeat at t={last_seen:.1f}); '
            'restart the job to resume from the last committed checkpoint')
        self.rank = rank
        self.last_seen = last_seen


def recovery_consumer(policy: str = 'abort') -> Consumer:
    """Consumer deciding what worker loss means for this job.

    ``'abort'`` (default): raise :class:`WorkerLostError` from the drain
    point — the restart-resume cycle above. ``'observe'``: log and carry
    on (the survivors still agree any stop collectively).
    """
    if policy not in ('abort', 'observe'):
        raise ValueError(f"policy must be 'abort' or 'observe', got {policy!r}")
    consumer = Consumer('recovery')

    @consumer.handler
    def on_worker_lost(event: WorkerLost) -> None:
        if policy == 'abort':
            raise WorkerLostError(event.rank, event.last_seen)
        logger.warning('worker %d lost (last seen t=%.1f); continuing',
                       event.rank, event.last_seen)

    @consumer.handler
    def on_worker_joined(event: WorkerJoined) -> None:
        logger.info('worker %d joined the control plane', event.rank)

    return consumer
