"""Elastic recovery policy: worker loss as a domain event with a decision.

The reference has no failure machinery beyond exceptions-as-events
(SURVEY.md §5 "failure detection / elastic recovery — absent"). The TPU
build's recovery story composes three existing pieces:

1. **detect** — the control plane surfaces a crashed or silent host as a
   :class:`~tpusystem.parallel.multihost.WorkerLost` event on every other
   host (socket death immediately; heartbeat timeout otherwise).
2. **decide** — the :func:`recovery_consumer` here turns that event into
   an :class:`WorkerLostError` raised on the host loop at the next
   ``runtime.sync()`` (remote events dispatch at drain time, so the error
   unwinds the epoch loop, never a collective mid-step).
3. **resume** — a TPU mesh cannot be resized live: recovery *is* restart.
   The job exits, the scheduler relaunches it, and the compilation
   pipeline's ``bring_epoch``/``restore_weights`` steps resume from the
   last committed checkpoint by identity hash (SURVEY.md §3.5) — the same
   path as an ordinary preemption.

Typical wiring::

    runtime.producer.register(recovery_consumer())
    try:
        for epoch in range(model.epoch, epochs):
            service.handle('iterate', model, loaders, metrics)
            runtime.sync()                  # WorkerLostError raises here
    except WorkerLostError as loss:
        repository.wait()                   # keep the last good checkpoint
        raise SystemExit(LOST_WORKER_EXIT)  # scheduler restarts -> resume

``policy='observe'`` logs instead of raising — for jobs that prefer to
finish the epoch on the survivors' data shards and stop at the agreed
early-stop point.
"""

from __future__ import annotations

import logging
import signal as signal_module

from tpusystem.parallel.multihost import WorkerJoined, WorkerLost
from tpusystem.services.prodcon import Consumer

logger = logging.getLogger('tpusystem.recovery')

# conventional exit codes a launcher maps to "restart me": 42 is a peer
# loss (the mesh must re-form), 43 a preemption of THIS host (SIGTERM from
# the scheduler); both resume from the last committed checkpoint. 44 is
# the sentinel's bounded give-up (DivergenceError): deliberately NOT in
# RESTART_EXITS — a blind relaunch of a deterministic divergence replays
# it; launchers should halt for triage (or cap automatic retries and
# adjust hyperparameters between attempts). 45 is emitted by the
# *launcher* side (:class:`tpusystem.parallel.Supervisor`) when the worker
# crash-loops: restartable exits kept arriving within seconds of launch,
# so relaunching has stopped making progress — halt for triage. 46 is the
# elastic-resize handshake (:mod:`tpusystem.parallel.elastic`): the
# supervisors agreed a NEW world size and this worker must be relaunched
# under the new world spec — restartable by definition (the relaunch IS
# the resize), and distinct from 42/43 so the timeline and ledger can
# tell a planned reshard from a fault. 47 is a deposed serving router
# (:class:`tpusystem.serve.fleet.RouterFenced`): a standby observed its
# missed lease renewals, fenced the term, and took over — deliberately
# NOT in RESTART_EXITS, because relaunching the old-term router would
# split-brain placements against the new incumbent; the supervisor
# halts it and the standby IS the restart. 1 is the generic non-restart
# failure (an unrecognized exception is a bug, not a recoverable fault —
# relaunching it forever would hide it).
LOST_WORKER_EXIT = 42
PREEMPTED_EXIT = 43
DIVERGED_EXIT = 44
CRASH_LOOP_EXIT = 45
RESIZED_EXIT = 46
ROUTER_FENCED_EXIT = 47
FAILURE_EXIT = 1
RESTART_EXITS = frozenset({LOST_WORKER_EXIT, PREEMPTED_EXIT, RESIZED_EXIT})


class WorkerLostError(RuntimeError):
    """A peer host died; the job should checkpoint-fence and restart.

    ``reason`` distinguishes the two detection paths — ``'socket'`` (the
    peer's connection died without a ``bye``: a crash or SIGKILL,
    detected immediately) vs ``'heartbeat'`` (the peer stopped
    heartbeating: alive-but-wedged, detected only after the liveness
    timeout). The two have different MTTR profiles — a socket death is
    seen in milliseconds, a heartbeat stall costs the full timeout before
    recovery even *starts* — so the ledger and recovery timeline record
    which one fired.
    """

    def __init__(self, rank: int, last_seen: float, reason: str = 'socket'):
        detail = ('socket death' if reason == 'socket'
                  else f'{reason} stall past the liveness timeout')
        super().__init__(
            f'worker {rank} lost to {detail} (last heartbeat at '
            f't={last_seen:.1f}); restart the job to resume from the last '
            'committed checkpoint')
        self.rank = rank
        self.last_seen = last_seen
        self.reason = reason


class Preempted(RuntimeError):
    """The scheduler is evicting this host (SIGTERM or maintenance notice).

    Raised on the host loop thread at the next ``runtime.sync()`` drain
    point — never from inside the signal handler, where the job could be
    mid-collective — so the epoch loop unwinds at a step boundary, fences
    an emergency checkpoint, and exits with :data:`PREEMPTED_EXIT`::

        try:
            ... epoch loop with runtime.sync() ...
        except (Preempted, WorkerLostError) as reason:
            checkpointer.save(identity, state.global_step, state,
                              extras=resume_extras(state, loader))
            checkpointer.fence(identity)        # durability receipt
            raise exit_for_restart(reason)
    """

    def __init__(self, signum: int):
        name = signal_module.Signals(signum).name
        super().__init__(
            f'preempted by {name}; checkpoint-fence and exit '
            f'{PREEMPTED_EXIT} so the scheduler restarts the job')
        self.signum = signum


class WorldResizedError(RuntimeError):
    """The supervisors agreed a new world size; this worker must restart
    under the new spec.

    Raised on the host loop at a drain point by
    :func:`tpusystem.parallel.elastic.elastic_consumer` when the elastic
    protocol (:class:`tpusystem.parallel.elastic.ElasticCoordinator`)
    commits a membership epoch while the worker is mid-run. Maps to
    :data:`RESIZED_EXIT` (46), which IS in :data:`RESTART_EXITS`: the
    relaunch is the resize — the supervisor re-execs the worker with the
    new world spec in its environment, the worker rebuilds the mesh at
    the agreed size and hot-reshards its state from the memstore tier
    (:func:`tpusystem.parallel.elastic.elastic_resume`).
    """

    def __init__(self, epoch: int, members: tuple):
        super().__init__(
            f'world resized to {len(members)} hosts (membership epoch '
            f'{epoch}, members {sorted(members)}); exit {RESIZED_EXIT} so '
            f'the supervisor relaunches under the new world spec')
        self.epoch = epoch
        self.members = tuple(members)


class DivergenceError(RuntimeError):
    """Training diverged beyond the sentinel's escalation ladder.

    Raised by :class:`tpusystem.train.Sentinel` when the bounded give-up is
    reached (skip → backoff → rollback all failed, or a cross-replica
    parity check flagged silent data corruption). Maps to
    :data:`DIVERGED_EXIT` (44) in the launcher contract — unlike 42/43 this
    is *not* an automatic-restart code: a deterministic divergence replays
    under a blind relaunch, so the launcher should halt for a human (or an
    automated sweep) to change something before retrying. An SDC parity
    failure also lands here: restart from the last committed checkpoint —
    which passed its parity check — after swapping out the suspect host.
    """

    def __init__(self, message: str, *, step: int | None = None):
        super().__init__(message)
        self.step = step


def exit_for_restart(reason: BaseException) -> SystemExit:
    """Map a recovery exception to its contract ``SystemExit``.

    ``raise exit_for_restart(error)`` ends the process with the exit code
    the launcher contract recognizes: :data:`RESTART_EXITS` (42 worker
    lost / 43 preempted / 46 resized) relaunch the job and resume from
    the last committed checkpoint (for 46: under the new world spec);
    :data:`DIVERGED_EXIT` (44, from :class:`DivergenceError`) halts for
    triage.

    Only the recovery exceptions map to contract codes. An exception
    from another layer can still opt into the contract by carrying an
    integer ``exit_code`` attribute (the serving router's
    :class:`~tpusystem.serve.fleet.RouterFenced` maps itself to
    :data:`ROUTER_FENCED_EXIT` this way — this module cannot import
    ``serve`` without a layering cycle). Anything else — a plain
    ``ValueError``, ``KeyboardInterrupt``, an assertion — is a *bug*,
    not a recoverable fault, and returns the generic
    :data:`FAILURE_EXIT`: mapping unknown exceptions to a restartable
    code (the old behavior) would relaunch a deterministic crash forever.

    Every mapping also flushes any installed
    :class:`~tpusystem.observe.FlightRecorder` with the verdict stamped
    (``reason``/``code``), so a typed contract exit always leaves its
    black box on disk before the process ends.
    """
    if isinstance(reason, WorkerLostError):
        code = LOST_WORKER_EXIT
    elif isinstance(reason, Preempted):
        code = PREEMPTED_EXIT
    elif isinstance(reason, WorldResizedError):
        code = RESIZED_EXIT
    elif isinstance(reason, DivergenceError):
        code = DIVERGED_EXIT
    elif isinstance(getattr(reason, 'exit_code', None), int):
        code = reason.exit_code          # e.g. RouterFenced -> 47
    else:
        code = FAILURE_EXIT
    try:   # the black box must never cost the contract its exit code
        from tpusystem.observe.flight import dump_installed
        dump_installed(reason=type(reason).__name__, code=code)
    except Exception:                            # pragma: no cover
        logger.exception('flight-recorder exit dump failed')
    return SystemExit(code)


def recovery_consumer(policy: str = 'abort') -> Consumer:
    """Consumer deciding what worker loss means for this job.

    ``'abort'`` (default): raise :class:`WorkerLostError` from the drain
    point — the restart-resume cycle above. ``'observe'``: log and carry
    on (the survivors still agree any stop collectively).
    """
    if policy not in ('abort', 'observe'):
        raise ValueError(f"policy must be 'abort' or 'observe', got {policy!r}")
    consumer = Consumer('recovery')

    @consumer.handler
    def on_worker_lost(event: WorkerLost) -> None:
        if policy == 'abort':
            raise WorkerLostError(event.rank, event.last_seen, event.reason)
        logger.warning('worker %d lost (%s, last seen t=%.1f); continuing',
                       event.rank, event.reason, event.last_seen)

    @consumer.handler
    def on_worker_joined(event: WorkerJoined) -> None:
        logger.info('worker %d joined the control plane', event.rank)

    return consumer
