"""Elastic training: agree a new world size, reshard in RAM, keep going.

The recovery stack restarts a fixed-size job in seconds (supervisor +
peer-replicated memstore) and topology-portable restore is proven
(``tests/test_multiprocess.py`` resumes a 4-device checkpoint on a
6-device world) — but a preemption wave still meant waiting for the lost
capacity or a cold full-world restart. Production fleets under
contention (Varuna, Bamboo, the spot-training literature) *shrink* on
loss and *grow* when capacity returns. This module closes that loop with
three pieces, all riding machinery the repo already has:

1. **Membership epochs** (:class:`ElasticCoordinator`) — supervisor-level
   agreement on the rank set. Loss/join events from the control-plane
   hub open a *wave*; after :attr:`ElasticPolicy.settle_window` seconds
   with no further change (so a 3-host wave triggers ONE resize, not
   three), each survivor broadcasts a ``(epoch, members)`` proposal over
   the event plane and commits when every proposed member has echoed the
   same proposal. Deliberately events + settle, not hub collectives: the
   hub's quota machinery excludes exactly the rejoining ranks a grow
   must re-admit (:meth:`~tpusystem.parallel.multihost.Hub.readmit`).
   Commitment restarts the workers under a new world spec with
   :data:`~tpusystem.parallel.recovery.RESIZED_EXIT`.

2. **Hot resharding** (:func:`elastic_resume` + :func:`collect_pieces`)
   — the relaunched workers rebuild the mesh at the agreed size
   (:meth:`~tpusystem.parallel.mesh.MeshSpec.resized`) and reassemble
   training state from the memstore tier: each survivor contributes its
   own :class:`~tpusystem.checkpoint.memstore.ShardedLeaf` pieces
   (``own:{identity}`` blob fetches), lost hosts' pieces come from their
   buddies' replica slots (``hot:{identity}``), the pieces merge
   (:func:`~tpusystem.checkpoint.memstore.merge_hot`) and re-lay onto
   the new mesh's shardings (``deserialize_state(..., reshard=True)``).
   Any digest/structure/missing-piece failure falls back to disk — the
   same rung discipline as
   :func:`~tpusystem.checkpoint.memstore.hot_resume`. Buddy pairs are
   re-derived from the new rank set and replication resumes immediately.

3. **The grow path** — a replacement host's supervisor dials the control
   plane, the hub's ``joined`` fanout (plus the joiner's own ``join``
   announcement) opens the next settle window, and the world expands
   back, bounded by :attr:`ElasticPolicy.max_world` and rate-limited by
   :attr:`ElasticPolicy.cooldown`.

Every transition is a domain event (``WorldResizeProposed`` /
``WorldResized`` / ``ElasticTimeline``) so the ledger orders a
preemption-wave incident and TensorBoard charts world size and resize
latency with zero trainer code. The serving fleet's traffic-driven
autoscaler (:mod:`tpusystem.serve.fleet`) is a second client of this
resize seam: its ``provision``/``release`` callables carve a serving
replica's capacity out of the training world (and give it back on ebb)
through exactly this membership protocol plus
:meth:`~tpusystem.parallel.supervisor.Supervisor.resize` — one fleet,
traffic-shaped. The chaos drill is the contract
(``tests/test_elastic.py``): kill k of n hosts mid-run → ONE resize →
training continues at n−k with state bitwise-equivalent to restoring the
same step from disk onto the shrunk mesh → a returning host grows the
world back — never a cold full-world restart.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from tpusystem.observe.events import WorldResized
from tpusystem.parallel.multihost import BlobError

logger = logging.getLogger('tpusystem.elastic')

__all__ = ['ELASTIC_ENV', 'ElasticPolicy', 'ResizeDecision',
           'ElasticCoordinator', 'elastic_consumer', 'elastic_resume',
           'collect_pieces', 'split_pieces']

# how a relaunched worker learns the agreed world spec (JSON:
# {"epoch": E, "members": [...], "member": this host's original rank})
ELASTIC_ENV = 'TPUSYSTEM_ELASTIC'

# the control-plane event channel the proposal exchange rides
ELASTIC_CHANNEL = 'elastic'


@dataclass(frozen=True)
class ResizeDecision:
    """One committed membership epoch: the agreed rank set.

    ``members`` are *original* supervisor ranks (stable across resizes —
    a replaced host re-joins under its original rank); workers address
    the new world through :meth:`rank_of` (dense 0..size-1 ranks in
    member order) and :meth:`buddy_of` (pairs re-derived from the new
    ordering, ``new_rank ^ 1`` — the last member of an odd world has no
    buddy and relies on disk).
    """

    epoch: int
    members: tuple[int, ...]

    @property
    def size(self) -> int:
        return len(self.members)

    def rank_of(self, member: int) -> int:
        """The dense rank of ``member`` in the new world."""
        return self.members.index(member)

    def buddy_of(self, member: int) -> int | None:
        """The member this one mirrors hot state with under the new
        pairing, or None (odd world's unpaired last member)."""
        paired = self.rank_of(member) ^ 1
        return self.members[paired] if paired < self.size else None

    def env(self, member: int) -> dict[str, str]:
        """The environment entry a relaunched worker reads to learn the
        new world (:meth:`from_env`)."""
        return {ELASTIC_ENV: json.dumps(
            {'epoch': self.epoch, 'members': list(self.members),
             'member': member})}

    @classmethod
    def from_env(cls, env: dict | None = None
                 ) -> tuple['ResizeDecision', int] | None:
        """Parse :data:`ELASTIC_ENV` → ``(decision, member)`` or None
        (not an elastic relaunch)."""
        import os
        spec = (env if env is not None else os.environ).get(ELASTIC_ENV)
        if not spec:
            return None
        try:
            payload = json.loads(spec)
            decision = cls(epoch=int(payload['epoch']),
                           members=tuple(int(m)
                                         for m in payload['members']))
            return decision, int(payload['member'])
        except (ValueError, KeyError, TypeError) as error:
            logger.warning('malformed %s=%r (%s); ignoring', ELASTIC_ENV,
                           spec, error)
            return None


@dataclass
class ElasticPolicy:
    """The resize policy knobs.

    Args:
        min_world: never agree a world smaller than this — a wave that
            would shrink below it leaves the coordinator waiting for
            capacity to return (disk checkpoints still protect the run).
        max_world: cap on grows (None: the original size is the cap the
            caller usually wants; pass explicitly). Joiners beyond the
            cap stay pending for a later wave.
        settle_window: seconds of quiet after the last membership change
            before a proposal is broadcast — the one-wave-one-resize
            knob: every loss/join inside the window folds into the same
            epoch.
        cooldown: seconds after a commit during which new changes
            accumulate but do not open a wave — rate-limits resize churn
            under flapping capacity.
        rebroadcast: proposal re-send interval while uncommitted (events
            are at-most-once; a dropped proposal must not stall the
            epoch forever).
    """

    min_world: int = 1
    max_world: int | None = None
    settle_window: float = 2.0
    cooldown: float = 0.0
    rebroadcast: float = 0.5


class ElasticCoordinator:
    """Supervisor-side membership-epoch agreement.

    Attach one per supervisor to the *supervisor pod's* control plane
    (the same transport the buddy replication rides). Loss/join frames
    from the hub feed the wave; ``step()`` drives the protocol on the
    caller's thread (or :meth:`start` spawns a polling thread). Events
    are dispatched on whichever thread calls ``step()``.

    Args:
        transport: the supervisor's control-plane client.
        rank: this supervisor's original rank.
        size: the initial world size (``members`` defaults to
            ``range(size)``). A *replacement* host joining an already
            resized pod passes ``members=None``: it bootstraps by
            adopting the first proposal that includes it.
        policy: the :class:`ElasticPolicy` knobs.
        producer: event bus for ``WorldResizeProposed`` /
            ``WorldResized`` / ``ElasticTimeline``.
        on_resize: called with the :class:`ResizeDecision` on every
            commit — the supervisor's restart hook
            (:meth:`~tpusystem.parallel.supervisor.Supervisor.resize`).
        clock: injection seam for the settle/cooldown arithmetic.
    """

    def __init__(self, transport: Any, rank: int, size: int | None = None,
                 *, policy: ElasticPolicy | None = None, producer: Any = None,
                 on_resize: Callable[[ResizeDecision], None] | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 tracer: Any = None,
                 members: tuple[int, ...] | None = 'from-size') -> None:
        self.transport = transport
        self.rank = rank
        self.policy = policy if policy is not None else ElasticPolicy()
        self.producer = producer
        self.on_resize = on_resize
        self._clock = clock
        # observe.Tracer | None: each committed wave becomes a parent
        # span (wave-open → resumed) with one child span per stage
        # transition — the span form of ElasticTimeline, same clock
        self.tracer = tracer
        if members == 'from-size':
            members = tuple(range(size)) if size is not None else None
        self.members: tuple[int, ...] | None = (
            tuple(sorted(members)) if members is not None else None)
        self.epoch = 0
        self.decisions: list[ResizeDecision] = []
        self._inbox: queue.SimpleQueue = queue.SimpleQueue()
        self._lost: set[int] = set()
        self._joins: set[int] = set()
        self._wave_opened: float | None = None
        self._settle_at = 0.0
        self._cooldown_until = 0.0
        self._proposal: tuple[int, tuple[int, ...]] | None = None
        self._votes: dict[int, tuple[int, tuple[int, ...]]] = {}
        self._last_broadcast = 0.0
        self._stages: dict[str, float] = {}
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None
        transport.subscribe(ELASTIC_CHANNEL, self._ingest)
        self._previous_on_control = transport.on_control

        def on_control(frame: tuple) -> None:
            self._ingest(frame)
            if self._previous_on_control is not None:
                self._previous_on_control(frame)
        self._on_control = on_control
        transport.on_control = on_control
        if self.members is None:
            # replacement-host bootstrap: announce; the survivors' hub
            # 'joined' fanout usually covers this, but a coordinator
            # attached after that fanout passed must still be seen
            self._send(('join', self.rank))

    # ------------------------------------------------------------------
    # wire

    def _send(self, message: tuple) -> None:
        try:
            self.transport.send_event(ELASTIC_CHANNEL, message)
        except OSError as error:
            logger.warning('elastic frame %r not sent (%s); the rebroadcast '
                           'loop retries', message[0], error)

    def _dispatch(self, event: Any) -> None:
        if self.producer is not None:
            self.producer.dispatch(event)

    # ------------------------------------------------------------------
    # the protocol

    def step(self) -> ResizeDecision | None:
        """Drive the protocol once on the caller's thread; returns the
        committed :class:`ResizeDecision` when this call commits one."""
        self._drain()
        now = self._clock()
        if (self._proposal is None and self.members is not None
                and (self._lost or self._joins)
                and now >= self._settle_at and now >= self._cooldown_until):
            self._open_proposal(now)
        if self._proposal is not None:
            if now - self._last_broadcast >= self.policy.rebroadcast:
                self._broadcast(now)
            return self._try_commit(now)
        return None

    def start(self, interval: float = 0.05) -> 'ElasticCoordinator':
        """Poll :meth:`step` on a daemon thread every ``interval``s."""
        def loop() -> None:
            while not self._closed.wait(interval):
                self.step()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def _ingest(self, frame: tuple) -> None:
        # nothing drains a closed coordinator's inbox — frames arriving
        # after close() (the transport outlives us: a replacement host
        # builds a NEW coordinator on the same wire) must not pile up
        if not self._closed.is_set():
            self._inbox.put(frame)

    def close(self) -> None:
        self._closed.set()
        # unhook from the transport chain where we are still the head;
        # if another hook was chained on top of ours after construction,
        # the _ingest guard above still makes us inert
        if self.transport.on_control is self._on_control:
            self.transport.on_control = self._previous_on_control

    # ------------------------------------------------------------------

    def _open_wave(self, now: float) -> None:
        if self._wave_opened is None:
            self._wave_opened = now
            self._stages = {}
        self._settle_at = now + self.policy.settle_window

    def _drain(self) -> None:
        while True:
            try:
                frame = self._inbox.get_nowait()
            except queue.Empty:
                return
            kind = frame[0]
            now = self._clock()
            if kind == 'lost':
                self._on_lost(frame[1], now)
            elif kind in ('joined', 'join'):
                self._on_join(frame[1], now)
            elif kind == 'propose':
                self._on_propose(frame[1], frame[2], tuple(frame[3]), now)

    def _on_lost(self, lost: int, now: float) -> None:
        if self.members is None or lost not in self.members:
            self._joins.discard(lost)        # a joiner that died mid-join
            return
        if lost in self._lost:
            return
        self._lost.add(lost)
        self._joins.discard(lost)
        logger.warning('elastic: rank %d lost; wave settles in %.1fs',
                       lost, self.policy.settle_window)
        if self._proposal is not None and lost in self._proposal[1]:
            # a proposed member died before the commit: the wave is not
            # over — withdraw and re-settle so the NEXT proposal covers
            # the whole wave (one resize, not two)
            self._proposal = None
            self._votes.clear()
        self._open_wave(now)

    def _on_join(self, joiner: int, now: float) -> None:
        if self.members is None or joiner == self.rank:
            return
        if joiner in self.members and joiner not in self._lost:
            return                            # initial pod assembly noise
        if joiner in self._lost:
            # the "lost" host came back within the settle window (a
            # flapped link, a fast replacement): cancel the loss
            self._lost.discard(joiner)
            self._open_wave(now)
            return
        if joiner in self._joins:
            return
        self._joins.add(joiner)
        logger.info('elastic: rank %d joined; wave settles in %.1fs',
                    joiner, self.policy.settle_window)
        self._open_wave(now)

    def _on_propose(self, sender: int, epoch: int,
                    proposed: tuple[int, ...], now: float) -> None:
        if self.members is None:
            # replacement-host bootstrap: adopt the first epoch that
            # includes us and echo it — the commit rule (every proposed
            # member voted) then completes on every survivor and on us
            self._votes[sender] = (epoch, proposed)
            if self._proposal is None and self.rank in proposed:
                self.epoch = epoch - 1
                self._proposal = (epoch, proposed)
                self._votes[self.rank] = self._proposal
                if self._wave_opened is None:
                    self._wave_opened = now
                self._broadcast(now)
            return
        if epoch <= self.epoch:
            if epoch == self.epoch and proposed == self.members:
                # a straggler still collecting votes for an epoch we
                # already committed (our pre-commit broadcasts to it were
                # dropped): re-echo so it can complete
                self._send(('propose', self.rank, epoch, proposed))
            return
        self._votes[sender] = (epoch, proposed)
        if self._proposal == (epoch, proposed):
            return
        # their epoch outranks ours: we lagged — missed frames, or we
        # were flapped out of an epoch that committed without us. Align
        # our epoch base so the proposal we make next can MATCH theirs
        # (votes compare exact (epoch, members) tuples; proposing a
        # lower epoch could never commit).
        if epoch - 1 > self.epoch:
            self.epoch = epoch - 1
        their = set(proposed)
        ours = set(self.members)
        lost = ours - their
        joins = their - ours - {self.rank}
        if not lost and not joins:
            # their higher epoch names OUR exact member set: a
            # re-admission after a commit we never saw (we were the one
            # flapped out). The commit needs our echo — adopt, like the
            # bootstrap path.
            if self.rank in proposed:
                self.epoch = epoch - 1
                self._proposal = (epoch, proposed)
                self._votes[self.rank] = self._proposal
                if self._wave_opened is None:
                    self._wave_opened = now
                self._broadcast(now)
            return
        # fold the difference into our pending changes (the hub
        # broadcasts every loss/join to everyone, so views converge;
        # this is the catch-up for a coordinator whose frames lagged)
        # and close our window — the peer's window closing IS the
        # wave's settle
        self._lost |= lost
        self._joins |= joins
        if self._proposal is not None and self._proposal[1] != proposed:
            self._proposal = None
        self._open_wave(now)
        self._settle_at = now                 # settle immediately: catch up

    def _target(self) -> tuple[int, ...]:
        target = (set(self.members) - self._lost) | self._joins
        cap = self.policy.max_world
        if cap is not None and len(target) > cap:
            # keep existing members first, then the lowest-ranked joiners
            keep = sorted(set(self.members) & target)
            for joiner in sorted(target - set(keep)):
                if len(keep) >= cap:
                    break
                keep.append(joiner)
            target = set(keep[:cap])
        return tuple(sorted(target))

    def _open_proposal(self, now: float) -> None:
        from tpusystem.observe.events import WorldResizeProposed
        target = self._target()
        if len(target) < self.policy.min_world:
            logger.error(
                'elastic: wave would shrink the world to %d (< min_world '
                '%d); holding at %d members and waiting for capacity',
                len(target), self.policy.min_world, len(self.members))
            self._settle_at = now + self.policy.settle_window
            return
        if target == self.members:            # e.g. a loss flapped back
            self._lost.clear()
            # joiners the max_world cap held out stay PENDING (the
            # policy's documented contract) — the next wave with room
            # (a loss) folds them in; only joins already folded clear
            self._joins -= set(self.members)
            self._wave_opened = None
            if self._joins:
                logger.info(
                    'elastic: joiner(s) %s wait beyond max_world=%s for a '
                    'later wave', sorted(self._joins),
                    self.policy.max_world)
                self._settle_at = now + self.policy.settle_window
            return
        cause = ('both' if self._lost and self._joins
                 else 'loss' if self._lost else 'join')
        self._proposal = (self.epoch + 1, target)
        self._votes[self.rank] = self._proposal
        self._stages.setdefault('propose', now - self._wave_opened)
        self._broadcast(now)
        self._dispatch(WorldResizeProposed(rank=self.rank,
                                           epoch=self.epoch + 1,
                                           members=list(target), cause=cause))

    def _broadcast(self, now: float) -> None:
        epoch, proposed = self._proposal
        self._send(('propose', self.rank, epoch, proposed))
        self._last_broadcast = now

    def _try_commit(self, now: float) -> ResizeDecision | None:
        from tpusystem.observe.events import WorldResized
        epoch, proposed = self._proposal
        agreed = {sender for sender, vote in self._votes.items()
                  if vote == self._proposal}
        if not set(proposed) <= agreed:
            return None
        decision = ResizeDecision(epoch=epoch, members=proposed)
        opened = self._wave_opened if self._wave_opened is not None else now
        seconds = now - opened
        self.epoch = epoch
        self.members = proposed
        self._lost.clear()
        self._joins -= set(proposed)
        self._proposal = None
        self._votes.clear()
        self._wave_opened = None
        self._cooldown_until = now + self.policy.cooldown
        self._stages.setdefault('commit', seconds)
        self._commit_stages = dict(self._stages)
        self._committed_at = now - seconds     # wave-open wall anchor
        self.decisions.append(decision)
        logger.info('elastic: epoch %d committed — world is %d members %s '
                    '(%.3fs wave->commit)', epoch, decision.size,
                    list(proposed), seconds)
        self._dispatch(WorldResized(epoch=epoch, members=list(proposed),
                                    size=decision.size, seconds=seconds))
        if self.on_resize is not None:
            self.on_resize(decision)
        return decision

    def resumed(self, step: int | None = None,
                source: str | None = None, **stages: float) -> None:
        """Close the elastic timeline: training resumed at the new size.

        Called by the resharding side after the first post-resize step;
        emits :class:`~tpusystem.observe.events.ElasticTimeline` with
        stage offsets relative to the wave opening."""
        from tpusystem.observe.events import ElasticTimeline
        if not self.decisions:
            return
        decision = self.decisions[-1]
        now = self._clock()
        anchor = getattr(self, '_committed_at', now)
        timeline = dict(getattr(self, '_commit_stages', {}))
        timeline.update(stages)
        timeline.setdefault('resumed', now - anchor)
        seconds = now - anchor
        if self.tracer is not None and timeline:
            root = self.tracer.record(
                f'elastic-resize epoch{decision.epoch}', anchor, now,
                cat='elastic', args={'epoch': decision.epoch,
                                     'size': decision.size, 'step': step,
                                     'source': source})
            previous = ('wave-open', anchor)
            for stage, offset in sorted(timeline.items(),
                                        key=lambda kv: kv[1]):
                self.tracer.record(f'{previous[0]}→{stage}', previous[1],
                                   anchor + offset, cat='elastic',
                                   trace=root.context)
                previous = (stage, anchor + offset)
        self._dispatch(ElasticTimeline(epoch=decision.epoch,
                                       size=decision.size, step=step,
                                       source=source, seconds=seconds,
                                       stages=timeline))


def elastic_consumer():
    """Worker-side resize policy: a committed ``WorldResized`` event
    raises :class:`~tpusystem.parallel.recovery.WorldResizedError` at the
    next ``runtime.sync()`` drain — the elastic sibling of
    :func:`~tpusystem.parallel.recovery.recovery_consumer`.

    Register it on the worker's producer and wire ``WorldResized`` over
    the worker control plane (or dispatch it locally from whatever
    observes the supervisor's commit): the epoch loop unwinds at a step
    boundary — never mid-collective — checkpoint-fences, and exits
    :data:`~tpusystem.parallel.recovery.RESIZED_EXIT` so the supervisor
    relaunches it under the new world spec::

        runtime.producer.register(elastic_consumer())
        try:
            ... epoch loop with runtime.sync() ...
        except WorldResizedError as resize:
            checkpointer.fence(identity)
            raise exit_for_restart(resize)      # exit 46

    Workers whose supervisor drives the restart directly
    (:meth:`~tpusystem.parallel.supervisor.Supervisor.resize` SIGTERMs
    them) do not need this — the consumer is for jobs that learn of the
    commit on their own bus first and want the 46-coded drain.
    """
    from tpusystem.parallel.recovery import WorldResizedError
    from tpusystem.services.prodcon import Consumer
    consumer = Consumer('elastic')

    @consumer.handler
    def on_world_resized(event: WorldResized) -> None:
        raise WorldResizedError(event.epoch, tuple(event.members))

    return consumer


# ---------------------------------------------------------------------------
# hot resharding


def split_pieces(state: Any, mesh: Any, hosts: int) -> list[bytes]:
    """Serialize ``state`` as if ``mesh`` were spread over ``hosts``
    processes: per-host blobs carrying only that host's device shards as
    :class:`~tpusystem.checkpoint.memstore.ShardedLeaf` pieces.

    On a real pod :func:`~tpusystem.checkpoint.memstore.serialize_state`
    produces exactly this shape naturally (each process only addresses
    its own shards); on a single process with virtual devices every leaf
    is fully addressable, so the multi-host piece contract would go
    unexercised. This is the simulation seam the chaos drill
    (``tests/test_elastic.py``) and the dryrun's elastic stage use to
    drive the merge/reshard path without real processes: host ``h`` owns
    the ``h``-th contiguous slice of ``mesh``'s flattened device order
    (the same host-major order a pod lays devices out in).
    """
    import pickle

    import jax
    import numpy as np

    from tpusystem.checkpoint.memstore import ShardedLeaf, _index_key
    devices = list(mesh.devices.flatten())
    if len(devices) % hosts:
        raise ValueError(f'{len(devices)} devices do not split over '
                         f'{hosts} hosts evenly')
    per_host = len(devices) // hosts
    owner = {device: index // per_host
             for index, device in enumerate(devices)}
    leaves_per_host: list[list] = [[] for _ in range(hosts)]
    for leaf in jax.tree.leaves(state):
        shards = getattr(leaf, 'addressable_shards', None)
        if shards is None:
            value = np.asarray(jax.device_get(leaf))
            for held in leaves_per_host:
                held.append(value)
            continue
        pieces: list[dict] = [{} for _ in range(hosts)]
        for shard in shards:
            host = owner.get(shard.device)
            if host is None:
                continue                  # a leaf placed off-mesh
            key = _index_key(shard.index, leaf.shape)
            pieces[host].setdefault(key, np.asarray(shard.data))
        dtype = np.dtype(leaf.dtype).str
        for host, held in enumerate(leaves_per_host):
            held.append(ShardedLeaf(tuple(leaf.shape), dtype, pieces[host]))
    return [pickle.dumps(held, protocol=pickle.HIGHEST_PROTOCOL)
            for held in leaves_per_host]


def collect_pieces(identity: str, *, rank: int, members, survivors,
                   store: Any = None, transport: Any = None,
                   buddy_of: Callable[[int], int | None] | None = None,
                   timeout: float = 10.0) -> list:
    """Gather every old-world host's hot pieces for an elastic reshard.

    For each member of the OLD world: this host's own pieces come from
    its supervisor's local slot (``store``); a *surviving* peer's pieces
    are fetched from its supervisor over the blob plane
    (``own:{member}:{identity}``); a *lost* host's pieces are pulled
    from its buddy's replica slot (``hot:{member}:{identity}``,
    ``buddy_of`` is the OLD pairing — the member segment keeps
    concurrent fetches key-distinct on this transport). Remote fetches
    run CONCURRENTLY (the reshard exists to beat the disk restore's
    wall clock; a 16-host world must not pay 15 serial round-trips, and
    an unreachable peer must cost one ``timeout``, not stack).
    Unfetchable contributions are skipped with a log — the caller's
    :func:`elastic_resume` detects incomplete coverage at placement
    time and falls back to disk. Transfer cost per contribution is that
    host's local shard bytes, not the global model.
    """
    from concurrent.futures import ThreadPoolExecutor

    from tpusystem.checkpoint.memstore import unpack_hot
    survivors = set(survivors)
    entries = []
    plan: list[tuple[int, int, str, str]] = []   # (member, peer, key, what)
    for member in sorted(members):
        if member == rank:
            entry = store.newest(identity) if store is not None else None
            if entry is None:
                logger.warning('elastic: no local hot state for %r on rank '
                               '%d', identity, rank)
            else:
                entries.append(entry)
            continue
        if transport is None:
            continue
        if member in survivors:
            plan.append((member, member, f'own:{member}:{identity}',
                         'survivor'))
        else:
            buddy = buddy_of(member) if buddy_of is not None else None
            if buddy is None or buddy not in survivors:
                logger.warning(
                    'elastic: lost rank %d has no surviving buddy — its hot '
                    'pieces are unrecoverable (disk is the fallback)', member)
                continue
            if buddy == rank:
                # WE are the lost host's buddy: its pieces sit in our own
                # replica slot — no self-routed fetch
                entry = (store.newest(identity, replica=True)
                         if store is not None else None)
                if entry is None:
                    logger.warning('elastic: no local replica of lost rank '
                                   '%d\'s pieces for %r', member, identity)
                else:
                    entries.append(entry)
                continue
            plan.append((member, buddy, f'hot:{member}:{identity}',
                         'buddy replica'))

    def fetch(job: tuple[int, int, str, str]):
        member, peer, key, what = job
        try:
            return unpack_hot(transport.fetch_blob(peer, key,
                                                   timeout=timeout))
        except BlobError as error:
            logger.warning('elastic: no %s pieces for rank %d from rank %d '
                           '(%s); disk is the fallback', what, member, peer,
                           error)
            return None
    if plan:
        with ThreadPoolExecutor(max_workers=min(8, len(plan))) as pool:
            entries.extend(entry for entry in pool.map(fetch, plan)
                           if entry is not None)
    return entries


def elastic_resume(checkpointer: Any, identity: str, target: Any,
                   contributions, client: Any = None
                   ) -> tuple[Any, int, Any | None, str]:
    """Resume onto a RESIZED mesh, preferring merged hot pieces over disk.

    ``target`` is a (concrete or abstract) pytree already laid out for
    the NEW mesh; ``contributions`` is the piece set from
    :func:`collect_pieces` (or any iterable of
    :class:`~tpusystem.checkpoint.memstore.HotState`). Returns
    ``(state, step, extras, source)`` with ``source`` in
    ``{'hot-reshard', 'disk'}``.

    The preference follows :func:`~tpusystem.checkpoint.memstore.
    hot_resume`'s rung discipline — RAM wins only when it cannot lose
    information or integrity: contributions must agree on one step, that
    step must be >= the newest committed disk step, every leaf's pieces
    must cover the full array under the merge, and shapes/structure must
    match the target. Any failure logs and falls back to the disk
    checkpoint restored onto the same (new) shardings — which is why the
    chaos drill can demand bitwise equivalence between the two paths.
    """
    import pickle

    from tpusystem.checkpoint.checkpointer import abstract_like
    from tpusystem.checkpoint.memstore import deserialize_state, merge_hot
    entries = [entry for entry in contributions if entry is not None]
    hot = None
    if entries:
        try:
            hot = merge_hot(entries)
        except (ValueError, pickle.UnpicklingError) as error:
            logger.warning('elastic: hot pieces for %r did not merge (%s); '
                           'restoring from disk', identity, error)
    if hot is not None:
        disk_step = checkpointer.latest(identity)
        if disk_step is not None and hot.step < disk_step:
            logger.warning(
                'elastic: merged hot state for %r is stale (step %d < '
                'committed disk step %d); restoring from disk', identity,
                hot.step, disk_step)
            hot = None
    result = None
    if hot is not None:
        try:
            state = deserialize_state(hot.blob, abstract_like(target),
                                      reshard=True)
            result = (state, hot.step, hot.extras, 'hot-reshard')
        except (ValueError, pickle.UnpicklingError) as error:
            logger.warning('elastic: merged hot state for %r step %d failed '
                           'to reshard (%s); restoring from disk', identity,
                           hot.step, error)
    if result is None:
        state, step, extras = checkpointer.resume(identity, target)
        result = (state, step, extras, 'disk')
    mark = getattr(client, 'mark', None)
    if mark is not None:
        mark('restore', source=result[3], step=result[1])
    return result
