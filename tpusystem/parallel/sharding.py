"""GSPMD sharding policies — parallelism as injectable strategy objects.

The reference has no parallelism at all (SURVEY.md §2.4); this module is
where the TPU build supplies DP/FSDP/TP as *policy objects* chosen in the
compiler pipeline (build -> place-on-mesh -> jit). A policy maps every leaf
of a state pytree to a ``PartitionSpec``:

1. **regex rules** (tensor parallelism): first pattern matching the leaf's
   ``/``-joined path wins — model families ship their own rule sets
   (e.g. ``attention/query/kernel -> P('fsdp' if combined else None, 'model')``).
   Optimizer slot variables (``mu/nu``) contain the parameter path as a
   suffix, so one rule set covers params *and* optimizer state.
2. **FSDP inference** (shape-based): any leaf still owning an unsharded
   dimension divisible by the ``fsdp`` axis size gets its largest such
   dimension sharded — ZeRO-3-style parameter + optimizer-state scatter
   with zero per-model configuration.

Placing leaves with the resulting ``NamedSharding`` is all GSPMD needs: the
jitted step then runs with all-gathers/reduce-scatters inserted over ICI.
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpusystem.parallel.mesh import EXPERT, FSDP, MODEL
from tpusystem.registry import register

Rules = Sequence[tuple[str, PartitionSpec]]

# The embedding-table sharding axes: recommender tables row-shard their
# vocab dimension over the combined (expert, model) axes — the two axes
# the LLM policies reserve for weight splitting, which a
# params-dominated sparse workload repurposes for table rows.
TABLE_AXES = (EXPERT, MODEL)


def table_row_spec(rank: int) -> PartitionSpec:
    """Spec for a row-sharded embedding table: the leading (vocab)
    dimension splits over the combined ``expert``/``model`` axes, every
    other dim stays unsharded. The expert-major shard order (expert
    index major, model index minor) is the contract the device-side id
    routing in :mod:`tpusystem.recsys.embedding` derives offsets from."""
    return PartitionSpec(TABLE_AXES, *([None] * (rank - 1)))


def constrain_table_rows(value, mesh):
    """Pin a row-sharded table (or table-shaped activation) to the
    ``expert``/``model`` axes (no-op off-mesh or when both are size 1).

    The :func:`constrain_expert_major` sibling for the recommender
    workload — the single annotation point
    :class:`tpusystem.recsys.ShardedEmbedding` applies to the table
    right before its routed ``shard_map``, so GSPMD holds the param
    row-sharded up to the manual boundary (no reshard) instead of
    choosing its own layout. Axes absent from a hand-built mesh are
    dropped (a ``MeshSpec`` mesh always carries all six at size >= 1)."""
    if mesh is None:
        return value
    present = tuple(axis for axis in TABLE_AXES
                    if axis in mesh.axis_names)
    if all(mesh.shape[axis] == 1 for axis in present):
        return value
    spec = PartitionSpec(present, *([None] * (value.ndim - 1)))
    return jax.lax.with_sharding_constraint(
        value, NamedSharding(mesh, spec))


def expert_major_spec(rank: int) -> PartitionSpec:
    """Spec for expert-major activation buffers: the leading dim carries
    the expert id — either explicitly (``[experts, capacity, dim]``) or
    flattened into the row index (``[experts * capacity, dim]``, the
    layout the fused grouped-matmul kernels and the gather/scatter
    dispatch buffers share) — and shards over the ``expert`` mesh axis;
    every other dim stays unsharded."""
    return PartitionSpec(EXPERT, *([None] * (rank - 1)))


def constrain_expert_major(value, mesh):
    """Pin an expert-major buffer to the ``expert`` axis (no-op off-mesh).

    The single annotation point for MoE dispatch intermediates — the
    dense one-hot einsum operands and the sparse
    ``[experts, capacity, dim]`` buffers — so GSPMD places the expert
    FFN's inputs/outputs on the experts' owners instead of choosing.
    Design note for the fused grouped-matmul path (single-shard today,
    and ``MoEMLP`` raises rather than silently substituting it on a
    multi-device mesh): ``pallas_call`` is a manual computation GSPMD
    cannot split, so a sharded-fused path would run the kernels one
    device per shard inside ``shard_map`` (like the flash kernels), with
    this constraint keeping the surrounding auto-partitioned tensors
    aligned to that boundary."""
    if mesh is None or mesh.shape.get(EXPERT, 1) == 1:
        return value
    sharding = NamedSharding(mesh, expert_major_spec(value.ndim))
    return jax.lax.with_sharding_constraint(value, sharding)


def leaf_path(key_path) -> str:
    """Render a jax key path as ``a/b/0/c`` for regex matching."""
    parts = []
    for entry in key_path:
        if hasattr(entry, 'key'):
            parts.append(str(entry.key))
        elif hasattr(entry, 'name'):
            parts.append(str(entry.name))
        elif hasattr(entry, 'idx'):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return '/'.join(parts)


def _mesh_axis_size(mesh: Mesh, axis) -> int:
    if axis is None:
        return 1
    if isinstance(axis, (tuple, list)):
        size = 1
        for name in axis:
            size *= mesh.shape[name]
        return size
    return mesh.shape[axis]


def fsdp_shard_dim(shape: tuple[int, ...], fsdp_size: int,
                   taken: Sequence[int] = ()) -> int | None:
    """Which dimension FSDP inference shards — the single source of truth.

    The **largest** dimension not in ``taken`` (indices already claimed by
    rule axes) whose size divides ``fsdp_size``; ``None`` when no dimension
    qualifies. Ties are broken **deterministically: the lowest index
    wins** — the choice is pinned here (and regression-tested) so a param
    tree can never silently reshard across jax/python versions from an
    enumeration-order change, which would invalidate every checkpoint
    placed under the old choice. The overlap scheduler's
    :func:`tpusystem.parallel.schedule.fsdp_plan` consults this same
    function, so the manual prefetch collectives always agree with the
    placement the policy chose.
    """
    candidates = [index for index in range(len(shape))
                  if index not in taken and shape[index] % fsdp_size == 0]
    if not candidates:
        return None
    return min(candidates, key=lambda index: (-shape[index], index))


def _with_fsdp(spec: PartitionSpec, shape: tuple[int, ...], mesh: Mesh,
               min_size: int) -> PartitionSpec:
    """Add the fsdp axis to the largest unsharded, divisible dimension
    (ties: lowest index — see :func:`fsdp_shard_dim`)."""
    fsdp_size = mesh.shape[FSDP]
    if fsdp_size == 1:
        return spec
    if _leaf_elements(shape) < min_size:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    if any(axis == FSDP or (isinstance(axis, tuple) and FSDP in axis)
           for axis in entries):
        return spec
    taken = [index for index, axis in enumerate(entries) if axis is not None]
    best = fsdp_shard_dim(tuple(shape), fsdp_size, taken)
    if best is None:
        return spec
    entries[best] = FSDP
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def _leaf_elements(shape: tuple[int, ...]) -> int:
    total = 1
    for size in shape:
        total *= size
    return total


@register
class ShardingPolicy:
    """Composable sharding strategy.

    Args:
        rules: ``(regex, PartitionSpec)`` pairs, first match wins. Patterns
            are ``re.search`` against the leaf path.
        fsdp: infer fsdp-axis sharding for leaves the rules left unsharded.
        fsdp_min_size: leaves with fewer elements stay replicated (tiny
            tensors cost more to gather than they save).
    """

    def __init__(self, rules: Rules = (), fsdp: bool = False,
                 fsdp_min_size: int = 4096):
        self.rules = [(re.compile(pattern), spec) for pattern, spec in rules]
        self.fsdp = fsdp
        self.fsdp_min_size = fsdp_min_size

    def spec(self, path: str, shape: tuple[int, ...], mesh: Mesh) -> PartitionSpec:
        chosen = PartitionSpec()
        for pattern, spec in self.rules:
            if pattern.search(path):
                chosen = spec
                break
        # drop rule axes that don't divide the dimension (e.g. seq axis on a
        # mesh where seq=1 always divides; model axis on an odd head count)
        entries = list(chosen) + [None] * (len(shape) - len(chosen))
        entries = [axis if axis is None or shape[index] % _mesh_axis_size(mesh, axis) == 0
                   else None
                   for index, axis in enumerate(entries)]
        while entries and entries[-1] is None:
            entries.pop()
        chosen = PartitionSpec(*entries)
        if self.fsdp:
            chosen = _with_fsdp(chosen, shape, mesh, self.fsdp_min_size)
        return chosen

    def tree_specs(self, tree: Any, mesh: Mesh) -> Any:
        """PartitionSpec pytree matching ``tree``'s structure."""
        def assign(key_path, leaf):
            shape = getattr(leaf, 'shape', ())
            return self.spec(leaf_path(key_path), tuple(shape), mesh)
        return jax.tree_util.tree_map_with_path(assign, tree)

    def tree_shardings(self, tree: Any, mesh: Mesh) -> Any:
        return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                            self.tree_specs(tree, mesh))

    def place(self, tree: Any, mesh: Mesh) -> Any:
        """Materialize the tree on the mesh with this policy's shardings."""
        return jax.device_put(tree, self.tree_shardings(tree, mesh))


def DataParallel() -> ShardingPolicy:
    """Replicate parameters everywhere; shard only the batch. The gradient
    all-reduce over ICI is inserted by GSPMD from the batch sharding."""
    return ShardingPolicy(rules=(), fsdp=False)


def FullyShardedDataParallel(min_size: int = 4096) -> ShardingPolicy:
    """ZeRO-3 equivalent: parameters and optimizer slots scattered over the
    ``fsdp`` axis, gathered just-in-time per layer by GSPMD."""
    return ShardingPolicy(rules=(), fsdp=True, fsdp_min_size=min_size)


def TensorParallel(rules: Rules, fsdp: bool = False,
                   fsdp_min_size: int = 4096) -> ShardingPolicy:
    """Megatron-style weight splitting from model-supplied rules, optionally
    combined with FSDP on the remaining dimensions."""
    return ShardingPolicy(rules=rules, fsdp=fsdp, fsdp_min_size=fsdp_min_size)
