from tpusystem.parallel.mesh import (
    AXES, DATA, EXPERT, FSDP, MODEL, SEQ, STAGE,
    MeshSpec, batch_sharding, replicated, single_device_mesh,
)
from tpusystem.parallel.sharding import (
    DataParallel, FullyShardedDataParallel, ShardingPolicy, TensorParallel,
)

__all__ = ['MeshSpec', 'single_device_mesh', 'batch_sharding', 'replicated',
           'ShardingPolicy', 'DataParallel', 'FullyShardedDataParallel',
           'TensorParallel', 'AXES', 'DATA', 'FSDP', 'MODEL', 'SEQ', 'EXPERT', 'STAGE']
