from tpusystem.parallel.mesh import (
    AXES, DATA, EXPERT, FSDP, MODEL, SEQ, STAGE,
    MeshSpec, batch_sharding, force_host_platform, replicated,
    scan_carry_constraint, stacked_batch_sharding,
    single_device_mesh,
)
from tpusystem.parallel.multihost import (
    BLOB_CHUNK, BlobError, CollectiveTimeout, ControlPlaneFailover,
    DistributedProducer, DistributedPublisher, Hub, Loopback, TcpTransport,
    World, WorkerJoined, WorkerLost, agree, connect, world,
)
from tpusystem.parallel.collectives import (
    all_gather, all_reduce_mean, all_reduce_sum, all_to_all, axis_index,
    axis_size, pp_hop, reduce_scatter, replica_checksums, ring_allgather,
    ring_reducescatter, ring_shift, ring_shift_chunked,
)
from tpusystem.parallel.overlap import (
    allgather_matmul, allgather_plan, matmul_reducescatter,
    reducescatter_plan, tp_ffn, tp_swiglu,
)
from tpusystem.parallel.schedule import (
    DecodeTpPlan, FsdpPlan, MoePlan, OverlapSchedule, PpPlan, decode_tp_plan,
    fsdp_plan, moe_plan, pp_plan, resolve_schedule, schedule_applicable,
    scheduled_ffn, scheduled_swiglu,
)
from tpusystem.parallel.pipeline import (PipelineParallel,
                                         compose_stacked_rules,
                                         pipeline_apply, pipeline_train)
from tpusystem.parallel.chaos import (ChaosHub, ChaosTransport, CorruptBatch,
                                      CorruptGrads, DieAtStep, Faults,
                                      FlipParamBit, PreemptionWave,
                                      WorkerKilled)
from tpusystem.parallel.elastic import (ELASTIC_ENV, ElasticCoordinator,
                                        ElasticPolicy, ResizeDecision,
                                        collect_pieces, elastic_consumer,
                                        elastic_resume)
from tpusystem.parallel.recovery import (CRASH_LOOP_EXIT, DIVERGED_EXIT,
                                         FAILURE_EXIT, LOST_WORKER_EXIT,
                                         PREEMPTED_EXIT, RESIZED_EXIT,
                                         RESTART_EXITS, DivergenceError,
                                         Preempted, WorkerLostError,
                                         WorldResizedError, exit_for_restart,
                                         recovery_consumer)
from tpusystem.parallel.supervisor import Supervisor
from tpusystem.parallel.sharding import (
    DataParallel, FullyShardedDataParallel, ShardingPolicy, TensorParallel,
)

__all__ = ['MeshSpec', 'single_device_mesh', 'batch_sharding', 'replicated',
           'scan_carry_constraint', 'stacked_batch_sharding',
           'force_host_platform',
           'ShardingPolicy', 'DataParallel', 'FullyShardedDataParallel',
           'TensorParallel', 'PipelineParallel', 'compose_stacked_rules',
           'pipeline_apply', 'pipeline_train',
           'AXES', 'DATA', 'FSDP', 'MODEL', 'SEQ', 'EXPERT', 'STAGE',
           'World', 'world', 'connect', 'agree', 'Hub', 'Loopback',
           'ControlPlaneFailover', 'CollectiveTimeout',
           'TcpTransport', 'DistributedProducer', 'DistributedPublisher',
           'WorkerLost', 'WorkerJoined',
           'WorkerLostError', 'recovery_consumer', 'LOST_WORKER_EXIT',
           'Preempted', 'PREEMPTED_EXIT', 'RESTART_EXITS', 'exit_for_restart',
           'DivergenceError', 'DIVERGED_EXIT', 'CRASH_LOOP_EXIT',
           'RESIZED_EXIT', 'WorldResizedError',
           'FAILURE_EXIT', 'Supervisor', 'BlobError', 'BLOB_CHUNK',
           'ELASTIC_ENV', 'ElasticCoordinator', 'ElasticPolicy',
           'ResizeDecision', 'collect_pieces', 'elastic_consumer',
           'elastic_resume',
           'Faults', 'ChaosTransport', 'ChaosHub', 'DieAtStep', 'WorkerKilled',
           'PreemptionWave', 'CorruptGrads', 'CorruptBatch', 'FlipParamBit',
           'all_reduce_sum', 'all_reduce_mean', 'all_gather',
           'reduce_scatter', 'all_to_all', 'ring_shift',
           'ring_shift_chunked', 'axis_index', 'axis_size',
           'replica_checksums',
           'allgather_matmul', 'matmul_reducescatter',
           'allgather_plan', 'reducescatter_plan', 'tp_ffn', 'tp_swiglu',
           'ring_allgather', 'ring_reducescatter', 'pp_hop',
           'OverlapSchedule', 'FsdpPlan', 'fsdp_plan', 'resolve_schedule',
           'PpPlan', 'pp_plan', 'MoePlan', 'moe_plan',
           'DecodeTpPlan', 'decode_tp_plan',
           'schedule_applicable', 'scheduled_ffn', 'scheduled_swiglu']
