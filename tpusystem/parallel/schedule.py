"""One overlap scheduler — *when* each collective runs relative to compute.

The repo grew three bespoke latency-hiding mechanisms — the decomposed TP
rings (:mod:`tpusystem.parallel.overlap`), GSPMD's implicit FSDP
gather/scatter (:mod:`tpusystem.parallel.sharding`), and the fused MoE
kernels — each behind its own knob, none composable. This module owns the
scheduling decision as one strategy object, :class:`OverlapSchedule`, and
implements its first two big clients:

* **TP rings** (``tp='overlap'``): the existing
  :func:`~tpusystem.parallel.overlap.allgather_matmul` /
  :func:`~tpusystem.parallel.overlap.matmul_reducescatter` decompositions,
  unchanged semantics, now selected by the schedule instead of a
  per-model ``tp_impl=`` string.
* **FSDP prefetch** (``fsdp='prefetch'``): GSPMD lowers a ZeRO-3 layer to
  a *monolithic* parameter all-gather on the critical path of every block
  and a *monolithic* gradient reduce-scatter on its backward. Here the
  FFN kernels enter the manual region still FSDP-sharded and are gathered
  by a ``custom_vjp`` ring pair mirroring the TP decompositions:
  forward, :func:`~tpusystem.parallel.collectives.ring_allgather` issues
  every kernel's gather at FFN entry — the down-projection's transfer
  hides under the up-projection matmul + activation, and the first gather
  depends only on the parameters, so XLA's latency-hiding scheduler is
  free to float it above the attention block that precedes the FFN;
  backward, the transpose is
  :func:`~tpusystem.parallel.collectives.ring_reducescatter` of the
  weight cotangent — issued where autodiff reverses the gather, *after*
  the activation/input cotangents the next layer's backward needs, so the
  scatter is deferred under the remaining backward matmuls instead of
  serializing against them.

**Composition** is the point: :func:`scheduled_ffn` /
:func:`scheduled_swiglu` run both clients inside ONE fully-manual
``shard_map`` — the FSDP weight gather rides ahead of the TP activation
ring, the TP weight-gradient ring feeds straight into the FSDP gradient
scatter — where the three-knob world could not express "prefetch the
fsdp shards of the kernel the model ring is about to consume".

Fallbacks are planned, never implicit: the pure :func:`fsdp_plan` helper
pins which path every leaf takes — ``'skip'`` (axis size 1, leaf below
``fsdp_min_size``, or no divisible dimension: the leaf was never sharded,
nothing to gather), ``'one-shot'`` (the monolithic ``lax.all_gather``
when the requested ``chunks`` cannot tile the shard), ``'ring'``
otherwise — and its dimension choice delegates to
:func:`tpusystem.parallel.sharding.fsdp_shard_dim`, the same function the
placement policy uses, so the manual collectives always agree with where
the policy actually put the shards. Keep ``fsdp_min_size`` equal between
the schedule and the policy (both default 4096) or jit inserts a
reshard at the manual boundary — correct, but the transfer lands back on
the critical path.

Two further collective families joined the schedule as arms of the same
strategy object (they are implemented where the collectives live, and
planned here):

* **Pipeline p2p** (``pp='overlap'``): the GPipe loop
  (:func:`tpusystem.parallel.pipeline.pipeline_apply`) issues the next
  microbatch's activation ``ppermute`` send *under* the current
  microbatch's stage compute — the skewed double-buffered tick (each
  stage sends last tick's output while computing this tick's microbatch,
  the PR-2/PR-6 ring idiom: transfer launched before the compute that
  hides it), with a ``custom_vjp`` hop
  (:func:`tpusystem.parallel.collectives.pp_hop`) so the backward's
  reversed sends hide under the backward matmuls the same way. The pure
  :func:`pp_plan` pins the one-shot fallback (classic post-compute
  sends) when the microbatch rows won't split into ``chunks`` ppermutes
  or the interleaved schedule owns the ticks.
* **MoE expert all-to-all** (``moe='overlap'``): the quota'd sharded
  sparse dispatch (:class:`tpusystem.ops.moe.MoEMLP`) splits its local
  token rows into microbatch pieces and issues piece ``k+1``'s dispatch
  ``all_to_all`` under the expert matmuls of piece ``k`` (the return
  exchange of ``k`` rides under the matmuls of ``k+1``). The pure
  :func:`moe_plan` pins the one-shot fallback (the single whole-batch
  exchange) for the ragged exchanges (receiver-seated, not yet
  pipelined) and for row counts that won't split.

Model wiring: GPT-2 and Llama accept ``schedule=OverlapSchedule(...)``
(threaded through ``Block``/``BlockSpan`` and the Llama twins, scan and
unrolled paths; ``GPT2Pipelined`` threads ``pp=`` into the GPipe loop
and ``moe=`` reaches :class:`~tpusystem.ops.moe.MoEMLP` through the
block plumbing); :func:`resolve_schedule` folds the legacy
``tp_impl=``/``tp_chunks=`` pair into the same object so existing
configs keep working. Param trees are built from the same
``DenseParams`` holders either way — the knob never changes a
checkpoint.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.parallel.collectives import ring_allgather, ring_reducescatter
from tpusystem.parallel.mesh import DATA, FSDP, MODEL, SEQ, shard_map
from tpusystem.parallel.overlap import (_out_dtype, _partial_matmul,
                                        _row_specs, allgather_matmul,
                                        matmul_reducescatter,
                                        overlap_applicable)
from tpusystem.parallel.sharding import fsdp_shard_dim
from tpusystem.registry import register


@register
class OverlapSchedule:
    """Which collectives are decomposed and scheduled, and how finely.

    Args:
        tp: ``'gspmd'`` leaves the Megatron TP collectives to the
            partitioner (monolithic); ``'overlap'`` routes the FFN through
            the decomposed latency-hiding rings
            (:mod:`tpusystem.parallel.overlap`).
        fsdp: ``'gspmd'`` leaves the ZeRO-3 parameter gather / gradient
            scatter to the partitioner; ``'prefetch'`` gathers the FFN
            kernels with the decomposed ring pair at FFN entry and
            scatters their gradients where autodiff reverses it — off the
            critical path both ways.
        chunks: per-hop ``ppermute`` payload split shared by every ring
            this schedule owns (TP and FSDP) — finer interleave for the
            XLA scheduler at more per-transfer overhead.
        fsdp_min_size: leaves with fewer elements are expected unsharded
            (must match the placement policy's ``fsdp_min_size``; the
            plans consult it so a tiny bias is never gathered).
        pp: ``'gspmd'`` keeps the classic GPipe tick (stage-to-stage
            ``ppermute`` after the compute that produced it, on the
            critical path between ticks); ``'overlap'`` skews the GPipe
            loop so every send is issued *under* the next microbatch's
            stage compute (:func:`~tpusystem.parallel.pipeline
            .pipeline_apply`; backward's reversed sends hide under the
            backward matmuls via the ``pp_hop`` custom_vjp).
        moe: ``'gspmd'`` keeps the one-shot expert exchange (the whole
            local batch's ``all_to_all`` before any expert matmul);
            ``'overlap'`` splits the quota'd sharded dispatch into
            microbatch pieces and issues piece ``k+1``'s dispatch under
            the expert matmuls of piece ``k``
            (:class:`tpusystem.ops.moe.MoEMLP`).

    A registered entity: its knobs capture into the experiment identity
    hash (like :class:`~tpusystem.parallel.mesh.MeshSpec`), so runs under
    different schedules are distinguishable while their checkpoints stay
    interchangeable (the schedule never changes a param tree).
    """

    def __init__(self, tp: str = 'gspmd', fsdp: str = 'gspmd',
                 chunks: int = 1, fsdp_min_size: int = 4096,
                 pp: str = 'gspmd', moe: str = 'gspmd'):
        if tp not in ('gspmd', 'overlap'):
            raise ValueError(f'unknown schedule tp {tp!r}; '
                             "expected 'gspmd' or 'overlap'")
        if fsdp not in ('gspmd', 'prefetch'):
            raise ValueError(f'unknown schedule fsdp {fsdp!r}; '
                             "expected 'gspmd' or 'prefetch'")
        if pp not in ('gspmd', 'overlap'):
            raise ValueError(f'unknown schedule pp {pp!r}; '
                             "expected 'gspmd' or 'overlap'")
        if moe not in ('gspmd', 'overlap'):
            raise ValueError(f'unknown schedule moe {moe!r}; '
                             "expected 'gspmd' or 'overlap'")
        if chunks < 1:
            raise ValueError(f'chunks must be >= 1, got {chunks}')
        self.tp = tp
        self.fsdp = fsdp
        self.chunks = chunks
        self.fsdp_min_size = fsdp_min_size
        self.pp = pp
        self.moe = moe

    def _key(self):
        return (self.tp, self.fsdp, self.chunks, self.fsdp_min_size,
                self.pp, self.moe)

    def __eq__(self, other):
        return (isinstance(other, OverlapSchedule)
                and self._key() == other._key())

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return (f'OverlapSchedule(tp={self.tp!r}, fsdp={self.fsdp!r}, '
                f'chunks={self.chunks}, fsdp_min_size={self.fsdp_min_size}, '
                f'pp={self.pp!r}, moe={self.moe!r})')

    @classmethod
    def for_policy(cls, policy, *, tp: str = 'gspmd',
                   fsdp: str = 'prefetch', chunks: int = 1,
                   pp: str = 'gspmd', moe: str = 'gspmd'):
        """Schedule paired to a placement policy: ``fsdp_min_size`` is
        copied from the :class:`~tpusystem.parallel.sharding.ShardingPolicy`
        so the manual in_specs replicate its placement exactly. A
        mismatched pair is still correct, but jit inserts a reshard at
        the manual boundary — the transfer this schedule exists to hide."""
        return cls(tp=tp, fsdp=fsdp, chunks=chunks,
                   fsdp_min_size=policy.fsdp_min_size, pp=pp, moe=moe)


def resolve_schedule(schedule, tp_impl: str = 'gspmd',
                     tp_chunks: int = 1) -> OverlapSchedule:
    """The models' knob seam: one :class:`OverlapSchedule` from either the
    ``schedule=`` object or the legacy ``tp_impl=``/``tp_chunks=`` pair.

    ``schedule=None`` folds the legacy pair into an equivalent schedule
    (``fsdp='gspmd'`` — exactly the old behavior); passing both a
    schedule and non-default legacy knobs raises, so a config can never
    silently say two different things.
    """
    if tp_impl not in ('gspmd', 'overlap'):
        raise ValueError(f'unknown tp_impl {tp_impl!r}; '
                         "expected 'gspmd' or 'overlap'")
    if schedule is None:
        return OverlapSchedule(tp=tp_impl, chunks=tp_chunks)
    if not isinstance(schedule, OverlapSchedule):
        raise TypeError('schedule= expects an OverlapSchedule, got '
                        f'{type(schedule).__name__}')
    if tp_impl != 'gspmd' or tp_chunks != 1:
        raise ValueError('pass schedule= or the legacy tp_impl=/tp_chunks= '
                         'knobs, not both')
    return schedule


class FsdpPlan(NamedTuple):
    """Which path one leaf's FSDP gather takes.

    ``path`` is ``'ring'`` (decomposed latency-hiding gather),
    ``'one-shot'`` (monolithic ``lax.all_gather`` — the requested chunks
    cannot tile the shard), or ``'skip'`` (the leaf was never
    fsdp-sharded: trivial axis, tiny leaf, or no divisible dimension —
    it arrives whole, no collective). ``dim`` is the gathered dimension
    (``-1`` when skipped), ``chunks`` the per-hop ppermute split the ring
    will use, ``reason`` documents a fallback.
    """

    path: str
    dim: int
    chunks: int
    reason: str


def fsdp_plan(shape: tuple[int, ...], ring: int, *, taken=(),
              chunks: int = 1, min_size: int = 4096,
              row_split: int = 1) -> FsdpPlan:
    """Plan one leaf's FSDP prefetch — pure, so tests can pin the path.

    Mirrors the placement side exactly: a leaf the policy's
    ``_with_fsdp`` left unsharded (below ``min_size``, or no unclaimed
    dimension divides ``ring``) plans ``'skip'``, and the gathered
    dimension is :func:`~tpusystem.parallel.sharding.fsdp_shard_dim`'s
    choice (``taken`` = indices already claimed by TP rule axes).
    ``row_split`` is how many ways dimension 0 is already sharded
    *inside* the manual region by those rule axes (the TP ring over a
    down-projection's rows): the chunk-tiling check must see the LOCAL
    row count the ppermute will actually split, or a plan could say
    ``'ring'`` for a shard the ring cannot chunk and crash at trace
    time instead of falling back.
    """
    if ring == 1:
        return FsdpPlan('skip', -1, 1, 'axis_size == 1')
    if math.prod(shape) < min_size:
        return FsdpPlan('skip', -1, 1,
                        f'leaf below fsdp_min_size ({min_size})')
    dim = fsdp_shard_dim(tuple(shape), ring, tuple(taken))
    if dim is None:
        return FsdpPlan('skip', -1, 1,
                        'no unsharded dimension divisible by the fsdp axis')
    if shape[0] % row_split:
        return FsdpPlan('one-shot', dim, 1,
                        f'rows ({shape[0]}) not divisible by the row '
                        f'split ({row_split})')
    shard_rows = (shape[0] // ring if dim == 0
                  else shape[0] // row_split)
    if chunks < 1 or shard_rows % chunks:
        return FsdpPlan('one-shot', dim, 1,
                        f'local shard rows ({shard_rows}) not divisible '
                        f'by chunks ({chunks})')
    return FsdpPlan('ring', dim, chunks, '')


_SKIP = FsdpPlan('skip', -1, 1, 'fsdp prefetch inactive')


class PpPlan(NamedTuple):
    """Which tick schedule the GPipe pipeline takes.

    ``path`` is ``'overlap'`` (the skewed double-buffered schedule: every
    stage-to-stage send issued under the next microbatch's compute),
    ``'one-shot'`` (the classic tick — send after the compute that
    produced it; the requested ``chunks`` cannot tile the microbatch
    rows, or the interleaved schedule owns the ticks), or ``'skip'``
    (``stage`` axis of size 1: there are no sends to hide). ``chunks``
    is the per-hop ppermute payload split the overlap hop will use,
    ``reason`` documents a fallback.
    """

    path: str
    chunks: int
    reason: str


def pp_plan(rows: int, stages: int, chunks: int = 1,
            interleave: int = 1) -> PpPlan:
    """Plan the pipeline's stage-to-stage sends — pure, so tests can pin
    the path.

    ``rows`` is the per-device microbatch's leading (batch) dimension —
    what :func:`~tpusystem.parallel.collectives.pp_hop` splits into
    ``chunks`` independent ``ppermute``\\ s. The skewed schedule pays one
    extra fill tick per stage (``M + 2(S-1)`` ticks vs ``M + S - 1``) to
    take every transfer off the tick-to-tick critical path — second-order
    at realistic ``M >= 4S``, which is why the fallback is the classic
    schedule, not a crash. The interleaved (``v > 1``) GPipe forward
    keeps its own tick formulas and stays classic.
    """
    if stages == 1:
        return PpPlan('skip', 1, 'axis_size == 1')
    if interleave > 1:
        return PpPlan('one-shot', 1,
                      'interleaved schedule keeps the classic ticks')
    if chunks < 1 or rows % chunks:
        return PpPlan('one-shot', 1,
                      f'microbatch rows ({rows}) not divisible by chunks '
                      f'({chunks})')
    return PpPlan('overlap', chunks, '')


class MoePlan(NamedTuple):
    """Which dispatch schedule the sharded sparse MoE takes.

    ``path`` is ``'overlap'`` (local rows split into ``pieces``
    microbatch pieces, piece ``k+1``'s dispatch ``all_to_all`` issued
    under the expert matmuls of piece ``k``), ``'one-shot'`` (the single
    whole-batch exchange — the ragged exchanges, or rows that won't
    split), or ``'skip'`` (expert axis of size 1: no exchange exists).
    ``reason`` documents a fallback.
    """

    path: str
    pieces: int
    reason: str


def moe_plan(local_rows: int, expert_size: int, exchange: str = 'quota',
             pieces: int = 2) -> MoePlan:
    """Plan the expert-parallel dispatch pipeline — pure, so tests can
    pin the path.

    Only the quota'd regular-``all_to_all`` formulation pipelines today:
    the ragged exchanges seat capacity at the *receiver* from gathered
    count matrices, so their geometry is a cross-piece dependency the
    pipeline would have to exchange twice. Rows must split evenly into
    ``pieces`` (each piece routes and seats independently — per-piece
    quotas are the quota path's per-sender drop discipline at finer
    grain; with ample capacity all formulations agree exactly).
    """
    if expert_size == 1:
        return MoePlan('skip', 1, 'axis_size == 1')
    if exchange != 'quota':
        return MoePlan('one-shot', 1,
                       f'{exchange!r} exchange seats at the receiver; '
                       'pipelined dispatch is quota-only')
    if pieces < 2 or local_rows % pieces or local_rows < 2 * pieces:
        return MoePlan('one-shot', 1,
                       f'local rows ({local_rows}) will not split into '
                       f'{pieces} pieces')
    return MoePlan('overlap', pieces, '')


class DecodeTpPlan(NamedTuple):
    """Which sharding path the serving engine's compiled steps take.

    ``path`` is ``'single'`` (no mesh, or a trivial ``model`` axis — the
    engine runs exactly as before on one device), ``'gspmd'`` (the decode
    and prefill programs run with TP-sharded matmuls: params placed by
    the module's ``partition_rules()``, the paged KV pool sharded over
    heads, block tables replicated so the host keeps ONE authority), or
    ``'unsupported'`` (the mesh carries a non-trivial axis serving cannot
    shard over — data/fsdp/seq/expert/stage parallelism belongs to
    training; serving batches are row-churned, not data-sharded).
    ``model`` is the TP degree, ``reason`` documents a fallback or gate.
    """

    path: str
    model: int
    reason: str


def decode_tp_plan(mesh) -> DecodeTpPlan:
    """Plan the engine's TP sharding — pure, so tests can pin the path.

    ``mesh`` is a built :class:`jax.sharding.Mesh` (or ``None``). Only
    the ``model`` axis may exceed 1: the engine's row dimension churns
    every step (admit/evict rewrite individual rows in place), so
    sharding rows across devices would turn every seat into a
    cross-device scatter. The fused Pallas chain has no ring arms yet —
    :func:`~tpusystem.train.decode_fused.fused_paged_reason` gates it
    separately and ``decode_impl='auto'`` falls back to the sharded
    flax step.
    """
    if mesh is None:
        return DecodeTpPlan('single', 1, 'no mesh')
    sizes = dict(getattr(mesh, 'shape', {}))
    model = sizes.get(MODEL, 1)
    offending = {axis: size for axis, size in sizes.items()
                 if axis != MODEL and size > 1}
    if offending:
        return DecodeTpPlan(
            'unsupported', model,
            f'serving shards over the {MODEL!r} axis only; mesh carries '
            f'non-trivial {sorted(offending)} — rows churn in place every '
            'step, so data-style sharding would scatter every seat')
    if model == 1:
        return DecodeTpPlan('single', 1, 'model axis of size 1')
    return DecodeTpPlan('gspmd', model, '')


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ring_gather(axis, dim, chunks, shard):
    return ring_allgather(shard, axis, dimension=dim, chunks=chunks)


def _ring_gather_fwd(axis, dim, chunks, shard):
    return _ring_gather(axis, dim, chunks, shard), None


def _ring_gather_bwd(axis, dim, chunks, _, grad):
    # the gather is a copy, so its transpose is the pure reduce-scatter
    # ring: each rank's block of the (per-device partial) cotangent summed
    # around the ring in f32, landing home sharded like the leaf. Issued
    # by autodiff AFTER the cotangents the next layer's backward depends
    # on, so it hides under the remaining backward matmuls. Reduction over
    # non-fsdp axes (data/seq replicas) is shard_map's transpose job —
    # the leaf's in_spec doesn't mention them.
    return (ring_reducescatter(grad, axis, dimension=dim, chunks=chunks),)


_ring_gather.defvjp(_ring_gather_fwd, _ring_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _one_shot_gather(axis, dim, shard):
    return lax.all_gather(shard, axis, axis=dim, tiled=True)


def _one_shot_gather_fwd(axis, dim, shard):
    return _one_shot_gather(axis, dim, shard), None


def _one_shot_gather_bwd(axis, dim, _, grad):
    # lax.all_gather's native transpose would psum_scatter the raw
    # (possibly bf16) cotangent; scatter the f32 cotangent and cast once
    # instead, so a leaf whose chunks stop tiling keeps the SAME
    # f32-reduction contract as the ring path it fell back from
    total = lax.psum_scatter(grad.astype(jnp.float32), axis,
                             scatter_dimension=dim, tiled=True)
    return (total.astype(grad.dtype),)


_one_shot_gather.defvjp(_one_shot_gather_fwd, _one_shot_gather_bwd)


def prefetched(shard, plan: FsdpPlan, axis: str = FSDP):
    """Gather one FSDP-sharded leaf inside ``shard_map``, per its plan.

    ``'ring'`` is the decomposed custom_vjp pair (gather forward,
    reduce-scatter backward); ``'one-shot'`` the monolithic
    ``lax.all_gather`` (its transpose scatters the f32 cotangent — the
    fallback keeps the ring's reduction contract); ``'skip'`` returns
    the leaf untouched.
    """
    if plan.path == 'skip':
        return shard
    if plan.path == 'one-shot':
        return _one_shot_gather(axis, plan.dim, shard)
    return _ring_gather(axis, plan.dim, plan.chunks, shard)


def _weight_spec_plan(base_entries, shape, prefetch_on: bool,
                      schedule: OverlapSchedule, fsdp_size: int,
                      row_split: int = 1):
    """(in_spec, plan) for one FFN kernel: the TP base spec with the fsdp
    axis added on exactly the dimension the placement policy picked.
    ``row_split`` = the TP axis size when ``base_entries[0]`` carries it
    (a down-projection's rows are TP-sharded inside the manual region,
    so the plan's chunk check must see the local row count)."""
    entries = list(base_entries)
    if not prefetch_on:
        return P(*entries), _SKIP
    taken = [index for index, axis in enumerate(entries) if axis is not None]
    plan = fsdp_plan(shape, fsdp_size, taken=taken, chunks=schedule.chunks,
                     min_size=schedule.fsdp_min_size, row_split=row_split)
    if plan.path != 'skip':
        entries[plan.dim] = FSDP
    return P(*entries), plan


def _prefetch_on(schedule: OverlapSchedule, sizes, batch: int) -> bool:
    """The ONE prefetch-safety gate — shared by :func:`schedule_applicable`
    and the ``scheduled_*`` entry points so the condition that prevents
    the fsdp-replicated-batch gradient double-count can never diverge
    from the condition that activates the ring scatter. The manual
    gradient scatter assumes each device contributed a distinct batch
    slice; a replicated batch (e.g. ``module.init``'s batch-1 trace)
    takes the GSPMD path instead."""
    fsdp_size = sizes.get(FSDP, 1)
    return (schedule.fsdp == 'prefetch' and fsdp_size > 1
            and batch % (sizes.get(DATA, 1) * fsdp_size) == 0)


def _prefetch_applicable(schedule, mesh, hidden_shape, grown_features: int,
                         axis: str) -> bool:
    sizes = dict(mesh.shape)
    batch, seq, _ = hidden_shape
    if not _prefetch_on(schedule, sizes, batch):
        return False
    ring = sizes.get(axis, 1)
    if ring > 1:
        return overlap_applicable(mesh, hidden_shape, grown_features, axis)
    return seq % sizes.get(SEQ, 1) == 0


def schedule_applicable(schedule: OverlapSchedule, mesh, hidden_shape,
                        grown_features: int, axis: str = MODEL) -> bool:
    """Should the FFN take the manual scheduled path for this shape?

    True when the schedule decomposes at least one collective family the
    shape supports: TP rings per
    :func:`~tpusystem.parallel.overlap.overlap_applicable` (unchanged
    from the ``tp_impl`` era), or FSDP prefetch when the fsdp axis is
    non-trivial AND the batch genuinely shards over ``(data, fsdp)``.
    Shapes that qualify for neither fall back to the GSPMD Dense path
    per call site — same params, so the fallback never changes a tree.
    """
    if mesh is None:
        return False
    if (schedule.tp == 'overlap'
            and overlap_applicable(mesh, hidden_shape, grown_features, axis)):
        return True
    return _prefetch_applicable(schedule, mesh, hidden_shape,
                                grown_features, axis)


def _tp_up(rows, w, axis, schedule, sizes):
    """``all_gather(rows) @ w`` under the schedule: the decomposed ring
    when ``tp='overlap'``, the one-shot manual collective otherwise
    (still f32-accumulated — the module's reduction contract)."""
    if schedule.tp == 'overlap' and axis in sizes:
        return allgather_matmul(rows, w, axis, chunks=schedule.chunks)
    if sizes.get(axis, 1) > 1:
        rows = lax.all_gather(rows, axis, axis=0, tiled=True)
    return _partial_matmul(rows, w).astype(_out_dtype(rows, w))


def _tp_down(grown, w, axis, schedule, sizes):
    """``psum_scatter(grown @ w)`` under the schedule — dual of
    :func:`_tp_up`; the one-shot path scatters the f32 product before
    casting (the overlap module's fallback discipline)."""
    if schedule.tp == 'overlap' and axis in sizes:
        return matmul_reducescatter(grown, w, axis, chunks=schedule.chunks)
    product = _partial_matmul(grown, w)
    if sizes.get(axis, 1) > 1:
        product = lax.psum_scatter(product, axis, scatter_dimension=0,
                                   tiled=True)
    return product.astype(_out_dtype(grown, w))


def scheduled_ffn(x, kernel_up, bias_up, kernel_down, bias_down, mesh, *,
                  schedule: OverlapSchedule, activation=jax.nn.gelu,
                  axis: str = MODEL):
    """Sequence-sharded FFN (bias + activation, GPT-2) under one schedule.

    Generalizes :func:`~tpusystem.parallel.overlap.tp_ffn`: the same
    fully-manual ``shard_map`` (batch over ``(data, fsdp)``, sequence
    rows over ``(seq, model)``), with the kernels entering still
    FSDP-sharded when ``schedule.fsdp='prefetch'`` — both kernel gathers
    issue at body entry (the down kernel's transfer hides under the up
    matmul + activation), then the TP collectives run decomposed or
    one-shot per ``schedule.tp``. Biases ride their TP specs untouched
    (they are a rounding error of the FSDP bytes and usually below
    ``fsdp_min_size`` anyway). Weight in_specs replicate the placement
    policy's choice bit-for-bit (same :func:`fsdp_shard_dim`, same
    ``min_size``), so jit inserts no resharding.
    """
    sizes = dict(mesh.shape)
    tp_axis = axis if axis in sizes else None
    fsdp_size = sizes.get(FSDP, 1)
    prefetch_on = _prefetch_on(schedule, sizes, x.shape[0])
    spec_up, plan_up = _weight_spec_plan(
        (None, tp_axis), kernel_up.shape, prefetch_on, schedule, fsdp_size)
    spec_down, plan_down = _weight_spec_plan(
        (tp_axis, None), kernel_down.shape, prefetch_on, schedule, fsdp_size,
        row_split=sizes.get(axis, 1))

    @functools.partial(
        shard_map, mesh=mesh, check_vma=False,
        in_specs=(_row_specs(mesh, x.shape[0], axis), spec_up, P(tp_axis),
                  spec_down, P(None)),
        out_specs=_row_specs(mesh, x.shape[0], axis))
    def mapped(x, w_up, b_up, w_down, b_down):
        # prefetch order: both kernel gathers issue before the first
        # matmul, so the down kernel's hop rides under the up matmul
        w_up = prefetched(w_up, plan_up)
        w_down = prefetched(w_down, plan_down)
        batch, seq, dim = x.shape
        rows = x.reshape(batch * seq, dim)
        grown = _tp_up(rows, w_up, axis, schedule, sizes)
        grown = activation(grown + b_up)
        out = _tp_down(grown, w_down, axis, schedule, sizes)
        # bias lands after the scatter so the sum counts it exactly once
        out = out + b_down
        return out.reshape(batch, seq, dim)

    return mapped(x, kernel_up, bias_up, kernel_down, bias_down)


def scheduled_swiglu(x, kernel_gate, kernel_up, kernel_down, mesh, *,
                     schedule: OverlapSchedule, axis: str = MODEL):
    """Sequence-sharded SwiGLU FFN (Llama) under one schedule.

    Generalizes :func:`~tpusystem.parallel.overlap.tp_swiglu`: gate and
    up kernels gather over fsdp first (the down kernel's gather hides
    under the fused ring), then concatenate into the single
    ``[dim, 2 * grown]`` right operand so the sequence rows ride the TP
    ring ONCE for both matmuls. No biases (Llama convention).
    """
    sizes = dict(mesh.shape)
    tp_axis = axis if axis in sizes else None
    fsdp_size = sizes.get(FSDP, 1)
    prefetch_on = _prefetch_on(schedule, sizes, x.shape[0])
    spec_gate, plan_gate = _weight_spec_plan(
        (None, tp_axis), kernel_gate.shape, prefetch_on, schedule, fsdp_size)
    spec_up, plan_up = _weight_spec_plan(
        (None, tp_axis), kernel_up.shape, prefetch_on, schedule, fsdp_size)
    spec_down, plan_down = _weight_spec_plan(
        (tp_axis, None), kernel_down.shape, prefetch_on, schedule, fsdp_size,
        row_split=sizes.get(axis, 1))

    @functools.partial(
        shard_map, mesh=mesh, check_vma=False,
        in_specs=(_row_specs(mesh, x.shape[0], axis), spec_gate, spec_up,
                  spec_down),
        out_specs=_row_specs(mesh, x.shape[0], axis))
    def mapped(x, w_gate, w_up, w_down):
        w_gate = prefetched(w_gate, plan_gate)
        w_up = prefetched(w_up, plan_up)
        w_down = prefetched(w_down, plan_down)
        batch, seq, dim = x.shape
        rows = x.reshape(batch * seq, dim)
        fused = jnp.concatenate([w_gate, w_up], axis=1)
        grown = _tp_up(rows, fused, axis, schedule, sizes)
        gate, up = jnp.split(grown, 2, axis=1)
        # jax.nn.silu IS flax's nn.silu (a re-export) — identical numerics
        # to the GSPMD Dense path
        hidden = jax.nn.silu(gate) * up
        out = _tp_down(hidden, w_down, axis, schedule, sizes)
        return out.reshape(batch, seq, dim)

    return mapped(x, kernel_gate, kernel_up, kernel_down)
