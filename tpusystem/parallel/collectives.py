"""Named collective wrappers for ``shard_map`` kernels.

The data plane of the distributed design (SURVEY.md §5): XLA collectives
over ICI within a slice and DCN across slices. GSPMD inserts most of these
implicitly from sharding annotations; explicit kernels (ring attention,
pipeline schedules, MoE dispatch) call these wrappers inside
``jax.shard_map``. They are thin by design — the value is one documented
vocabulary with ring-neighbor conventions fixed in a single place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpusystem.parallel.mesh import axis_size as _axis_size


def all_reduce_sum(value, axis: str):
    """Sum over every shard on ``axis`` (gradient reduction)."""
    return lax.psum(value, axis)


def all_reduce_mean(value, axis: str):
    return lax.pmean(value, axis)


def all_gather(value, axis: str, *, dimension: int = 0, tiled: bool = True):
    """Concatenate shards along ``dimension`` (FSDP weight gather)."""
    return lax.all_gather(value, axis, axis=dimension, tiled=tiled)


def reduce_scatter(value, axis: str, *, dimension: int = 0):
    """Sum then scatter along ``dimension`` (ZeRO gradient scatter)."""
    return lax.psum_scatter(value, axis, scatter_dimension=dimension, tiled=True)


def all_to_all(value, axis: str, *, split_dimension: int, concat_dimension: int):
    """Shard-transpose (MoE token dispatch, Ulysses head/seq swap)."""
    return lax.all_to_all(value, axis, split_axis=split_dimension,
                          concat_axis=concat_dimension, tiled=True)


def ring_shift(value, axis: str, *, reverse: bool = False):
    """Send this shard to the next (or previous) rank on the ring —
    the ``ppermute`` at the heart of ring attention and 1F1B pipelines.
    Neighbor convention: rank ``i`` sends to ``(i+1) % n`` when forward.
    """
    size = _axis_size(axis)
    if reverse:
        permutation = [(source, (source - 1) % size) for source in range(size)]
    else:
        permutation = [(source, (source + 1) % size) for source in range(size)]
    return lax.ppermute(value, axis, permutation)


def ring_shift_chunked(value, axis: str, *, chunks: int = 1,
                       reverse: bool = False):
    """:func:`ring_shift` with the payload split into ``chunks``
    independent ``ppermute``\\ s along dimension 0.

    Semantically identical to one monolithic shift; the split gives XLA's
    latency-hiding scheduler ``chunks`` independent transfers it can
    interleave with compute at finer granularity — the knob the
    decomposed TP matmuls (:mod:`tpusystem.parallel.overlap`) sweep.
    Shares :func:`ring_shift`'s neighbor convention exactly — rank ``i``
    sends to ``(i + 1) % n`` when forward — so after ``s`` forward shifts
    a device holds the shard of rank ``(i - s) % n``; the all-gather and
    reduce-scatter decompositions both index their row-blocks from that
    convention, which is what keeps the two duals' transposes reusable as
    each other's backward. Requires ``value.shape[0] % chunks == 0``
    (callers plan around this; see ``overlap.allgather_plan``).
    """
    if chunks <= 1:
        return ring_shift(value, axis, reverse=reverse)
    if value.shape[0] % chunks:
        raise ValueError(f'cannot split {value.shape[0]} rows into '
                         f'{chunks} ppermute chunks')
    pieces = jnp.split(value, chunks, axis=0)
    shifted = [ring_shift(piece, axis, reverse=reverse) for piece in pieces]
    return jnp.concatenate(shifted, axis=0)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return _axis_size(axis)
