"""Named collective wrappers for ``shard_map`` kernels.

The data plane of the distributed design (SURVEY.md §5): XLA collectives
over ICI within a slice and DCN across slices. GSPMD inserts most of these
implicitly from sharding annotations; explicit kernels (ring attention,
pipeline schedules, MoE dispatch) call these wrappers inside
``jax.shard_map``. They are thin by design — the value is one documented
vocabulary with ring-neighbor conventions fixed in a single place.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from tpusystem.parallel.mesh import axis_size as _axis_size
from tpusystem.parallel.mesh import shard_map as _shard_map


def all_reduce_sum(value, axis: str):
    """Sum over every shard on ``axis`` (gradient reduction)."""
    return lax.psum(value, axis)


def all_reduce_mean(value, axis: str):
    return lax.pmean(value, axis)


def all_gather(value, axis: str, *, dimension: int = 0, tiled: bool = True):
    """Concatenate shards along ``dimension`` (FSDP weight gather)."""
    return lax.all_gather(value, axis, axis=dimension, tiled=tiled)


def reduce_scatter(value, axis: str, *, dimension: int = 0):
    """Sum then scatter along ``dimension`` (ZeRO gradient scatter)."""
    return lax.psum_scatter(value, axis, scatter_dimension=dimension, tiled=True)


def all_to_all(value, axis: str, *, split_dimension: int, concat_dimension: int):
    """Shard-transpose (MoE token dispatch, Ulysses head/seq swap)."""
    return lax.all_to_all(value, axis, split_axis=split_dimension,
                          concat_axis=concat_dimension, tiled=True)


def ring_shift(value, axis: str, *, reverse: bool = False):
    """Send this shard to the next (or previous) rank on the ring —
    the ``ppermute`` at the heart of ring attention and 1F1B pipelines.
    Neighbor convention: rank ``i`` sends to ``(i+1) % n`` when forward.
    """
    size = _axis_size(axis)
    if reverse:
        permutation = [(source, (source - 1) % size) for source in range(size)]
    else:
        permutation = [(source, (source + 1) % size) for source in range(size)]
    return lax.ppermute(value, axis, permutation)


def ring_shift_chunked(value, axis: str, *, chunks: int = 1,
                       reverse: bool = False):
    """:func:`ring_shift` with the payload split into ``chunks``
    independent ``ppermute``\\ s along dimension 0.

    Semantically identical to one monolithic shift; the split gives XLA's
    latency-hiding scheduler ``chunks`` independent transfers it can
    interleave with compute at finer granularity — the knob the
    decomposed TP matmuls (:mod:`tpusystem.parallel.overlap`) sweep.
    Shares :func:`ring_shift`'s neighbor convention exactly — rank ``i``
    sends to ``(i + 1) % n`` when forward — so after ``s`` forward shifts
    a device holds the shard of rank ``(i - s) % n``; the all-gather and
    reduce-scatter decompositions both index their row-blocks from that
    convention, which is what keeps the two duals' transposes reusable as
    each other's backward. Requires ``value.shape[0] % chunks == 0``
    (callers plan around this; see ``overlap.allgather_plan``).
    """
    if chunks <= 1:
        return ring_shift(value, axis, reverse=reverse)
    if value.shape[0] % chunks:
        raise ValueError(f'cannot split {value.shape[0]} rows into '
                         f'{chunks} ppermute chunks')
    pieces = jnp.split(value, chunks, axis=0)
    shifted = [ring_shift(piece, axis, reverse=reverse) for piece in pieces]
    return jnp.concatenate(shifted, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def pp_hop(axis, chunks, value):
    """One pipeline stage-to-stage hop: the forward chunked ring shift
    with an explicit transpose, so autodiff of a pipelined schedule
    issues *reversed* chunked sends.

    Forward is exactly :func:`ring_shift_chunked` (rank ``i`` sends to
    ``(i + 1) % n``); the custom backward is the reverse chunked shift of
    the cotangent (rank ``i`` sends to ``(i - 1) % n``) — a pure copy in
    both directions, bitwise-exact in any dtype. The value of the
    custom_vjp is *placement*: under the skewed GPipe schedule
    (``pp='overlap'`` in :class:`~tpusystem.parallel.schedule
    .OverlapSchedule`) the forward hop is issued at tick top, before the
    stage compute that hides it, and autodiff transposes that structure —
    the reversed send of backward tick ``t`` is independent of tick
    ``t``'s block vjp matmuls, so it hides under them instead of
    serializing the reversed ring.
    """
    return ring_shift_chunked(value, axis, chunks=chunks)


def _pp_hop_fwd(axis, chunks, value):
    return pp_hop(axis, chunks, value), None


def _pp_hop_bwd(axis, chunks, _, grad):
    return (ring_shift_chunked(grad, axis, chunks=chunks, reverse=True),)


pp_hop.defvjp(_pp_hop_fwd, _pp_hop_bwd)


def ring_allgather(value, axis: str, *, dimension: int = 0,
                   chunks: int = 1):
    """:func:`all_gather` decomposed into ``axis_size`` ring steps.

    Each device's shard rotates forward one hop per step
    (:func:`ring_shift_chunked`, the shared neighbor convention: after
    ``s`` forward shifts rank ``i`` holds the shard of rank
    ``(i - s) % n``) and is copied into its row-block of the full
    ``[..., n * shard, ...]`` result along ``dimension``. The next hop's
    ``ppermute`` is issued *before* the current block's copy — the
    latency-hiding order every ring in this repo uses — so XLA's
    scheduler can hide the transfers under whatever compute consumes the
    early blocks. The FSDP prefetch path
    (:mod:`tpusystem.parallel.schedule`) builds its parameter gather on
    this; semantically identical to one monolithic ``lax.all_gather``.
    Requires ``value.shape[0] % chunks == 0`` (callers plan around this;
    see ``schedule.fsdp_plan``).
    """
    ring = _axis_size(axis)
    rank = lax.axis_index(axis)
    rows = value.shape[dimension]
    shape = list(value.shape)
    shape[dimension] = ring * rows
    out = jnp.zeros(shape, value.dtype)
    held = value
    incoming = ring_shift_chunked(held, axis, chunks=chunks)
    for step in range(ring):
        if step:
            held = incoming
            if step + 1 < ring:
                incoming = ring_shift_chunked(held, axis, chunks=chunks)
        source = (rank - step) % ring
        start = [0] * len(shape)
        start[dimension] = source * rows
        out = lax.dynamic_update_slice(out, held, tuple(start))
    return out


def ring_reducescatter(value, axis: str, *, dimension: int = 0,
                       chunks: int = 1):
    """:func:`reduce_scatter` decomposed into ``axis_size`` ring steps.

    The dual of :func:`ring_allgather`: at step ``t`` every device takes
    block ``(rank - 1 - t) % n`` of its full-size ``value`` along
    ``dimension`` and folds it into the running **float32** sum arriving
    from its predecessor; the sum's forward shift is issued *before* the
    next block's add, so after ``n`` steps block ``rank`` lands home
    carrying all ``n`` contributions with the transfers hidden under the
    compute that produced the later blocks. Semantically identical to
    ``lax.psum_scatter(..., tiled=True)`` up to f32 summation order;
    result is cast back to ``value.dtype``. The FSDP prefetch path uses
    this as the gradient scatter (the transpose of the parameter gather).
    """
    ring = _axis_size(axis)
    rank = lax.axis_index(axis)
    rows = value.shape[dimension] // ring
    sizes = list(value.shape)
    sizes[dimension] = rows

    def block(step):
        start = [0] * len(sizes)
        start[dimension] = ((rank - 1 - step) % ring) * rows
        return lax.dynamic_slice(value, tuple(start), tuple(sizes))

    total = block(0).astype(jnp.float32)
    for step in range(1, ring):
        inflight = ring_shift_chunked(total, axis, chunks=chunks)
        total = inflight + block(step)
    return total.astype(value.dtype)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return _axis_size(axis)


# ---------------------------------------------------------------------------
# cross-replica parity (SDC detection)


def _bit_checksum(leaf):
    """Order-independent uint32 checksum of a leaf's raw bits.

    ``bitcast -> widen -> wrapping sum``: integer addition is commutative,
    so the checksum is layout- and reduction-order-independent — two
    replicas holding bit-identical data always agree, and any single bit
    flip changes the sum (multi-flip collisions are the usual mod-2^32
    checksum caveat). Float summation would not give that guarantee.
    """
    nbits = np.dtype(leaf.dtype).itemsize * 8
    if nbits > 32:   # 64-bit leaves split into two uint32 words
        bits = lax.bitcast_convert_type(leaf, jnp.uint32)
    else:
        bits = lax.bitcast_convert_type(
            leaf, jnp.dtype(f'uint{nbits}')).astype(jnp.uint32)
    return jnp.sum(bits, dtype=jnp.uint32)


@functools.lru_cache(maxsize=32)
def _checksum_program(mesh, specs, axis: str):
    """Compiled per-(mesh, layout) checksum gather — jit caches per shape."""
    others = tuple(name for name in mesh.axis_names if name != axis)

    def local(*shards):
        vec = jnp.stack([_bit_checksum(shard) for shard in shards])
        if others:
            # fold shard checksums into the replica's full-leaf checksum
            vec = lax.psum(vec, others)
        return lax.all_gather(vec, axis)

    mapped = _shard_map(local, mesh=mesh, in_specs=specs,
                        out_specs=PartitionSpec(), check_vma=False)
    return jax.jit(mapped)


def replica_checksums(tree, mesh, *, axis: str = 'data'):
    """Per-replica bit checksums of every leaf in ``tree``.

    The device half of the sentinel's SDC parity check
    (:meth:`tpusystem.train.Sentinel.check_parity`): each device checksums
    its local shard of every leaf, the checksums are summed over the
    non-``axis`` mesh axes (one scalar per leaf per replica) and
    all-gathered over ``axis`` — exchanged bytes are
    ``4 * leaves * axis_size``, independent of the model size, so the check
    is cheap enough for checkpoint cadence.

    Returns ``(matrix, paths)``: a ``[axis_size, leaves]`` uint32 numpy
    matrix (row ``r`` = replica ``r``'s per-leaf checksums; for params
    replicated over ``axis`` all rows must be equal) and the matching leaf
    path strings. The host read is one scalar matrix — the same cadence
    discipline as the health vector.
    """
    leaves = jax.tree.leaves(tree)
    paths = [jax.tree_util.keystr(path) for path, _ in
             jax.tree_util.tree_flatten_with_path(tree)[0]]
    specs = tuple(
        leaf.sharding.spec
        if isinstance(getattr(leaf, 'sharding', None), NamedSharding)
        else PartitionSpec()
        for leaf in leaves)
    program = _checksum_program(mesh, specs, axis)
    return np.asarray(jax.device_get(program(*leaves))), paths
