"""Pipeline parallelism — microbatch schedule over the ``stage`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.4: "PP | absent");
this module supplies it TPU-natively: layers are stacked into a leading
``layers`` dimension, that dimension is sharded over the ``stage`` axis (so
each device owns ``layers / stages`` contiguous layers), and microbatch
activations travel stage-to-stage with ``ppermute`` over the ICI ring inside
``shard_map``.

Schedule: GPipe. All microbatch forwards stream through the pipe; XLA's
autodiff of the tick ``lax.scan`` then replays the schedule in reverse, so
the backward pass drains the pipe stage-by-stage in the transposed order —
the same bubble fraction as hand-written 1F1B, ``(S-1)/(M+S-1)``, with
memory bounded by per-microbatch rematerialisation (``remat=True`` wraps
each stage body in ``jax.checkpoint``, so live activations are O(M) *block
inputs*, not O(M·L) intermediates).

Composition: the batch dimension stays sharded over ``(data, fsdp)``, so
DP×PP works out of the box. Tensor parallelism *within* a stage is left to
GSPMD outside the shard_map (a stage body is local by construction).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.parallel.mesh import DATA, FSDP, STAGE
from tpusystem.parallel.sharding import ShardingPolicy

# One layer of the pipelined stack: (layer_params, activations) -> activations
BlockFn = Callable[[Any, jax.Array], jax.Array]


def pipeline_apply(block_fn: BlockFn, stacked_params: Any, hidden: jax.Array,
                   mesh, *, microbatches: int, remat: bool = True) -> jax.Array:
    """Run ``hidden`` through a layer stack pipelined over ``stage``.

    Args:
        block_fn: pure per-layer function ``(layer_params, x) -> x``.
        stacked_params: pytree whose leaves carry a leading ``layers``
            dimension (e.g. built with ``jax.vmap(block.init)``); ``layers``
            must be divisible by the mesh's ``stage`` size.
        hidden: global activations ``[batch, ...]``; batch must divide by
            ``data*fsdp*microbatches``.
        mesh: mesh with a ``stage`` axis (size 1 degenerates gracefully).
        microbatches: how many microbatches to stream through the pipe.
    """
    stages = mesh.shape[STAGE]
    layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if layers % stages:
        raise ValueError(f'{layers} layers not divisible by {stages} stages')
    data_parallel = mesh.shape[DATA] * mesh.shape[FSDP]
    if hidden.shape[0] % (data_parallel * microbatches):
        raise ValueError(
            f'batch {hidden.shape[0]} not divisible by data*fsdp*microbatches '
            f'= {data_parallel}*{microbatches}')
    batch_axes = (DATA, FSDP) if data_parallel > 1 else None
    activation_spec = P(batch_axes, *([None] * (hidden.ndim - 1)))
    param_specs = jax.tree.map(lambda _: P(STAGE), stacked_params)

    stage_body = _stage_scan(block_fn)
    if remat:
        stage_body = jax.checkpoint(stage_body)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(param_specs, activation_spec),
        out_specs=activation_spec, check_vma=False)
    def pipelined(params, local_hidden):
        stage = lax.axis_index(STAGE)
        count = lax.axis_size(STAGE)
        shape = (microbatches, local_hidden.shape[0] // microbatches)
        batches = local_hidden.reshape(shape + local_hidden.shape[1:])

        def tick(state, t):
            feed = lax.dynamic_index_in_dim(
                batches, jnp.clip(t, 0, microbatches - 1), keepdims=False)
            take = jnp.logical_and(stage == 0, t < microbatches)
            state = jnp.where(take, feed, state)
            state = stage_body(params, state)
            emitted = state
            if count > 1:
                permutation = [(source, (source + 1) % count)
                               for source in range(count)]
                state = lax.ppermute(state, STAGE, permutation)
            return state, emitted

        ticks = microbatches + count - 1
        state = jnp.zeros_like(batches[0])
        _, emitted = lax.scan(tick, state, jnp.arange(ticks))
        # the last stage emits microbatch m at tick m + count - 1; broadcast
        # its slice to the other stages (the out_spec replicates over stage)
        outputs = lax.slice_in_dim(emitted, count - 1, count - 1 + microbatches)
        outputs = _broadcast_from_last(outputs, stage, count)
        return outputs.reshape(local_hidden.shape)

    return pipelined(stacked_params, hidden)


def _broadcast_from_last(outputs, stage, count: int):
    """Ring-chain broadcast of the last stage's ``outputs`` to every stage:
    ``count - 1`` single-pair ``ppermute`` rounds walk the buffer around the
    ring one neighbor hop at a time. On the 1D ring ICI a stage axis maps to,
    each link carries the buffer exactly once (the zero-padded ring ``psum``
    this replaces moved ~2x the bytes per link to all-reduce mostly zeros);
    neighbor-only hops mean no multi-hop routing. Latency is count-1 hops —
    the same order as the ring all-reduce. A single-source multi-destination
    ``ppermute`` would be one hop but JAX requires unique destinations."""
    if count == 1:
        return outputs
    state = jnp.where(stage == count - 1, outputs, 0)
    for hop in range(count - 1):
        source = (count - 1 + hop) % count
        state = state + lax.ppermute(state, STAGE,
                                     [(source, (source + 1) % count)])
    return state


def _stage_scan(block_fn: BlockFn):
    """Apply this stage's local layer stack (leading dim layers/stages)."""
    def run(params, state):
        def layer(carry, layer_params):
            return block_fn(layer_params, carry), None
        state, _ = lax.scan(layer, state, params)
        return state
    return run


def PipelineParallel(stacked_prefix: str = r'(^|/)h/', extra_rules=(),
                     fsdp: bool = False, fsdp_min_size: int = 4096) -> ShardingPolicy:
    """Sharding policy for pipelined models: leaves under ``stacked_prefix``
    (the stacked layer collection) shard their leading ``layers`` dimension
    over ``stage``; everything else follows ``extra_rules`` / FSDP."""
    rules = ((stacked_prefix, P(STAGE)),) + tuple(extra_rules)
    return ShardingPolicy(rules=rules, fsdp=fsdp, fsdp_min_size=fsdp_min_size)
