"""Pipeline parallelism — microbatch schedule over the ``stage`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.4: "PP | absent");
this module supplies it TPU-natively: layers are stacked into a leading
``layers`` dimension, that dimension is sharded over the ``stage`` axis (so
each device owns ``layers / stages`` contiguous layers), and microbatch
activations travel stage-to-stage with ``ppermute`` over the ICI ring inside
``shard_map``.

Schedule: GPipe. All microbatch forwards stream through the pipe; XLA's
autodiff of the tick ``lax.scan`` then replays the schedule in reverse, so
the backward pass drains the pipe stage-by-stage in the transposed order —
the same bubble fraction as hand-written 1F1B, ``(S-1)/(M+S-1)``, with
memory bounded by per-microbatch rematerialisation (``remat=True`` wraps
each stage body in ``jax.checkpoint``, so live activations are O(M) *block
inputs*, not O(M·L) intermediates).

Composition: the batch dimension stays sharded over ``(data, fsdp)``, so
DP×PP works out of the box. Tensor parallelism *within* a stage works via
*partial-manual* ``shard_map``: the pipeline is manual over the
``(data, fsdp, stage)`` axes only (``axis_names=``), leaving the ``model``
axis to GSPMD **inside** the stage bodies — stacked params placed
``P(stage, ..., model)`` (see :func:`PipelineParallel`'s
``stacked_rules``) keep their model-axis sharding through the shard_map
boundary, and GSPMD partitions each stage's matmuls over ``model`` with
the usual Megatron collectives, composed with the manual ``ppermute``
ring over ``stage``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.parallel.mesh import (DATA, FSDP, MODEL, STAGE, axis_size,
                                     shard_map)
from tpusystem.parallel.sharding import ShardingPolicy

# One layer of the pipelined stack: (layer_params, activations) -> activations
BlockFn = Callable[[Any, jax.Array], jax.Array]


def _unit_runner(mesh):
    """How schedule units execute on this mesh: ``run_unit(predicate, run,
    skip)``.

    Without a live ``model`` axis, idle (fill/drain/pad) ticks *skip* the
    unit body via ``lax.cond`` — inside shard_map, cond on a
    device-varying predicate is real per-device control flow. With
    ``model > 1`` the stage bodies carry GSPMD-inserted model collectives
    (TP all-reduces, resharding permutes), and a collective may never sit
    under control flow that only some participants take: devices must
    issue every collective in lockstep (XLA:CPU's in-process rendezvous
    deadlocks outright; on any backend non-uniform collective execution
    is undefined SPMD). So under PP x TP every unit executes *masked* —
    both paths run, ``jnp.where`` keeps the active one.

    Masked cost: *block* units pay only the fill/drain bubble's worth of
    extra FLOPs (they were active on ~all non-bubble ticks anyway, and
    idle devices sit in lockstep either way). The 1F1B *head/tail* units
    are different: masked, they run on every stage at every round instead
    of once per microbatch on one stage — up to ~S x redundant head/tail
    work. Under PP x TP keep the per-tick tail light (chunked/fused loss,
    ``return_features``) or use the GPipe path (:func:`pipeline_apply`),
    whose head and tail run outside the pipe entirely."""
    if mesh.shape.get(MODEL, 1) == 1:
        return lambda predicate, run, skip: lax.cond(predicate, run, skip)

    def masked(predicate, run, skip):
        return jax.tree.map(
            lambda a, b: jnp.where(predicate, a, b), run(), skip())
    return masked


def _needs_jit_wrap(mesh) -> bool:
    """Partial-manual shard_map (live model axis) only traces under jit,
    so PP x TP calls are wrapped unconditionally — a non-jit trace
    context (eager ``jax.grad``, ``vmap``, ``eval_shape``) needs the
    wrapper just as plain eager execution does, and under an outer jit
    the nested jit is cheap. The wrapper (and the traced schedule inside
    it) is memoized per stacked-params structure in
    :func:`pipeline_train`, so eager PP x TP callers compile once and
    replay from jit's cache; without a model axis the runner is a bare
    ``shard_map`` and eager callers still pay per-call tracing — jit the
    surrounding step for anything hot."""
    return mesh.shape.get(MODEL, 1) > 1


def _manual_axes(mesh) -> frozenset:
    """Mesh axes the pipeline handles manually inside ``shard_map``.

    With a live ``model`` axis, only the axes whose collectives the
    schedule issues itself (batch ``psum``, stage ``ppermute``) are
    manual; ``model`` stays *auto* — GSPMD sees through the shard_map
    boundary there, so model-axis-sharded stacked params keep their
    sharding and the stage bodies partition over ``model`` with
    GSPMD-inserted collectives (Megatron TP within a stage). Partial
    manualness currently traces only under ``jit`` (eager shard_map
    rejects it), so the degenerate model=1 mesh keeps the classic fully
    manual mapping — identical semantics, eager-friendly. Every axis
    except ``model`` stays manual either way (a block_fn issuing its own
    seq/expert collectives keeps working under PP x TP)."""
    if mesh.shape.get(MODEL, 1) == 1:
        return frozenset(mesh.axis_names)
    return frozenset(mesh.axis_names) - {MODEL}


def pipeline_apply(block_fn: BlockFn, stacked_params: Any, hidden: jax.Array,
                   mesh, *, microbatches: int, remat: bool = True,
                   interleave: int = 1, schedule=None,
                   has_aux: bool = False):
    """Run ``hidden`` through a layer stack pipelined over ``stage``.

    Args:
        block_fn: pure per-layer function ``(layer_params, x) -> x`` —
            or ``(layer_params, x) -> (x, aux)`` with ``has_aux=True``.
        stacked_params: pytree whose leaves carry a leading ``layers``
            dimension (e.g. built with ``jax.vmap(block.init)``); ``layers``
            must be divisible by the mesh's ``stage`` size. With
            ``interleave = v > 1`` the leaves are chunk-major
            ``[v, layers/v, ...]`` (a plain reshape of the layer-major
            stack) sharded ``P(None, stage)`` — the same layout contract as
            :func:`pipeline_train`.
        hidden: global activations ``[batch, ...]``; batch must divide by
            ``data*fsdp*microbatches``.
        mesh: mesh with a ``stage`` axis (size 1 degenerates gracefully).
        microbatches: how many microbatches to stream through the pipe.
        interleave: virtual-pipeline chunks per device. ``v > 1`` shrinks
            the forward fill/drain bubble from ``S-1`` stage-units to
            ``(S-1)/v`` (microbatches ride the ring ``v`` times through
            chunk-sized units — the schedule of :func:`pipeline_train`'s
            forward slot). Microbatch counts that don't divide the stage
            count pad the last chunk sweep with idle units (the intrinsic
            ring-latency bubble of a short group — see
            :func:`pipeline_train`).
        schedule: optional :class:`~tpusystem.parallel.schedule
            .OverlapSchedule`. With ``pp='overlap'`` the loop takes the
            *skewed double-buffered* tick: each stage issues the
            ``ppermute`` of last tick's output at tick top — under this
            tick's stage compute, which consumes the message received a
            tick earlier — so every stage-to-stage transfer rides under a
            microbatch's matmuls instead of sitting on the tick-to-tick
            critical path (the classic tick sends *after* the compute
            that produced the message, so the next tick's compute waits
            out the wire). Price: one extra fill tick per stage
            (``M + 2(S-1)`` ticks vs ``M + S - 1``). The hop is the
            :func:`~tpusystem.parallel.collectives.pp_hop` custom_vjp,
            so autodiff's reversed sends hide under the backward matmuls
            the same way; both schedules compute identical math on
            identical operands — outputs are **bitwise-equal**. The pure
            :func:`~tpusystem.parallel.schedule.pp_plan` pins the
            classic fallback (microbatch rows won't split into
            ``schedule.chunks`` ppermutes, or ``interleave > 1``).
        has_aux: ``block_fn`` returns ``(x, aux_scalar)`` per unit (the
            MoE router losses); the call returns ``(hidden, aux)`` with
            ``aux`` the mean over every (unit, microbatch) — summed over
            stages, averaged over batch shards.
    """
    stages = mesh.shape[STAGE]
    chunks = interleave
    leading = jax.tree.leaves(stacked_params)[0].shape[:2]
    if chunks > 1 and leading[0] != chunks:
        raise ValueError(
            f'interleave={chunks} expects chunk-major stacked params '
            f'[{chunks}, layers/{chunks}, ...]; got leading dims {leading}')
    layers = leading[0] if chunks == 1 else chunks * leading[1]
    if layers % (stages * chunks):
        raise ValueError(f'{layers} layers not divisible by {stages} stages '
                         f'x {chunks} chunks')
    data_parallel = mesh.shape[DATA] * mesh.shape[FSDP]
    if hidden.shape[0] % (data_parallel * microbatches):
        raise ValueError(
            f'batch {hidden.shape[0]} not divisible by data*fsdp*microbatches '
            f'= {data_parallel}*{microbatches}')
    batch_axes = (DATA, FSDP) if data_parallel > 1 else None
    activation_spec = P(batch_axes, *([None] * (hidden.ndim - 1)))
    chunk_spec = P(STAGE) if chunks == 1 else P(None, STAGE)
    param_specs = jax.tree.map(lambda _: chunk_spec, stacked_params)
    # a partial last group pads with idle units (see pipeline_train)
    padded = (microbatches if chunks == 1
              else -(-microbatches // stages) * stages)

    # the pp= arm: the pure plan decides skewed-overlap vs classic ticks
    # from the per-device microbatch's leading dimension (what pp_hop's
    # chunked ppermute splits)
    from tpusystem.parallel.schedule import PpPlan, pp_plan
    micro_rows = hidden.shape[0] // data_parallel // microbatches
    if schedule is not None and schedule.pp == 'overlap':
        plan = pp_plan(micro_rows, stages, chunks=schedule.chunks,
                       interleave=interleave)
    else:
        plan = PpPlan('skip', 1, 'pp overlap inactive')

    stage_body = _stage_scan(block_fn, has_aux=has_aux)
    if remat:
        stage_body = jax.checkpoint(stage_body)
    run_unit = _unit_runner(mesh)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, activation_spec),
        out_specs=(activation_spec, P()) if has_aux else activation_spec,
        check_vma=False,
        axis_names=_manual_axes(mesh))
    def pipelined(params, local_hidden):
        stage = lax.axis_index(STAGE)
        count = axis_size(STAGE)
        shape = (microbatches, local_hidden.shape[0] // microbatches)
        batches = local_hidden.reshape(shape + local_hidden.shape[1:])
        if chunks == 1:
            params_all = jax.tree.map(lambda leaf: leaf[None], params)
        else:
            params_all = params
        span = chunks * count

        def unit_out(params_c, x):
            out = stage_body(params_c, x)
            return out if has_aux else (out, jnp.float32(0))

        def idle_out(x):
            return jnp.zeros_like(x), jnp.float32(0)

        def schedule_unit(unit):
            """Unit index -> (active, chunk, microbatch) — the forward slot
            of pipeline_train's interleaved schedule; for chunks == 1 it
            reduces to (0 <= unit < M, 0, unit)."""
            group, rem = jnp.divmod(unit, span)
            chunk, pos = jnp.divmod(rem, count)
            m = group * count + pos
            active = ((unit >= 0) & (unit < chunks * padded)
                      & (m < microbatches))
            return (active, jnp.clip(chunk, 0, chunks - 1),
                    jnp.clip(m, 0, microbatches - 1))

        def classic_tick(state, t):
            active, c_f, m_f = schedule_unit(t - stage)
            feed = lax.dynamic_index_in_dim(batches, m_f, keepdims=False)
            # a microbatch enters the pipe at stage 0 chunk 0; every later
            # virtual stage consumes the ring message
            x = jnp.where((stage == 0) & (c_f == 0), feed, state)
            params_c = jax.tree.map(
                lambda leaf: lax.dynamic_index_in_dim(leaf, c_f, 0,
                                                      keepdims=False),
                params_all)
            # idle (fill/drain/pad) ticks skip the block compute (cond —
            # real per-device control flow inside shard_map) or run it
            # masked under PP x TP: see _unit_runner
            emitted, unit_aux = run_unit(active,
                                         lambda: unit_out(params_c, x),
                                         lambda: idle_out(x))
            if count > 1:
                permutation = [(source, (source + 1) % count)
                               for source in range(count)]
                state = lax.ppermute(emitted, STAGE, permutation)
            else:
                state = emitted
            return state, (emitted, unit_aux)

        if plan.path == 'overlap':
            # the skewed double-buffered schedule (pp='overlap', plain
            # GPipe only: chunks == 1 pinned by pp_plan). Stage s computes
            # microbatch m at tick m + 2s: the carry holds (last tick's
            # unsent output, the message received last tick), and the
            # send issues FIRST — this tick's compute consumes `arrived`,
            # not `incoming`, so the pp_hop transfer and the stage matmuls
            # are independent within one scan iteration and XLA's
            # latency-hiding scheduler runs them concurrently. The classic
            # tick's send sits between its producer and next tick's
            # consumer — unhideable inside a sequential scan.
            from tpusystem.parallel.collectives import pp_hop
            params_0 = jax.tree.map(lambda leaf: leaf[0], params_all)

            def overlap_tick(carry, t):
                pending, arrived = carry
                incoming = pp_hop(STAGE, plan.chunks, pending)
                unit = t - 2 * stage
                active = (unit >= 0) & (unit < microbatches)
                m = jnp.clip(unit, 0, microbatches - 1)
                feed = lax.dynamic_index_in_dim(batches, m, keepdims=False)
                x = jnp.where(stage == 0, feed, arrived)
                emitted, unit_aux = run_unit(active,
                                             lambda: unit_out(params_0, x),
                                             lambda: idle_out(x))
                # aux rides the scan ys, not the carry: a scalar carried
                # across the scan becomes a scalar shard_map residual at
                # linearization, which this jax's partial-eval cannot
                # name-check ({0: all_names} on a rank-0 aval)
                return (emitted, incoming), (emitted, unit_aux)

            ticks = microbatches + 2 * (count - 1)
            zero = jnp.zeros_like(batches[0])
            _, (emitted, aux_ticks) = lax.scan(overlap_tick, (zero, zero),
                                               jnp.arange(ticks))
            aux_local = jnp.sum(aux_ticks)
            # the last stage computes microbatch m at tick m + 2(S-1)
            emit_ticks = np.arange(microbatches) + 2 * (count - 1)
        else:
            ticks = chunks * padded + count - 1
            state = jnp.zeros_like(batches[0])
            _, (emitted, aux_ticks) = lax.scan(classic_tick, state,
                                               jnp.arange(ticks))
            aux_local = jnp.sum(aux_ticks)
            # the last stage emits microbatch m (final chunk) at tick
            # (m//S)*v*S + (v-1)*S + m%S + S-1 — contiguous [S-1, S-1+M)
            # for v == 1; gather the group-strided ticks otherwise
            emit_ticks = np.array(
                [(m // stages) * span + (chunks - 1) * stages + (m % stages)
                 + stages - 1 for m in range(microbatches)])
        outputs = jnp.take(emitted, emit_ticks, axis=0)
        outputs = _broadcast_from_last(outputs, stage, count)
        outputs = outputs.reshape(local_hidden.shape)
        if not has_aux:
            return outputs
        # aux: sum over stages (each unit lives on exactly one stage),
        # mean over every (unit, microbatch), mean over batch shards
        aux = lax.psum(aux_local, STAGE) / (microbatches * layers)
        if batch_axes:
            aux = lax.pmean(aux, batch_axes)
        return outputs, aux

    if _needs_jit_wrap(mesh):
        pipelined = jax.jit(pipelined)
    return pipelined(stacked_params, hidden)


def _broadcast_from_last(outputs, stage, count: int):
    """Ring-chain broadcast of the last stage's ``outputs`` to every stage:
    ``count - 1`` single-pair ``ppermute`` rounds walk the buffer around the
    ring one neighbor hop at a time. On the 1D ring ICI a stage axis maps to,
    each link carries the buffer exactly once (the zero-padded ring ``psum``
    this replaces moved ~2x the bytes per link to all-reduce mostly zeros);
    neighbor-only hops mean no multi-hop routing. Latency is count-1 hops —
    the same order as the ring all-reduce. A single-source multi-destination
    ``ppermute`` would be one hop but JAX requires unique destinations."""
    if count == 1:
        return outputs
    state = jnp.where(stage == count - 1, outputs, 0)
    for hop in range(count - 1):
        source = (count - 1 + hop) % count
        state = state + lax.ppermute(state, STAGE,
                                     [(source, (source + 1) % count)])
    return state


def _stage_scan(block_fn: BlockFn, has_aux: bool = False):
    """Apply this stage's local layer stack (leading dim layers/stages).

    With ``has_aux`` the block_fn returns ``(x, aux_scalar)`` per unit
    (MoE router losses) and the stage body returns ``(x, aux_sum)`` —
    the f32 sum over this stage's local units, reduced across stages and
    microbatches by the caller."""
    if has_aux:
        def run_aux(params, state):
            def layer(carry, layer_params):
                x, aux = carry
                x, unit_aux = block_fn(layer_params, x)
                return (x, aux + unit_aux.astype(jnp.float32)), None
            carry, _ = lax.scan(layer, (state, jnp.float32(0)), params)
            return carry
        return run_aux

    def run(params, state):
        def layer(carry, layer_params):
            return block_fn(layer_params, carry), None
        state, _ = lax.scan(layer, state, params)
        return state
    return run


@functools.lru_cache(maxsize=None)
def _stash_slots(stages: int, interleave: int, microbatches: int) -> int:
    """Smallest per-chunk stash size such that ``m % slots`` indexing never
    clobbers a live microbatch input.

    A chunk input written at its forward tick must survive until its
    backward tick; reuse of slot ``m % slots`` by microbatch ``m + slots``
    is safe iff that later forward happens strictly after this backward.
    Checked directly against the schedule formulas (see
    :func:`pipeline_train`); for ``interleave == 1`` this recovers the
    classic 1F1B bound ``2 * stages - 1``. Memoized: the brute-force
    check is O(slots * interleave * stages * microbatches) of pure Python
    and otherwise re-runs at every ``pipeline_train`` construction.
    """
    def fwd_tick(c, s, m):
        group, pos = divmod(m, stages)
        return s + group * interleave * stages + c * stages + pos

    def bwd_tick(c, s, m):
        group, pos = divmod(m, stages)
        return ((interleave * stages + stages - 2 - s)
                + group * interleave * stages
                + (interleave - 1 - c) * stages + pos)

    for slots in range(1, microbatches + 1):
        if all(fwd_tick(c, s, m + slots) > bwd_tick(c, s, m)
               for c in range(interleave) for s in range(stages)
               for m in range(microbatches - slots)):
            return slots
    return microbatches


def pipeline_train(head_fn, block_fn, tail_fn, mesh, *, microbatches: int,
                   weight_fn=None, interleave: int = 1):
    """1F1B-scheduled pipelined loss + gradients (one combined pass).

    The GPipe path (:func:`pipeline_apply` under ``jax.grad``) stashes
    O(microbatches) activations per stage because the backward replays the
    whole forward scan in reverse. This schedule interleaves forwards with
    backwards, so a microbatch's backward runs a bounded number of ticks
    after its forward and the per-stage stash is bounded independent of
    the microbatch count (block outputs are rematerialized in the backward
    ``jax.vjp``) — the activation-memory lever for deep pipes. The last
    stage backwards each microbatch in the same tick it forwards it
    (classic 1F1B).

    **Interleaved (circular) schedule** (``interleave = v > 1``): each
    device owns ``v`` *non-contiguous* layer chunks — virtual stage
    ``q = c * stages + s`` lives on device ``s`` — and microbatches travel
    the ring ``v`` times through chunk-sized units. With ``S`` stages and
    ``M`` microbatches the tick count is ``vM + vS + S - 2`` chunk-units
    against plain 1F1B's ``(M + 2S - 2)`` stage-units = ``v(M + 2S - 2)``
    chunk-units: the pipeline fill/drain bubble shrinks from ``~2S`` stage
    units toward ``~S/v`` stage units. Schedule (tick ``r``, device ``s``,
    groups of ``S`` microbatches per chunk sweep):

    * forward: unit index ``i = r - s`` (active while ``0 <= i < vM``),
      group ``g = i // (vS)``, chunk ``c_f = (i % (vS)) // S``, microbatch
      ``m_f = gS + i % S``.
    * backward: ``j = r - (vS + S - 2 - s)``, group ``g = j // (vS)``,
      chunk ``c_b = v - 1 - (j % (vS)) // S``, microbatch
      ``m_b = gS + j % S``.

    Every dependency (virtual stage ``q`` before ``q+1``, forward before
    backward, one-tick ``ppermute`` latency on both rings) holds with
    equality along the critical path, and for ``v = 1`` the formulas
    reduce exactly to classic 1F1B (forward ``r - s``, backward
    ``r - (2S - 2 - s)``).

    Idle units cost (almost) nothing *without tensor parallelism*: the
    head, the tail, and each block forward/backward unit sit under
    ``lax.cond`` — inside ``shard_map``, ``lax.cond`` on a device-varying
    predicate is real per-device control flow, so fill/drain ticks skip
    the block compute instead of executing it masked. With a live
    ``model`` axis (PP x TP) every unit runs *masked* instead — a
    GSPMD-inserted model collective cannot sit under control flow only
    some devices take — so block units pay the bubble's FLOPs and the
    head/tail run on every stage at every round (up to ~S x redundant
    head/tail work; keep the per-tick tail light under PP x TP — see
    :func:`_unit_runner`).

    No autodiff runs through the round loop: gradients are accumulated
    explicitly, so ``jax.grad`` of the caller is neither needed nor
    supported — the function *returns* the grads.

    Args:
        head_fn: ``(replicated_params, micro_inputs) -> activations`` —
            the pre-pipe part (embeddings), executed at stage 0 (chunk 0).
        block_fn: ``(layer_params, x) -> x`` per layer; layers stacked and
            stage-sharded as in :func:`pipeline_apply`.
        tail_fn: ``(replicated_params, activations, micro_targets) ->
            scalar mean loss`` — the post-pipe part (final norm, LM head,
            criterion), executed at the last stage (last chunk).
            ``replicated_params`` is ONE pytree shared by head and tail (a
            tied embedding appears in both; its two gradient contributions
            are summed).
        mesh: mesh with ``stage`` (and optionally data/fsdp) axes.
        microbatches: microbatches per step; batch must divide by
            ``data*fsdp*microbatches``. With interleave the schedule
            sweeps chunks in groups of ``stages`` microbatches; a
            remainder group is padded with idle units, so prefer
            ``microbatches % stages == 0``. The padding is the schedule's
            *intrinsic* short-group bubble, not an artifact: advancing a
            chunk sweep to the next chunk needs the previous chunk's
            output back from the last device — ``S`` one-tick ``ppermute``
            hops — and a group of ``R = M % S < S`` microbatches can only
            cover ``R`` of those ticks with work, so ``S - R`` idle units
            per chunk transition are forced by the ring latency (a
            "compressed" sweep would consume activations before they
            arrive). Total overhead: at most ``v * (S - R)`` idle
            chunk-units of ``vM + vS + S - 2`` — the same order as the
            fill/drain bubble itself, and second-order at realistic
            ``M >= 4S``.
        weight_fn: optional ``(micro_targets) -> scalar`` microbatch weight
            (the masked LM losses' unmasked-token count) — the same
            weighting ``build_train_step(accumulate=...)`` applies, so
            padded microbatches reproduce the full-batch mean. ``None``
            weighs microbatches equally.
        interleave: virtual-pipeline chunks per device. ``1`` = classic
            1F1B over contiguous stage slices (stacked leaves
            ``[layers, ...]``, sharded ``P(stage)``); ``v > 1`` expects
            stacked leaves reshaped to ``[v, layers/v, ...]`` (a plain
            reshape of the layer-major stack) sharded ``P(None, stage)``,
            so device ``s`` holds layers ``{(c*S + s) * Lc + j}``.

    Returns:
        ``step(replicated_params, stacked_params, inputs, targets) ->
        (loss, (d_replicated, d_stacked))`` with the loss and gradients
        weight-averaged over microbatches and data shards; gradients
        accumulate in float32 and return in the parameter dtypes.
    """
    stages = mesh.shape[STAGE]
    data_parallel = mesh.shape[DATA] * mesh.shape[FSDP]
    batch_axes = (DATA, FSDP) if data_parallel > 1 else None
    chunks = interleave
    slots = _stash_slots(stages, chunks, microbatches)
    # the interleaved schedule sweeps each chunk over groups of `stages`
    # microbatches; a partial last group is padded with idle units (clipped
    # microbatch indices would silently duplicate/skip work). For chunks==1
    # the group decomposition is exact for any microbatch count.
    padded = (microbatches if chunks == 1
              else -(-microbatches // stages) * stages)
    rounds = chunks * padded + chunks * stages + stages - 2
    stage_body = _stage_scan(block_fn)
    run_unit = _unit_runner(mesh)

    batch_spec = P(batch_axes)
    chunk_spec = P(STAGE) if chunks == 1 else P(None, STAGE)
    # the traced pipeline is memoized per stacked-params STRUCTURE (the
    # only input the shard_map specs depend on): an eager PP x TP caller
    # used to rebuild `run` and re-wrap it in a fresh `jax.jit` every
    # step, retracing the whole schedule each call — now the wrapper is
    # built once and jit's own cache handles shape changes
    runners: dict = {}

    def _build_runner(param_structure):
        param_specs = param_structure.unflatten(
            [chunk_spec] * param_structure.num_leaves)

        @functools.partial(
            shard_map, mesh=mesh, check_vma=False,
            in_specs=(P(), param_specs, batch_spec, batch_spec),
            out_specs=(P(), (P(), param_specs)),
            axis_names=_manual_axes(mesh))
        def run(reps, stacked, local_inputs, local_targets):
            stage = lax.axis_index(STAGE)
            count = stages
            micro = lambda a: a.reshape(
                (microbatches, a.shape[0] // microbatches) + a.shape[1:])
            micro_in, micro_tgt = micro(local_inputs), micro(local_targets)

            # unify layouts: local chunk stack [chunks, layers/chunk, ...]
            # (for chunks == 1 the P(stage) local slice [layers/S, ...]
            # gains a unit leading dim; grads reshape back at the end)
            stacked_in = stacked
            if chunks == 1:
                stacked = jax.tree.map(lambda leaf: leaf[None], stacked)

            def chunk_params(tree, c):
                if chunks == 1:
                    return jax.tree.map(lambda leaf: leaf[0], tree)
                return jax.tree.map(
                    lambda leaf: lax.dynamic_index_in_dim(leaf, c, 0,
                                                          keepdims=False),
                    tree)

            sample = head_fn(reps, micro_in[0])
            zero_act = jnp.zeros_like(sample)
            # gradient accumulators in float32 regardless of param dtype
            # (stable sums + exact token-count weights), cast back at the end
            zeros_f32 = lambda tree: jax.tree.map(
                lambda leaf: jnp.zeros(leaf.shape, jnp.float32), tree)
            carry = dict(
                fwd_msg=zero_act,
                bwd_msg=jnp.zeros_like(sample),
                stash=jnp.zeros((chunks, slots) + sample.shape, sample.dtype),
                d_stacked=zeros_f32(stacked),
                d_reps=zeros_f32(reps),
                loss=jnp.float32(0),
                weight=jnp.float32(0),
            )

            perm_fwd = [(i, (i + 1) % count) for i in range(count)]
            perm_bwd = [(i, (i - 1) % count) for i in range(count)]
            span = chunks * count    # ticks per (group, chunk) sweep

            def schedule(unit):
                """Unit index -> (active, chunk, microbatch)."""
                group, rem = jnp.divmod(unit, span)
                chunk, pos = jnp.divmod(rem, count)
                m = group * count + pos
                # padding units of a partial last group are idle, never
                # clipped onto a real microbatch (that would duplicate it)
                active = ((unit >= 0) & (unit < chunks * padded)
                          & (m < microbatches))
                return (active, jnp.clip(chunk, 0, chunks - 1),
                        jnp.clip(m, 0, microbatches - 1))

            def round_body(carry, r):
                active_f, c_f_raw, m_f = schedule(r - stage)
                c_f = c_f_raw
                feed = lax.dynamic_index_in_dim(micro_in, m_f, keepdims=False)
                # run_unit: lax.cond per-device control flow (only stage 0
                # pays for the embedding, only the last stage for the tail
                # fwd+bwd, fill/drain ticks skip the block unit) — or
                # masked lockstep execution under PP x TP (_unit_runner)
                x = run_unit((stage == 0) & (c_f == 0),
                             lambda: head_fn(reps, feed),
                             lambda: carry['fwd_msg'])
                params_f = chunk_params(stacked, c_f)
                y = run_unit(active_f,
                             lambda: stage_body(params_f, x),
                             lambda: zero_act)
                stash = jnp.where(
                    active_f,
                    lax.dynamic_update_slice(
                        carry['stash'], x[None, None],
                        (c_f, m_f % slots) + (0,) * x.ndim),
                    carry['stash'])

                # tail: the last stage turns its final-chunk forward into a
                # loss and a cotangent seed in the same tick (1F1B)
                tgt = lax.dynamic_index_in_dim(micro_tgt, m_f, keepdims=False)
                is_last = stage == count - 1
                active_t = active_f & is_last & (c_f == chunks - 1)

                def run_tail():
                    loss_m, (d_tail_m, dy) = jax.value_and_grad(
                        tail_fn, argnums=(0, 1))(reps, y, tgt)
                    return loss_m, d_tail_m, dy

                def skip_tail():
                    return (jnp.float32(0), jax.tree.map(jnp.zeros_like, reps),
                            jnp.zeros_like(y))

                loss_m, d_tail_m, dy = run_unit(active_t, run_tail,
                                                skip_tail)
                weight = (jnp.float32(weight_fn(tgt)) if weight_fn
                          else jnp.float32(1.0))
                # the weight rides the cotangent seed, so every downstream
                # gradient (blocks, head) is weighted without extra work
                dy = dy * weight.astype(dy.dtype)
                loss_acc = carry['loss'] + jnp.where(active_t,
                                                     loss_m * weight, 0)
                weight_acc = carry['weight'] + jnp.where(active_t, weight, 0)

                # backward unit: recompute this chunk's forward from the
                # stashed input (rematerialization) and pull grads through
                active_b, c_b_rev, m_b = schedule(
                    r - (chunks * count + count - 2 - stage))
                c_b = chunks - 1 - c_b_rev
                x_saved = lax.dynamic_slice(
                    stash, (c_b, m_b % slots) + (0,) * sample.ndim,
                    (1, 1) + sample.shape)
                x_saved = jnp.squeeze(x_saved, axis=(0, 1))
                # the last stage's final-chunk backward consumes the dy it
                # just produced; every other unit consumes the ring message
                cot = jnp.where(is_last & (c_b == chunks - 1), dy,
                                carry['bwd_msg'])
                params_b = chunk_params(stacked, c_b)

                def run_bwd():
                    _, vjp_fn = jax.vjp(stage_body, params_b, x_saved)
                    return vjp_fn(cot.astype(y.dtype))

                def skip_bwd():
                    return (jax.tree.map(jnp.zeros_like, params_b),
                            jnp.zeros_like(x_saved))

                d_chunk_m, dx = run_unit(active_b, run_bwd, skip_bwd)
                if chunks == 1:
                    d_stacked = jax.tree.map(
                        lambda acc, g: acc + g.astype(jnp.float32)[None],
                        carry['d_stacked'], d_chunk_m)
                else:
                    d_stacked = jax.tree.map(
                        lambda acc, g: lax.dynamic_update_index_in_dim(
                            acc,
                            lax.dynamic_index_in_dim(acc, c_b, 0,
                                                     keepdims=False)
                            + g.astype(jnp.float32),
                            c_b, 0),
                        carry['d_stacked'], d_chunk_m)

                # stage 0's chunk-0 input cotangent flows into the head
                feed_b = lax.dynamic_index_in_dim(micro_in, m_b,
                                                  keepdims=False)
                active_h = active_b & (stage == 0) & (c_b == 0)

                def run_head_vjp():
                    _, head_vjp = jax.vjp(lambda p: head_fn(p, feed_b), reps)
                    (d_head_m,) = head_vjp(dx)
                    return d_head_m

                d_head_m = run_unit(active_h, run_head_vjp,
                                    lambda: jax.tree.map(jnp.zeros_like, reps))
                accumulate = lambda acc_tree, grad_tree, condition: jax.tree.map(
                    lambda acc, g: acc + jnp.where(condition,
                                                   g.astype(jnp.float32), 0),
                    acc_tree, grad_tree)
                d_reps = accumulate(
                    accumulate(carry['d_reps'],
                               jax.tree.map(lambda g: g * weight, d_tail_m),
                               active_t),
                    d_head_m, active_h)

                return dict(
                    fwd_msg=lax.ppermute(y, STAGE, perm_fwd),
                    bwd_msg=lax.ppermute(dx, STAGE, perm_bwd),
                    stash=stash, d_stacked=d_stacked, d_reps=d_reps,
                    loss=loss_acc, weight=weight_acc), None

            if count > 1:
                carry, _ = lax.scan(round_body, carry, jnp.arange(rounds))
            else:
                # degenerate single stage: plain microbatch loop (head must
                # sit INSIDE the objective so embedding grads flow); the
                # chunk dim flattens back to the layer-major stack
                flat = jax.tree.map(
                    lambda leaf: leaf.reshape((-1,) + leaf.shape[2:]), stacked)

                def single(carry, m):
                    tgt = micro_tgt[m]
                    weight = (jnp.float32(weight_fn(tgt)) if weight_fn
                              else jnp.float32(1.0))

                    def objective(reps, flat):
                        x = head_fn(reps, micro_in[m])
                        return weight * tail_fn(reps, stage_body(flat, x),
                                                tgt)
                    loss_m, (d_r, d_s) = jax.value_and_grad(
                        objective, argnums=(0, 1))(reps, flat)
                    add_f32 = lambda acc_tree, grad_tree: jax.tree.map(
                        lambda acc, g: acc + g.astype(jnp.float32).reshape(
                            acc.shape),
                        acc_tree, grad_tree)
                    return dict(
                        carry,
                        loss=carry['loss'] + loss_m,
                        weight=carry['weight'] + weight,
                        d_reps=add_f32(carry['d_reps'], d_r),
                        d_stacked=add_f32(carry['d_stacked'], d_s),
                    ), None
                carry, _ = lax.scan(single, carry, jnp.arange(microbatches))

            # weighted means: sum(w_m * value_m) / sum(w_m) across the
            # microbatches of every data shard (loss/replicated grads also
            # sum over stage: each term lives on exactly one stage)
            batch_reduce = batch_axes or ()
            total = lax.psum(carry['weight'], (STAGE,) + batch_reduce)
            loss = lax.psum(carry['loss'], (STAGE,) + batch_reduce) / total
            d_reps = jax.tree.map(
                lambda g, p: (lax.psum(g, (STAGE,) + batch_reduce)
                              / total).astype(p.dtype),
                carry['d_reps'], reps)
            d_stacked = jax.tree.map(
                lambda g, p: (
                    (lax.psum(g, batch_reduce) if batch_reduce else g)
                    / total).astype(p.dtype).reshape(p.shape),
                carry['d_stacked'], stacked_in)
            return loss, (d_reps, d_stacked)

        return jax.jit(run) if _needs_jit_wrap(mesh) else run

    def step(replicated_params, stacked_params, inputs, targets):
        if inputs.shape[0] % (data_parallel * microbatches):
            raise ValueError(
                f'batch {inputs.shape[0]} not divisible by '
                f'data*fsdp*microbatches = {data_parallel}*{microbatches}')
        structure = jax.tree.structure(stacked_params)
        runner = runners.get(structure)
        if runner is None:
            runner = runners[structure] = _build_runner(structure)
        return runner(replicated_params, stacked_params, inputs, targets)

    return step


def PipelineParallel(stacked_prefix: str = r'(^|/)h/', extra_rules=(),
                     stacked_rules=(), fsdp: bool = False,
                     fsdp_min_size: int = 4096,
                     interleave: int = 1) -> ShardingPolicy:
    """Sharding policy for pipelined models: leaves under ``stacked_prefix``
    (the stacked layer collection) shard their leading ``layers`` dimension
    over ``stage``; everything else follows ``extra_rules`` / FSDP.

    ``stacked_rules`` composes Megatron TP *within* stages: ``(pattern,
    spec)`` pairs matched against the within-stack leaf path (the same
    per-block rules the non-pipelined family ships, e.g.
    ``('attn/qkv/kernel$', P(None, 'model'))``); the matched spec is
    shifted right past the stage dim(s), so a qkv kernel lands on
    ``P(stage, None, 'model')``. The pipeline's partial-manual
    ``shard_map`` leaves the ``model`` axis to GSPMD inside stage bodies,
    which turns these placements into partitioned stage matmuls + TP
    collectives (see the module docstring). Leaves no stacked rule
    matches fall back to plain stage sharding.

    ``interleave > 1`` matches :func:`pipeline_train`'s chunk-major layout
    (leaves ``[interleave, layers/interleave, ...]``): the *second* dim
    shards over ``stage``, so each device holds its ``interleave``
    non-contiguous chunks without per-step resharding."""
    rules = compose_stacked_rules(stacked_prefix, stacked_rules, interleave)
    rules += tuple(extra_rules)
    return ShardingPolicy(rules=rules, fsdp=fsdp, fsdp_min_size=fsdp_min_size)


def compose_stacked_rules(stacked_prefix: str, stacked_rules,
                          interleave: int = 1):
    """Shift within-stack TP rules past the stage dim(s) and append the
    plain stage-sharding fallback — the rule set both
    :func:`PipelineParallel` and the pipelined model families build their
    policies from. ``stacked_rules`` patterns are ``re.search``-ed against
    the leaf path, so anchor them to the leaf end (``kernel$``)."""
    stage_dims = (STAGE,) if interleave <= 1 else (None, STAGE)
    rules = tuple(
        (rf'(?:{stacked_prefix}).*(?:{pattern})', P(*stage_dims, *spec))
        for pattern, spec in stacked_rules)
    return rules + ((stacked_prefix, P(*stage_dims)),)
