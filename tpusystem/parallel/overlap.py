"""Latency-hiding tensor-parallel collectives — decomposed all-gather /
reduce-scatter matmuls over the ``model`` mesh axis.

GSPMD lowers a Megatron TP layer with sequence-sharded activations to a
*monolithic* all-gather before the up-projection and a *monolithic*
reduce-scatter after the down-projection, serializing the ICI transfer
against the MXU. This module applies the repo's latency-hiding idiom
(``ops/ring.py``: issue the next step's ``ppermute`` before this step's
compute) to those dense matmuls, after the decomposition strategy of
GSPMD/TPU-v4 systems work (Xu et al., GSPMD; Wang et al., "Overlap
Communication with Dependent Computation via Decomposition"):

* :func:`allgather_matmul` — ``all_gather(x) @ w`` as ``n`` ring steps.
  Each device's shard of ``x`` rotates around the ring; every step's
  partial matmul (one row-block of the result) runs while the next
  shard's ``ppermute`` is in flight, so the transfer hides under the
  matmul instead of preceding it.
* :func:`matmul_reducescatter` — the dual: ``psum_scatter(x @ w)`` as
  ``n`` chunked partial matmuls whose running f32 sum ring-shifts one hop
  per step toward the row-block's owner, hiding the reduction behind the
  next chunk's compute.

Both carry a ``custom_vjp`` built from the same two decompositions — the
transpose of an overlapped all-gather-matmul *is* an overlapped
matmul-reduce-scatter with swapped operands (and vice versa), and the
weight gradient is the shared :func:`_ring_transpose_matmul` ring (the
gathered operand rotates against static row-blocks of the other factor) —
so the backward pass overlaps exactly like the forward. All partial
matmuls accumulate in float32 (``preferred_element_type``) and the
cross-step reduce-scatter sum is carried in float32, then cast once to
the operands' result dtype.

Fallback: when ``axis_size == 1`` or the requested ``chunks`` cannot tile
the shard rows, both functions take the **one-shot** collective path
(``lax.all_gather`` + matmul / matmul + ``lax.psum_scatter``) — the plan
is computed by the pure :func:`allgather_plan` / :func:`reducescatter_plan`
helpers so tests can pin which path a shape takes.

Model wiring: :func:`tp_ffn` (bias + activation, GPT-2) and
:func:`tp_swiglu` (gate/up fused into ONE ring, Llama) shard_map a whole
sequence-sharded FFN over the mesh; the model families expose them behind
``tp_impl='overlap' | 'gspmd'`` (threaded like ``moe_sparse_impl``).
Everything here is called *inside* ``shard_map`` except those two
wrappers, which build it.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpusystem.parallel.collectives import ring_shift_chunked
from tpusystem.parallel.mesh import DATA, FSDP, MODEL, SEQ, axis_size


class OverlapPlan(NamedTuple):
    """Which path a (shape, ring, chunks) combination takes.

    ``path`` is ``'overlap'`` (decomposed ring) or ``'one-shot'`` (the
    monolithic collective); ``chunks`` is the per-hop ppermute split the
    overlap path will use; ``reason`` documents a fallback.
    """

    path: str
    chunks: int
    reason: str


def allgather_plan(rows: int, ring: int, chunks: int = 1) -> OverlapPlan:
    """Plan for ``allgather_matmul`` with per-device shards of ``rows``."""
    if ring == 1:
        return OverlapPlan('one-shot', 1, 'axis_size == 1')
    if chunks < 1 or rows % chunks:
        return OverlapPlan(
            'one-shot', 1,
            f'shard rows ({rows}) not divisible by chunks ({chunks})')
    return OverlapPlan('overlap', chunks, '')


def reducescatter_plan(rows: int, ring: int, chunks: int = 1) -> OverlapPlan:
    """Plan for ``matmul_reducescatter`` with ``rows`` total result rows.

    ``rows % ring != 0`` raises: a scatter over non-dividing rows has no
    semantics on the one-shot path either (``psum_scatter`` tiles).
    """
    if ring == 1:
        return OverlapPlan('one-shot', 1, 'axis_size == 1')
    if rows % ring:
        raise ValueError(
            f'matmul_reducescatter needs rows ({rows}) divisible by the '
            f'ring ({ring}) — the scattered result has no shape otherwise')
    if chunks < 1 or (rows // ring) % chunks:
        return OverlapPlan(
            'one-shot', 1,
            f'scatter block ({rows // ring}) not divisible by chunks '
            f'({chunks})')
    return OverlapPlan('overlap', chunks, '')


def _partial_matmul(a, b):
    """One ring step's matmul, always accumulating in float32."""
    return jnp.matmul(a, b, preferred_element_type=jnp.float32)


def _out_dtype(x, w):
    return jnp.result_type(x.dtype, w.dtype)


def _allgather_matmul_overlap(axis, chunks, x, w):
    """The decomposed ring: shard ``s`` of ``x`` rotates forward; its
    partial matmul lands in row-block ``s`` of the result while the next
    shard's ``ppermute`` is in flight."""
    ring = axis_size(axis)
    rank = lax.axis_index(axis)
    rows = x.shape[0]
    out = jnp.zeros((ring * rows, w.shape[1]), _out_dtype(x, w))
    held = x
    # step 1's shard is in flight before step 0's matmul issues — the
    # ops/ring.py latency-hiding order
    incoming = ring_shift_chunked(held, axis, chunks=chunks)
    for step in range(ring):
        if step:
            held = incoming
            if step + 1 < ring:
                incoming = ring_shift_chunked(held, axis, chunks=chunks)
        # forward shifts: at step s we hold the shard of rank (rank - s)
        source = (rank - step) % ring
        partial = _partial_matmul(held, w).astype(out.dtype)
        out = lax.dynamic_update_slice(out, partial, (source * rows, 0))
    return out


def _matmul_reducescatter_overlap(axis, chunks, x, w):
    """The dual ring: at step ``t`` every device computes the partial for
    row-block ``(rank - 1 - t) mod n`` and folds it into the running f32
    sum arriving from the previous rank; the sum's forward shift is
    issued *before* the next partial's matmul, so after ``n`` steps
    block ``rank`` lands home having collected all ``n`` contributions
    with the transfers hidden under the matmuls."""
    ring = axis_size(axis)
    rank = lax.axis_index(axis)
    rows = x.shape[0] // ring
    cols = x.shape[1]

    def block(step):
        start = ((rank - 1 - step) % ring) * rows
        return lax.dynamic_slice(x, (start, 0), (rows, cols))

    total = _partial_matmul(block(0), w)
    for step in range(1, ring):
        inflight = ring_shift_chunked(total, axis, chunks=chunks)
        total = inflight + _partial_matmul(block(step), w)
    return total.astype(_out_dtype(x, w))


def _ring_transpose_matmul(axis, chunks, rotating, sliced):
    """``sum_j rotating_j^T @ sliced[j*m:(j+1)*m]`` with ``rotating_j`` =
    rank ``j``'s shard — the weight-gradient ring both custom_vjps share
    (the gathered operand rotates against static row-blocks of the local
    cotangent/input). f32 accumulator, same overlap order as the forward
    rings."""
    ring = axis_size(axis)
    rank = lax.axis_index(axis)
    rows = rotating.shape[0]
    held = rotating
    incoming = ring_shift_chunked(held, axis, chunks=chunks)
    total = jnp.zeros((rotating.shape[1], sliced.shape[1]), jnp.float32)
    for step in range(ring):
        if step:
            held = incoming
            if step + 1 < ring:
                incoming = ring_shift_chunked(held, axis, chunks=chunks)
        source = (rank - step) % ring
        rows_block = lax.dynamic_slice(
            sliced, (source * rows, 0), (rows, sliced.shape[1]))
        total = total + _partial_matmul(held.T, rows_block)
    return total


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _allgather_matmul(axis, chunks, x, w):
    return _allgather_matmul_overlap(axis, chunks, x, w)


def _allgather_matmul_fwd(axis, chunks, x, w):
    return _allgather_matmul_overlap(axis, chunks, x, w), (x, w)


def _allgather_matmul_bwd(axis, chunks, residuals, grad):
    # y = AG(x) @ w: dx is the dual decomposition with swapped operands
    # (an overlapped matmul-reduce-scatter of the cotangent against w^T),
    # dw the shared transpose ring — the backward overlaps like the fwd.
    x, w = residuals
    dx = _matmul_reducescatter_overlap(axis, chunks, grad, w.T).astype(x.dtype)
    dw = _ring_transpose_matmul(axis, chunks, x, grad).astype(w.dtype)
    return dx, dw


_allgather_matmul.defvjp(_allgather_matmul_fwd, _allgather_matmul_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _matmul_reducescatter(axis, chunks, x, w):
    return _matmul_reducescatter_overlap(axis, chunks, x, w)


def _matmul_reducescatter_fwd(axis, chunks, x, w):
    return _matmul_reducescatter_overlap(axis, chunks, x, w), (x, w)


def _matmul_reducescatter_bwd(axis, chunks, residuals, grad):
    # z = RS(x @ w): the reduce-scatter's transpose is the all-gather, so
    # dx is an overlapped all-gather-matmul of the cotangent against w^T;
    # dw is the same transpose ring with the roles swapped.
    x, w = residuals
    dx = _allgather_matmul_overlap(axis, chunks, grad, w.T).astype(x.dtype)
    dw = _ring_transpose_matmul(axis, chunks, grad, x).T.astype(w.dtype)
    return dx, dw


_matmul_reducescatter.defvjp(_matmul_reducescatter_fwd,
                             _matmul_reducescatter_bwd)


def allgather_matmul(x, w, axis: str = MODEL, *, chunks: int = 1):
    """``all_gather(x, axis) @ w`` with the transfer hidden under compute.

    Call inside ``shard_map``. ``x`` is this device's row shard
    ``[rows, k]`` of a ``[ring * rows, k]`` tensor sharded over ``axis``;
    ``w`` is the local ``[k, p]`` column shard of a Megatron up-projection
    (never gathered). Decomposes into ``axis_size`` ring steps — each
    step's partial matmul fills one row-block of the ``[ring * rows, p]``
    result while the next shard's ``ppermute`` is in flight. ``chunks``
    splits each hop's payload into that many independent ``ppermute``\\ s
    (finer interleave for the scheduler; see
    :func:`~tpusystem.parallel.collectives.ring_shift_chunked`).

    Differentiable: the custom_vjp computes ``dx`` as the dual overlapped
    :func:`matmul_reducescatter` of the cotangent against ``w.T`` and
    ``dw`` via the shared transpose ring. Falls back to the one-shot
    ``lax.all_gather`` + matmul when ``axis_size == 1`` or ``chunks``
    cannot tile the shard (see :func:`allgather_plan`).
    """
    plan = allgather_plan(x.shape[0], axis_size(axis), chunks)
    if plan.path == 'one-shot':
        gathered = lax.all_gather(x, axis, axis=0, tiled=True)
        return _partial_matmul(gathered, w).astype(_out_dtype(x, w))
    return _allgather_matmul(axis, plan.chunks, x, w)


def matmul_reducescatter(x, w, axis: str = MODEL, *, chunks: int = 1):
    """``psum_scatter(x @ w, axis)`` with the reduction hidden under compute.

    Call inside ``shard_map``. ``x`` is the local ``[rows, k]`` activation
    against ``w``'s local ``[k, p]`` row shard of a Megatron
    down-projection; the ``[rows, k] @ [k, p]`` partial products are
    summed over the ring and row-block ``r`` of the ``[rows / ring, p]``
    result lands on rank ``r`` (``lax.psum_scatter`` tiled semantics).
    Decomposes into ``axis_size`` chunked partial matmuls whose running
    f32 sum ring-shifts one hop per step toward its owner — each shift is
    issued before the next chunk's matmul, hiding the reduce behind the
    compute.

    Differentiable: ``dx`` is the dual overlapped :func:`allgather_matmul`
    of the cotangent against ``w.T``. Falls back to the one-shot
    matmul + ``lax.psum_scatter`` when ``axis_size == 1`` or ``chunks``
    cannot tile the scatter block (:func:`reducescatter_plan`); rows not
    divisible by the ring raise (no scatter semantics exist).
    """
    plan = reducescatter_plan(x.shape[0], axis_size(axis), chunks)
    if plan.path == 'one-shot':
        # scatter the f32 partial products and cast AFTER: the fallback
        # must keep the module's f32-reduction contract, or a silently
        # non-tiling layer would sum its ring in bf16
        product = _partial_matmul(x, w)
        if axis_size(axis) > 1:
            product = lax.psum_scatter(product, axis, scatter_dimension=0,
                                       tiled=True)
        return product.astype(_out_dtype(x, w))
    return _matmul_reducescatter(axis, plan.chunks, x, w)


# ---------------------------------------------------------------------------
# Model wiring: sequence-sharded FFN behind the ``tp_impl`` knob
# ---------------------------------------------------------------------------


def _define_dense_params():
    """Build the :class:`DenseParams` flax module on first access (PEP 562
    ``__getattr__`` below): the core collectives in this module are
    jax-only, and eagerly importing flax here would put it on the import
    path of every ``tpusystem.parallel`` consumer (multihost tooling,
    mesh utilities) that never touches a model."""
    from flax import linen as nn

    class DenseParams(nn.Module):
        """Bare ``kernel``/``bias`` params under the module's scope —
        exactly what ``nn.Dense`` would create (same paths, shapes,
        initializers), but retrievable so the overlap path can run the
        matmul through the decomposed collectives. A model may init
        through ``nn.Dense`` and apply through this holder (or vice
        versa): the param trees are identical, so ``tp_impl`` never
        changes a checkpoint."""

        features: int
        use_bias: bool = True

        @nn.compact
        def __call__(self, in_features: int):
            kernel = self.param('kernel', nn.initializers.lecun_normal(),
                                (in_features, self.features))
            if not self.use_bias:
                return kernel, None
            bias = self.param('bias', nn.initializers.zeros,
                              (self.features,))
            return kernel, bias

    return DenseParams


def __getattr__(name: str):
    if name == 'DenseParams':
        cls = _define_dense_params()
        globals()['DenseParams'] = cls
        return cls
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


def overlap_applicable(mesh, hidden_shape, grown_features: int,
                       axis: str = MODEL) -> bool:
    """Can the overlap FFN shard ``[batch, seq, dim]`` activations with
    the hidden dim split over ``axis``? Falls back to the GSPMD path when
    the mesh is absent, the TP axis is trivial, the sequence cannot shard
    over ``(seq, model)`` rows, or the FFN width cannot split."""
    if mesh is None:
        return False
    sizes = dict(mesh.shape)
    ring = sizes.get(axis, 1)
    if ring <= 1:
        return False
    _, seq, _ = hidden_shape
    row_split = ring * sizes.get(SEQ, 1)
    return seq % row_split == 0 and grown_features % ring == 0


def _row_specs(mesh, batch: int, axis: str):
    """Activation spec [batch, seq, dim]: batch over (data, fsdp) when it
    divides (replicated for e.g. ``module.init``'s batch-1 trace — the
    ring.py convention), sequence rows over (seq, model). Mentions only
    axes the mesh actually has, so plain ``jax.sharding.Mesh`` layouts
    (not built by ``MeshSpec``) work too."""
    sizes = dict(mesh.shape)
    data_axes = tuple(name for name in (DATA, FSDP) if name in sizes)
    data_parallel = math.prod(sizes[name] for name in data_axes)
    batch_axes = (data_axes if data_axes and batch % data_parallel == 0
                  else None)
    row_axes = tuple(name for name in (SEQ, axis) if name in sizes)
    return P(batch_axes, row_axes or None, None)


def tp_ffn(x, kernel_up, bias_up, kernel_down, bias_down, mesh, *,
           activation=jax.nn.gelu, axis: str = MODEL, chunks: int = 1):
    """Sequence-sharded Megatron FFN with decomposed collectives.

    ``x`` is the global ``[batch, seq, dim]`` activation; the up kernel
    ``[dim, grown]`` splits columns on ``axis``, the down kernel
    ``[grown, dim]`` rows (the models' standard partition rules, so jit
    inserts no weight resharding). Inside ``shard_map`` the sequence rows
    all-gather *into* the up matmul, the activation applies on the
    gathered rows, and the down matmul reduce-scatters rows back —
    both collectives overlapped. Output is ``[batch, seq, dim]`` sharded
    like the input.

    Since the unified scheduler landed this is the TP-only special case
    of :func:`tpusystem.parallel.schedule.scheduled_ffn` (kept as the
    stable two-knob API; the delegation is exact — same specs, same
    body, same numerics).
    """
    from tpusystem.parallel.schedule import OverlapSchedule, scheduled_ffn
    return scheduled_ffn(x, kernel_up, bias_up, kernel_down, bias_down,
                         mesh, schedule=OverlapSchedule(tp='overlap',
                                                        chunks=chunks),
                         activation=activation, axis=axis)


def tp_swiglu(x, kernel_gate, kernel_up, kernel_down, mesh, *,
              axis: str = MODEL, chunks: int = 1):
    """Sequence-sharded SwiGLU FFN (Llama) with decomposed collectives.

    The gate and up projections share one all-gather: their column shards
    concatenate into a single ``[dim, 2 * grown]`` right operand, so the
    sequence rows ride the ring ONCE for both matmuls. No biases (Llama
    convention).

    Since the unified scheduler landed this is the TP-only special case
    of :func:`tpusystem.parallel.schedule.scheduled_swiglu` (kept as the
    stable two-knob API; the delegation is exact).
    """
    from tpusystem.parallel.schedule import OverlapSchedule, scheduled_swiglu
    return scheduled_swiglu(x, kernel_gate, kernel_up, kernel_down, mesh,
                            schedule=OverlapSchedule(tp='overlap',
                                                     chunks=chunks),
                            axis=axis)
