"""Multi-host control plane: the event buses, across TPU-VM workers.

The reference's Producer/Consumer and Publisher/Subscriber buses are
in-process method calls (``torchsystem/services/prodcon.py:209-218``,
``torchsystem/services/pubsub.py:206-215``) — the degenerate single-host
case. On a pod each host runs its own Python process; domain events raised
on one worker (metrics, Trained/Validated, stop requests) must reach
consumers anywhere, and stop decisions must be *collectively agreed* or
hosts deadlock in XLA collectives (SURVEY.md §7.3 "events across hosts").

Two planes, by design:

- **data plane** — tensors move via XLA collectives over ICI/DCN, inserted
  by GSPMD from sharding annotations (:mod:`tpusystem.parallel.sharding`).
  This module never touches device arrays.
- **control plane** (this module) — small, host-side, already-materialized
  Python values move over a TCP star: the primary host runs a :class:`Hub`
  router; every host (primary included) attaches a :class:`TcpTransport`
  client. The same API degrades to :class:`Loopback` in one process, so
  training code is identical on a laptop and on a pod.

Capabilities:

- :class:`DistributedProducer` / :class:`DistributedPublisher` — drop-in
  supersets of the in-process buses. Events whose types are ``wire()``-d are
  forwarded to every other host; consumers may be registered
  ``primary_only`` so storage/TensorBoard run exactly once per experiment
  (SURVEY.md §5 "only rank-0 runs storage/TB consumers").
- :func:`agree` — boolean all-reduce over hosts: the early-stop commit
  point. One host's ``StopTraining`` becomes everyone's.
- heartbeat failure detection — the hub tracks per-host liveness and
  broadcasts :class:`WorkerLost` as a *domain event* when a host goes
  silent, so recovery policy is just another consumer (SURVEY.md §5
  "failure detection").

Transport frames are length-prefixed pickles on a trusted cluster network
(the same trust model as NCCL/MPI bootstrap); event payloads must be plain
host values — never device arrays.
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import pickle
import queue
import socket
import struct
import threading
import time
from collections.abc import Callable
from dataclasses import dataclass
from typing import Any

from tpusystem.services.prodcon import Consumer, Producer, event
from tpusystem.services.pubsub import Publisher, Subscriber

logger = logging.getLogger('tpusystem.multihost')

# ---------------------------------------------------------------------------
# world


@dataclass(frozen=True)
class World:
    """Host-level topology facts (not chips — processes)."""
    process_index: int
    process_count: int

    @property
    def is_primary(self) -> bool:
        return self.process_index == 0


def world() -> World:
    """The JAX runtime's view of the multi-host job (1 process off-pod)."""
    import jax
    return World(jax.process_index(), jax.process_count())


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> World:
    """Join the multi-host job (wraps ``jax.distributed.initialize``).

    No-op when the job is single-process and no coordinator is given, so the
    same ``main()`` runs unchanged off-pod.
    """
    import jax
    if coordinator_address is not None or (num_processes or 1) > 1:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    return world()


# ---------------------------------------------------------------------------
# control-plane events


@event
class WorkerLost:
    """A host left the pod; consumers decide the recovery policy
    (checkpoint-restore restart, mesh re-init, abort).

    ``reason`` records *how* the loss was detected: ``'socket'`` — the
    connection died without a ``bye`` (crash/SIGKILL, seen immediately) —
    vs ``'heartbeat'`` — the host went silent past the liveness timeout
    (alive-but-wedged: GC pause, hung NFS, a stuck collective). The two
    have different MTTR profiles (a stall eats the whole timeout before
    recovery starts), so the ledger and recovery timeline distinguish
    them."""
    rank: int
    last_seen: float
    reason: str = 'socket'


@event
class WorkerJoined:
    """A host attached to the control plane."""
    rank: int


# ---------------------------------------------------------------------------
# wire format

_LEN = struct.Struct('>Q')


def _send_frame(sock: socket.socket, payload: tuple) -> None:
    blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    chunks = []
    while n:
        chunk = sock.recv(n)
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b''.join(chunks)


def _recv_frame(sock: socket.socket) -> tuple | None:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    blob = _recv_exact(sock, _LEN.unpack(header)[0])
    return None if blob is None else pickle.loads(blob)


# client-local sentinel: a 'rejected' frame resolves the waiting collective
_REJECTED = object()
# client-local sentinel: the active hub died mid-collective (failover)
_FAILED_OVER = object()
# client-local sentinels for fetch_blob: peer has no such blob / the
# reassembled bytes failed their digest (a chunk was truncated in flight) /
# the transport died or failed over with the fetch in flight
_BLOB_NAK = object()
_BLOB_CORRUPT = object()
_BLOB_DEAD = object()

# bound on a single blob frame's payload: large transfers (hot TrainState
# replicas) are chunked so one blob cannot monopolize the control-plane
# socket — heartbeats and collective frames interleave between chunks
BLOB_CHUNK = 1 << 20


def _blob_digest(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=16).hexdigest()


class BlobError(RuntimeError):
    """A point-to-point blob transfer failed (peer had no such blob, a
    chunk was lost/truncated in flight, or the wait timed out). Blobs are
    a best-effort sidecar of the control plane — the caller decides the
    fallback (for hot state: restore from disk)."""


class ControlPlaneFailover(RuntimeError):
    """The active hub died while this collective was in flight.

    The result may have reached some ranks and not others, so the op
    cannot be transparently retried — surface the failover and let the
    caller resynchronize at a safe point (epoch boundary / checkpoint
    restore). Collectives issued *after* the failover complete normally
    on the promoted deputy."""


class CollectiveTimeout(ControlPlaneFailover):
    """A host collective did not complete within its timeout.

    The usual cause is a peer that died or hung *between* sync points —
    alive enough that the hub has not excluded it, but never contributing
    its share — so the waiter would otherwise block forever. Raised typed
    (instead of the raw ``queue.Empty`` it used to surface as) so callers
    can checkpoint-fence and restart like any other control-plane loss.
    """

_REDUCERS: dict[str, Callable[[list], Any]] = {
    'and': all,
    'or': any,
    'sum': sum,
    'min': min,
    'max': max,
}


# ---------------------------------------------------------------------------
# hub (runs on the primary host)


class Hub:
    """Star-topology router for the control plane.

    Pure router: every host (the primary included) attaches a
    :class:`TcpTransport` client, so client logic is rank-uniform. The hub
    forwards ``event`` frames to every *other* client, completes collective
    ops (``reduce``/``gather``/``barrier``) once all ranks contribute, and
    monitors heartbeats.
    """

    def __init__(self, size: int, host: str = '127.0.0.1', port: int = 0,
                 heartbeat_timeout: float | None = None,
                 standby_of: tuple | None = None):
        self.size = size
        self.heartbeat_timeout = heartbeat_timeout
        self._server = socket.create_server((host, port))
        self.address = self._server.getsockname()
        self._clients: dict[int, socket.socket] = {}
        self._locks = threading.Lock()
        # one send lock per client: hub threads (client loops routing blob
        # chunks, the monitor's lost fanout, the accept loop's joined
        # fanout) write concurrently to the same sockets, and a 1 MiB blob
        # chunk's sendall can interleave mid-frame with another thread's
        # frame — a torn length-prefixed stream desyncs the client for
        # good. Small frames rode single send() calls; blobs ended that.
        self._send_locks: dict[int, threading.Lock] = {}
        self._pending: dict[tuple, list] = {}
        self._last_seen: dict[int, float] = {}
        self._lost: set[int] = set()
        # ranks out of the collective quota for good (crash, timeout, bye);
        # see _live() for why this set only grows
        self._excluded: set[int] = set()
        self._closed = threading.Event()
        # Deputy mode: while the primary hub (at ``standby_of``) is alive,
        # answer every contribution with ('standby',) so a client whose
        # *link* to the primary flaked cannot split the pod — it is told to
        # go back. When the hub-to-hub peer link dies, promote and serve.
        self._standby = threading.Event()
        self._peers: list[socket.socket] = []   # standby deputies' links
        if standby_of is not None:
            self._standby.set()
        self._threads = [threading.Thread(target=self._accept_loop, daemon=True)]
        if standby_of is not None:
            self._threads.append(threading.Thread(
                target=self._peer_monitor, args=(standby_of,), daemon=True))
        if heartbeat_timeout:
            self._threads.append(
                threading.Thread(target=self._monitor_loop, daemon=True))
        for thread in self._threads:
            thread.start()

    @property
    def is_standby(self) -> bool:
        return self._standby.is_set()

    def _peer_monitor(self, primary_address: tuple) -> None:
        """Hold a hub-to-hub link to the primary; promote when it dies.

        A broken link is confirmed by redial before promoting: a transient
        blip on the peer socket alone must not create two active hubs
        (split brain). Only when the primary is unreachable afresh does the
        deputy take over."""

        def dial(deadline: float):
            while not self._closed.is_set() and time.monotonic() < deadline:
                try:
                    sock = socket.create_connection(primary_address,
                                                    timeout=5.0)
                    sock.settimeout(None)
                    _send_frame(sock, ('peer',))
                    return sock
                except OSError:
                    time.sleep(0.1)
            return None

        # bootstrap: generous window for the primary to come up at all
        sock = dial(time.monotonic() + 60.0)
        while sock is not None and not self._closed.is_set():
            try:
                while not self._closed.is_set():
                    if _recv_frame(sock) is None:
                        break
            except OSError:
                pass
            finally:
                sock.close()
            # link died: confirm by redial (short window) before promoting
            sock = dial(time.monotonic() + 3.0)
        if not self._closed.is_set():
            # The primary's exclusion state died with it. Seed liveness for
            # every rank not yet connected here: ranks lost BEFORE the
            # failover never dial in, the heartbeat monitor marks them
            # stale, and collectives degrade to the survivors instead of
            # deadlocking on ghosts. (Without heartbeats there is no
            # failure detection at all — same contract as the primary.)
            with self._locks:
                now = time.monotonic()
                for rank in range(self.size):
                    if rank not in self._clients:
                        self._last_seen.setdefault(rank, now)
            self._standby.clear()       # promote
            self._complete_satisfied()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                sock, _ = self._server.accept()
            except OSError:
                return
            try:
                frame = _recv_frame(sock)
            except OSError:
                continue
            if frame and frame[0] == 'peer':
                # a standby deputy monitoring this hub: keep the socket open
                # (its death is how the deputy learns we died); nothing to read
                with self._locks:
                    self._peers.append(sock)
                threading.Thread(target=self._peer_hold, args=(sock,),
                                 daemon=True).start()
                continue
            if not frame or frame[0] != 'hello':
                sock.close()
                continue
            rank = frame[1]
            with self._locks:
                self._clients[rank] = sock
                self._send_locks.setdefault(rank, threading.Lock())
                self._last_seen[rank] = time.monotonic()
                self._lost.discard(rank)     # a rejoining worker is alive
                # NOT removed from _excluded: see _live()
            self._fanout(('joined', rank), exclude=rank)
            threading.Thread(target=self._client_loop, args=(rank, sock),
                             daemon=True).start()

    def _peer_hold(self, sock: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                if _recv_frame(sock) is None:
                    return
        except OSError:
            pass
        finally:
            sock.close()

    def _client_loop(self, rank: int, sock: socket.socket) -> None:
        while not self._closed.is_set():
            try:
                frame = _recv_frame(sock)
            except OSError:
                frame = None
            if frame is None or frame[0] == 'bye':
                # 'bye' is a graceful detach; a dead connection (None) means
                # the worker crashed — report it as lost immediately rather
                # than waiting for the heartbeat monitor (which could never
                # fire: the rank leaves the liveness table here).
                # A STANDBY deputy never judges: clients touch it only
                # transiently (failover probing before promotion, bounced
                # flakes), and excluding them here would lock healthy ranks
                # out of the quota for the deputy's whole post-promotion life.
                standby = self._standby.is_set()
                with self._locks:
                    self._clients.pop(rank, None)
                    last_seen = self._last_seen.pop(rank, time.monotonic())
                    crashed = (not standby and frame is None
                               and rank not in self._lost
                               and not self._closed.is_set())
                    if crashed:
                        self._lost.add(rank)
                    if not standby:
                        self._excluded.add(rank)
                sock.close()
                if crashed:
                    self._fanout(('lost', rank, last_seen, 'socket'))
                # either way the rank can no longer contribute: complete
                # collectives that were only waiting on it
                self._complete_satisfied()
                return
            with self._locks:
                self._last_seen[rank] = time.monotonic()
                self._lost.discard(rank)     # any frame proves recovery
            kind = frame[0]
            if kind == 'hb':
                continue
            if self._standby.is_set() and kind in ('event', 'reduce', 'gather',
                                                   'blob', 'blob-req',
                                                   'blob-nak'):
                # not the active hub: tell the client to go back to the
                # primary (its link may have flaked while the primary lives)
                self._send_to(rank, sock, ('standby',))
                continue
            if kind == 'event':
                self._fanout(frame, exclude=rank)
            elif kind in ('blob', 'blob-req', 'blob-nak'):
                # point-to-point: route to the addressee only, rewriting the
                # 'to' slot into 'from' so the receiver can answer. Blobs are
                # best-effort (the sidecar of the control plane): an absent
                # addressee just drops the frame — the requester's timeout
                # (or the replica's previous copy) is the fallback.
                to = frame[1]
                with self._locks:
                    target = self._clients.get(to)
                if target is not None:
                    self._send_to(to, target, (kind, rank) + frame[2:])
            elif kind in ('reduce', 'gather'):
                _, op_key, value = frame
                with self._locks:
                    if rank in self._excluded:
                        # a rank outside the quota (crashed-then-revived or
                        # restarted) must not resurrect completed op_keys or
                        # skew live ranks' sequence numbers: reject it
                        # explicitly. (Its op counter restarted at 0, so its
                        # op_key can never line up with the survivors' —
                        # without the reject it would block until timeout.)
                        excluded = True
                    else:
                        excluded = False
                        values = self._pending.setdefault(op_key, {})
                        values[rank] = value
                        done = self._live() <= values.keys()
                        if done:
                            del self._pending[op_key]
                if excluded:
                    self._send_to(rank, sock, ('rejected', op_key))
                    continue
                if done:
                    self._emit_result(op_key, values)

    def _monitor_loop(self) -> None:
        while not self._closed.wait(self.heartbeat_timeout / 4):
            now = time.monotonic()
            with self._locks:
                stale = [(rank, seen) for rank, seen in self._last_seen.items()
                         if now - seen > self.heartbeat_timeout
                         and rank not in self._lost]
                if self._standby.is_set():
                    # a STANDBY deputy never judges (same guard as
                    # _client_loop): clients touch it only transiently and
                    # send no heartbeats here, so a stale entry means a
                    # flaked probe, not a dead rank — drop the socket and
                    # liveness entry instead of excluding a healthy rank
                    # from the quota for the deputy's post-promotion life
                    for rank, _ in stale:
                        sock = self._clients.pop(rank, None)
                        self._last_seen.pop(rank, None)
                        if sock is not None:
                            sock.close()
                    continue
                self._lost.update(rank for rank, _ in stale)
                self._excluded.update(rank for rank, _ in stale)
            for rank, seen in stale:
                self._fanout(('lost', rank, seen, 'heartbeat'))
            if stale:
                self._complete_satisfied()

    def _send_to(self, rank: int, sock: socket.socket, frame: tuple) -> None:
        """Serialize whole frames per client socket (see ``_send_locks``);
        a dead link is the receiver's problem, not the sender thread's."""
        with self._locks:
            lock = self._send_locks.setdefault(rank, threading.Lock())
        with lock:
            try:
                _send_frame(sock, frame)
            except OSError:
                pass

    def _live(self) -> set[int]:
        """Ranks a collective must wait for. The quota only ever shrinks:
        losing a host degrades collectives to the survivors (what lets the
        'observe' recovery policy keep agreeing stops instead of
        deadlocking), and a rank that left — crash, heartbeat timeout, or
        graceful 'bye' — never counts again for this Hub's lifetime (a
        restarted worker's op counters restart at 0, so its contributions
        cannot line up with the survivors'; full re-admission is the
        restart-resume cycle, :mod:`tpusystem.parallel.recovery`). It still
        receives events and control frames, but NOT collective results: its
        own collective calls fail fast with a 'rejected' frame (silently
        consuming survivor results while its own contributions are dropped
        would let it believe it participated). Caller holds the lock."""
        return set(range(self.size)) - self._excluded

    def readmit(self, rank: int) -> None:
        """Return a previously excluded rank to the collective quota — the
        elastic grow's re-admission step (:mod:`tpusystem.parallel.
        elastic`).

        The quota normally only shrinks (see :meth:`_live`): a restarted
        worker's op counters restart at 0 and can never line up with the
        survivors' mid-stream. Re-admission is therefore only sound at a
        *membership-epoch boundary*, when EVERY rank restarts its
        counters together — exactly what the resize relaunch guarantees
        (all workers re-exec under the new world spec). Call it on the
        hub when the epoch commits folding ``rank`` back in; calling it
        into a live, counting pod would desync collective keys."""
        with self._locks:
            self._excluded.discard(rank)
            self._lost.discard(rank)

    def _emit_result(self, op_key: tuple, values: dict[int, Any]) -> None:
        # include every contribution received for this op — a rank that
        # voted and then died still voted
        contributions = [values[rank] for rank in sorted(values)]
        kind_name, op, _ = op_key
        result = (_REDUCERS[op](contributions) if kind_name == 'reduce'
                  else contributions)
        # live_only: an excluded-but-connected rank (heartbeat stall whose op
        # counter still lines up) must not race a 'result' against its
        # 'rejected' — its collectives deterministically fail fast
        self._fanout(('result', op_key, result), live_only=True)

    def _complete_satisfied(self) -> None:
        """After a loss, pending collectives that were only waiting on the
        departed rank complete with the contributions already received."""
        with self._locks:
            live = self._live()
            ready = [(op_key, values)
                     for op_key, values in self._pending.items()
                     if live <= values.keys()]
            for op_key, _ in ready:
                del self._pending[op_key]
        for op_key, values in ready:
            self._emit_result(op_key, values)

    def _fanout(self, frame: tuple, exclude: int | None = None,
                live_only: bool = False) -> None:
        with self._locks:
            targets = [(rank, sock) for rank, sock in self._clients.items()
                       if rank != exclude
                       and not (live_only and rank in self._excluded)]
        for rank, sock in targets:
            self._send_to(rank, sock, frame)

    def close(self) -> None:
        self._closed.set()
        self._server.close()
        with self._locks:
            # shutdown before close: close() alone does not send FIN while
            # another thread blocks in recv on the same fd, so clients (and
            # standby deputies) would never learn this hub died
            for sock in list(self._clients.values()) + self._peers:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                sock.close()
            self._clients.clear()
            self._peers.clear()


# ---------------------------------------------------------------------------
# transports


class Loopback:
    """Single-process control plane: collectives are identities, nothing is
    forwarded. Keeps one code path from laptop to pod."""

    rank = 0
    size = 1

    def __init__(self) -> None:
        self._channels: dict[str, Callable[[Any], None]] = {}
        self.on_control: Callable[[tuple], None] | None = None
        self.on_blob: Callable[[int, str, bytes], None] | None = None
        self.on_blob_request: Callable[[str], bytes | None] | None = None

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        """Register the receiver for one named event channel (each bus owns
        its own channel, so several buses share one transport)."""
        self._channels[channel] = callback

    def send_event(self, channel: str, message: Any) -> None:
        pass

    def send_blob(self, to: int, key: str, data: bytes,
                  chunk_size: int = BLOB_CHUNK) -> None:
        if self.on_blob is not None:
            self.on_blob(0, key, bytes(data))

    def fetch_blob(self, peer: int, key: str, timeout: float = 30.0) -> bytes:
        data = (self.on_blob_request(key)
                if self.on_blob_request is not None else None)
        if data is None:
            raise BlobError(f'no blob {key!r} on the loopback transport')
        return bytes(data)

    def allreduce(self, value: Any, op: str = 'and') -> Any:
        return _REDUCERS[op]([value])

    def gather(self, value: Any) -> list:
        return [value]

    def barrier(self, timeout: float = 300.0) -> None:
        pass

    def heartbeat(self) -> None:
        pass

    def close(self) -> None:
        pass


class TcpTransport:
    """Per-host client of the :class:`Hub`.

    ``send_event`` forwards a pickled message to every other host (delivered
    to their ``on_event``); ``allreduce``/``gather``/``barrier`` are
    collective over all hosts and must be called in the same order on every
    rank (SPMD control flow — the same discipline XLA collectives require).
    """

    def __init__(self, address, rank: int, size: int,
                 heartbeat_interval: float | None = None,
                 connect_timeout: float = 60.0):
        self.rank = rank
        self.size = size
        # one address, or an ordered [primary, deputy, ...] failover list
        self._addresses = ([tuple(a) for a in address]
                           if isinstance(address, list) else [tuple(address)])
        self._active = 0
        self._channels: dict[str, Callable[[Any], None]] = {}
        self.on_control: Callable[[tuple], None] | None = None
        self._send_lock = threading.Lock()
        self._results: dict[tuple, queue.Queue] = {}
        self._results_lock = threading.Lock()
        # unanswered collective frames, replayed after a 'standby' redirect
        # (the deputy deterministically dropped them); abandoned with
        # _FAILED_OVER when the active hub died (delivery state unknown)
        self._pending_sends: dict[tuple, tuple] = {}
        self._counter = itertools.count()
        # point-to-point blob plane (chunked, digest-verified): in-flight
        # reassemblies, completed unclaimed blobs, and fetch_blob waiters
        self.blob_chunk = BLOB_CHUNK   # per-frame payload bound
        self._blob_lock = threading.Lock()
        self._blob_parts: dict[tuple, dict] = {}
        self._blob_ready: dict[str, tuple[int, bytes]] = {}
        # fetch waiters are keyed by blob key but pinned to the peer the
        # request went to: a same-key blob arriving from anyone else (e.g.
        # the buddy's own concurrent push) must not satisfy the fetch.
        # The request frame rides along so a standby bounce / redial can
        # replay it (a deputy deterministically drops blob-reqs).
        self._blob_waiters: dict[str, tuple[int, queue.Queue, tuple]] = {}
        self.on_blob: Callable[[int, str, bytes], None] | None = None
        self.on_blob_request: Callable[[str], bytes | None] | None = None
        self._closed = threading.Event()
        self._reconnected = threading.Event()
        self._dead = False       # set when every failover avenue is spent
        self._sock = self._dial(self._addresses[0], connect_timeout)
        self._reconnected.set()
        self._threads = [threading.Thread(target=self._recv_loop, daemon=True)]
        if heartbeat_interval:
            self._threads.append(threading.Thread(
                target=self._heartbeat_loop, args=(heartbeat_interval,),
                daemon=True))
        for thread in self._threads:
            thread.start()

    def _dial(self, address: tuple, connect_timeout: float) -> socket.socket:
        # Hosts of a pod start concurrently; the hub may not be listening
        # yet when a non-primary dials in — bounded retry with backoff.
        deadline = time.monotonic() + connect_timeout
        delay = 0.05
        while True:
            try:
                sock = socket.create_connection(address, timeout=5.0)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
        sock.settimeout(None)
        _send_frame(sock, ('hello', self.rank))
        return sock

    def _send(self, frame: tuple, op_key: tuple | None = None) -> None:
        # a send racing a failover retries while the recv loop replaces
        # self._sock in the background — EXCEPT when the op it belongs to
        # was abandoned by that failover: delivering a pre-failover
        # collective frame to the promoted deputy would plant an op_key no
        # other rank will ever complete
        deadline = time.monotonic() + 60.0
        while True:
            try:
                with self._send_lock:
                    if op_key is not None:
                        with self._results_lock:
                            abandoned = op_key not in self._pending_sends
                        if abandoned:
                            raise ControlPlaneFailover(
                                f'rank {self.rank}: collective abandoned '
                                f'by a control-plane failover before send')
                    _send_frame(self._sock, frame)
                return
            except OSError:
                if (self._closed.is_set() or self._dead
                        or time.monotonic() >= deadline):
                    raise
                self._reconnected.wait(timeout=0.5)
                if self._dead:
                    raise

    def _recv_loop(self) -> None:
        while not self._closed.is_set():
            try:
                frame = _recv_frame(self._sock)
            except OSError:
                frame = None
            if frame is None:
                if self._closed.is_set():
                    # the transport was closed with collectives still in
                    # flight (pod teardown races a waiting thread): fail
                    # them typed and fast, never leave a waiter to ride
                    # out its full collective timeout into a raw
                    # queue.Empty (regression: test_chaos.py::TestClose)
                    self._abandon()
                    return
                if not self._failover():
                    self._abandon()
                    return
                continue
            if frame[0] == 'standby':
                # we dialed a standby deputy while the primary lives (our
                # link flaked, not the primary): go back to the primary and
                # replay the frames the deputy deterministically dropped
                if self._redial(0, replay=True, connect_timeout=5.0):
                    continue
                # primary unreachable after all: the deputy will promote —
                # return to it (the 0.2s same-index pause gives it time)
                if self._redial(self._active, replay=True):
                    continue
                self._abandon()
                return
            kind = frame[0]
            if kind == 'event':
                callback = self._channels.get(frame[1])
                if callback is not None:
                    callback(frame[2])
            elif kind == 'result':
                _, op_key, result = frame
                # deliver only to a registered box (always present for own
                # ops — registered before send); a result landing after the
                # waiter timed out (CollectiveTimeout popped its box) must
                # not leak a fresh never-read queue into _results
                with self._results_lock:
                    box = self._results.get(op_key)
                if box is not None:
                    box.put(result)
            elif kind == 'rejected':
                # the hub excluded this rank from the quota; fail the
                # waiting call fast instead of letting it hit its timeout.
                # Deliver only to a registered box (always present for own
                # ops — registered before send); a stray late frame must
                # not leak a fresh queue into _results.
                with self._results_lock:
                    box = self._results.get(frame[1])
                if box is not None:
                    box.put(_REJECTED)
            elif kind == 'blob':
                _, sender, key, index, total, digest, chunk = frame
                self._blob_accept(sender, key, index, total, digest, chunk)
            elif kind == 'blob-req':
                _, sender, key = frame
                self._answer_blob_request(sender, key)
            elif kind == 'blob-nak':
                with self._blob_lock:
                    waiter = self._blob_waiters.get(frame[2])
                if waiter is not None and waiter[0] == frame[1]:
                    waiter[1].put(_BLOB_NAK)
            elif kind in ('lost', 'joined'):
                if self.on_control is not None:
                    self.on_control(frame)

    def _failover(self) -> bool:
        """The active hub died: fail in-flight collectives (their delivery
        state is unknowable — see :class:`ControlPlaneFailover`) and switch
        to the next address in the failover list."""
        with self._results_lock:
            self._pending_sends.clear()
            boxes = list(self._results.values())
        for box in boxes:
            box.put(_FAILED_OVER)
        self._fail_blob_waiters()
        if len(self._addresses) == 1:
            return False
        return self._redial((self._active + 1) % len(self._addresses),
                            replay=False)

    def _abandon(self) -> None:
        """Every failover avenue is spent: fail anything waiting (typed —
        callers see ControlPlaneFailover, not a raw timeout) and make
        future sends raise immediately instead of retrying a dead link."""
        self._dead = True
        with self._results_lock:
            self._pending_sends.clear()
            boxes = list(self._results.values())
        for box in boxes:
            box.put(_FAILED_OVER)
        self._fail_blob_waiters()

    def _fail_blob_waiters(self) -> None:
        """Fail in-flight blob fetches typed and fast when the transport
        dies or fails over — the same no-hang-to-timeout discipline the
        collective waiters get (their delivery state is unknowable)."""
        with self._blob_lock:
            waiters = list(self._blob_waiters.values())
        for waiter in waiters:
            waiter[1].put(_BLOB_DEAD)

    def _redial(self, index: int, *, replay: bool,
                connect_timeout: float = 30.0) -> bool:
        self._reconnected.clear()
        try:
            self._sock.close()
        except OSError:
            pass
        if index == self._active:     # redialing the same address: brief
            time.sleep(0.2)           # pause so a dead hub cannot hot-loop
        try:
            sock = self._dial(self._addresses[index],
                              connect_timeout=connect_timeout)
        except OSError:
            return False
        with self._send_lock:
            self._sock = sock
        self._active = index
        self._reconnected.set()
        if replay:
            with self._results_lock:
                pending = list(self._pending_sends.values())
            # in-flight blob requests too: a standby deputy deterministically
            # dropped them, and without a replay the fetch would ride out
            # its full timeout against a healthy primary
            with self._blob_lock:
                pending += [waiter[2] for waiter in self._blob_waiters.values()]
            for frame in pending:
                try:
                    self._send(frame)
                except OSError:
                    return False
        return True

    def _heartbeat_loop(self, interval: float) -> None:
        while not self._closed.wait(interval):
            try:
                self._send(('hb',))
            except OSError:
                continue   # a failover may be in progress; retry next beat

    def _collective(self, kind: str, op: str, value: Any, timeout: float) -> Any:
        # Same call order on every rank => the same per-kind sequence number
        # identifies the same collective everywhere.
        op_key = (kind, op, next(self._counter))
        frame = (kind, op_key, value)
        with self._results_lock:
            box = self._results.setdefault(op_key, queue.Queue())
            self._pending_sends[op_key] = frame
        try:
            self._send(frame, op_key=op_key)
            try:
                result = box.get(timeout=timeout)
            except queue.Empty:
                raise CollectiveTimeout(
                    f'rank {self.rank}: {kind} collective timed out after '
                    f'{timeout:.0f}s — a peer likely died or stalled between '
                    f'sync points; checkpoint-fence and restart, or '
                    f'resynchronize at a safe point') from None
        finally:
            # timeouts and send failures must not leak the box or leave a
            # stale frame eligible for a later redial replay
            with self._results_lock:
                self._results.pop(op_key, None)
                self._pending_sends.pop(op_key, None)
        if result is _FAILED_OVER:
            if self._closed.is_set():
                raise ControlPlaneFailover(
                    f'rank {self.rank}: transport closed while this '
                    f'collective was in flight')
            raise ControlPlaneFailover(
                f'rank {self.rank}: the active hub died while this '
                f'collective was in flight; resynchronize at a safe point '
                f'(collectives after the failover complete on the deputy)')
        if result is _REJECTED:
            raise RuntimeError(
                f'rank {self.rank} is excluded from collectives (it crashed, '
                'timed out, or restarted); re-admission is the restart-resume '
                'cycle — see tpusystem.parallel.recovery')
        return result

    # ------------------------------------------------------------------
    # blob plane: chunked, digest-verified point-to-point byte transfers.
    # The control plane's collectives and events carry small host values;
    # blobs carry the occasional BIG one — a serialized hot TrainState
    # replica shipped between supervisors (tpusystem.parallel.supervisor),
    # or a serving replica's request journal ('journal:{identity}' —
    # tpusystem.serve.failover) that the fleet router pulls back through
    # the buddy chain to re-home a dead replica's rows onto survivors
    # (tpusystem.serve.fleet). Bounded frames (BLOB_CHUNK) keep
    # heartbeats and collective traffic interleaving with a transfer; the
    # whole-blob digest makes any lost, truncated, or
    # reordered-into-oblivion chunk a *detected* failure.

    def send_blob(self, to: int, key: str, data: bytes,
                  chunk_size: int | None = None) -> None:
        """Ship ``data`` to rank ``to`` under ``key`` (fire-and-forget).

        The receiver reassembles and digest-verifies; a corrupt or
        incomplete transfer is discarded there (logged), never delivered —
        best-effort by design: the replication rider keeps its previous
        copy, a fetcher times out and falls back. ``chunk_size`` defaults
        to the transport's ``blob_chunk`` bound.
        """
        chunk_size = chunk_size or self.blob_chunk
        data = bytes(data)
        digest = _blob_digest(data)
        total = max(1, -(-len(data) // chunk_size))
        for index in range(total):
            chunk = data[index * chunk_size:(index + 1) * chunk_size]
            self._send(('blob', to, key, index, total, digest, chunk))

    def fetch_blob(self, peer: int, key: str, timeout: float = 30.0) -> bytes:
        """Request blob ``key`` from rank ``peer`` and wait for it.

        The peer answers from its ``on_blob_request`` hook (or NAKs when
        it has nothing). Raises :class:`BlobError` on NAK, digest
        mismatch, or timeout — callers treat all three as "no hot copy"
        and fall back (for checkpoints: to disk).
        """
        with self._blob_lock:
            ready = self._blob_ready.pop(key, None)
            if ready is not None and ready[0] != peer:
                # a same-key blob someone ELSE pushed is not this answer
                self._blob_ready[key] = ready
                ready = None
            if ready is None:
                if key in self._blob_waiters:
                    # one waiter registration per key: a second concurrent
                    # fetch would clobber the first's (and its finally
                    # would then strand the second) — refuse typed instead
                    raise BlobError(
                        f'rank {self.rank}: a fetch for blob {key!r} is '
                        f'already in flight on this transport')
                box = queue.Queue()
                self._blob_waiters[key] = (peer, box,
                                           ('blob-req', peer, key))
        if ready is not None:
            return ready[1]
        try:
            try:
                self._send(('blob-req', peer, key))
            except OSError as error:
                raise BlobError(
                    f'rank {self.rank}: could not request blob {key!r} '
                    f'from rank {peer}: {error}') from error
            try:
                result = box.get(timeout=timeout)
            except queue.Empty:
                raise BlobError(
                    f'rank {self.rank}: blob {key!r} from rank {peer} did '
                    f'not arrive within {timeout:.0f}s (dropped chunk, dead '
                    f'peer, or nothing to send)') from None
        finally:
            with self._blob_lock:
                self._blob_waiters.pop(key, None)
        if result is _BLOB_NAK:
            raise BlobError(f'rank {peer} has no blob {key!r}')
        if result is _BLOB_CORRUPT:
            raise BlobError(
                f'blob {key!r} from rank {peer} failed its digest check '
                f'(truncated or corrupted chunk)')
        if result is _BLOB_DEAD:
            raise BlobError(
                f'rank {self.rank}: transport closed or failed over while '
                f'fetching blob {key!r}; delivery state unknown')
        return result[1]

    def _answer_blob_request(self, sender: int, key: str) -> None:
        data = None
        if self.on_blob_request is not None:
            try:
                data = self.on_blob_request(key)
            except Exception:
                logger.exception('on_blob_request(%r) failed; NAKing', key)
        try:
            if data is None:
                self._send(('blob-nak', sender, key))
            else:
                self.send_blob(sender, key, data)
        except OSError:
            # best-effort reply: the link (or this transport) died while
            # answering — the requester's own timeout is the fallback
            pass

    def _blob_accept(self, sender: int, key: str, index: int, total: int,
                     digest: str, chunk: bytes) -> None:
        slot = (sender, key, digest)
        now = time.monotonic()
        with self._blob_lock:
            entry = self._blob_parts.setdefault(slot, {'chunks': {},
                                                       'touched': now})
            entry['chunks'][index] = chunk
            entry['touched'] = now
            parts = entry['chunks']
            if len(parts) < total:
                # bound abandoned reassemblies: a transfer whose chunk was
                # dropped in flight never completes, and without eviction
                # each partial (potentially a multi-GB hot TrainState)
                # would hold its bytes forever. Only *stale* slots (no
                # chunk for 120s) are evicted — a big transfer that merely
                # started first is still live — and the sweep runs on
                # every arrival, not past some count: even ONE abandoned
                # partial is a leak worth collecting.
                for stale, held in list(self._blob_parts.items()):
                    if stale != slot and now - held['touched'] > 120.0:
                        del self._blob_parts[stale]
                        logger.warning(
                            'evicted stale blob reassembly %r from '
                            'rank %d', stale[1], stale[0])
                return
            del self._blob_parts[slot]
        data = b''.join(parts[i] for i in sorted(parts))
        if _blob_digest(data) != digest:
            logger.warning('blob %r from rank %d failed its digest check; '
                           'discarded', key, sender)
            self._blob_deliver(key, sender, _BLOB_CORRUPT)
            return
        self._blob_deliver(key, sender, data)

    def _blob_deliver(self, key: str, sender: int, payload: Any) -> None:
        with self._blob_lock:
            waiter = self._blob_waiters.get(key)
            if waiter is not None and waiter[0] != sender:
                waiter = None            # not the peer this fetch asked
            if waiter is None and isinstance(payload, bytes):
                if self.on_blob is None:
                    self._blob_ready[key] = (sender, payload)
                    return
        if waiter is not None:
            marker = payload is _BLOB_NAK or payload is _BLOB_CORRUPT
            waiter[1].put(payload if marker else (sender, payload))
        elif isinstance(payload, bytes) and self.on_blob is not None:
            self.on_blob(sender, key, payload)

    def subscribe(self, channel: str, callback: Callable[[Any], None]) -> None:
        """Register the receiver for one named event channel."""
        self._channels[channel] = callback

    def send_event(self, channel: str, message: Any) -> None:
        """Fire-and-forget. Delivery is at-most-once across a control-plane
        failover window (no event ack protocol, by design — events are
        observability; collectives are the agreement primitive)."""
        self._send(('event', channel, message))

    def allreduce(self, value: Any, op: str = 'and', timeout: float = 300.0) -> Any:
        return self._collective('reduce', op, value, timeout)

    def gather(self, value: Any, timeout: float = 300.0) -> list:
        return self._collective('gather', 'sum', value, timeout)

    def barrier(self, timeout: float = 300.0) -> None:
        self._collective('reduce', 'and', True, timeout)

    def heartbeat(self) -> None:
        self._send(('hb',))

    def close(self) -> None:
        self._closed.set()
        try:
            self._send(('bye',))
        except OSError:
            pass
        self._sock.close()
        # collectives other threads still have in flight can never
        # complete now — fail them typed (ControlPlaneFailover) instead of
        # leaving them to their full timeout; the recv loop does the same,
        # but it may itself be gone already
        self._abandon()


def connect(address: tuple, world: World,
            heartbeat_interval: float | None = None,
            heartbeat_timeout: float | None = None,
            deputy_address: tuple | None = None) -> tuple[TcpTransport, Hub | None]:
    """Attach this host to the control plane; the primary also hosts the Hub.

    Returns ``(transport, hub)`` — ``hub`` is the primary Hub on rank 0,
    the standby deputy Hub on rank 1 when ``deputy_address`` is given
    (a concrete ``(host, port)`` every rank can compute), else None.
    With a deputy, transports dial ``[address, deputy_address]`` and
    survive primary-hub loss: the deputy promotes when its hub-to-hub
    link to the primary dies, clients fail over, and only collectives
    that were in flight at the instant of the loss fail (with
    :class:`ControlPlaneFailover`).
    """
    hub = None
    if world.is_primary:
        hub = Hub(world.process_count, host=address[0], port=address[1],
                  heartbeat_timeout=heartbeat_timeout)
        address = hub.address
    if deputy_address is not None and world.process_count > 1:
        if world.process_index == 1:
            hub = Hub(world.process_count, host=deputy_address[0],
                      port=deputy_address[1],
                      heartbeat_timeout=heartbeat_timeout,
                      standby_of=tuple(address))
        dial = [tuple(address), tuple(deputy_address)]
    else:
        dial = tuple(address)
    transport = TcpTransport(dial, world.process_index, world.process_count,
                             heartbeat_interval=heartbeat_interval)
    return transport, hub


# ---------------------------------------------------------------------------
# distributed buses


class DistributedProducer(Producer):
    """The in-process :class:`Producer`, extended across hosts.

    - ``register(consumer, primary_only=True)`` — the consumer runs only on
      rank 0 (storage, TensorBoard), all other ranks skip it silently.
    - ``wire(EventType, ...)`` — instances of these types are forwarded to
      every other host on dispatch. Unwired events stay host-local (the
      default: most events are per-host observability).
    - remote events arrive on a transport thread and are queued; call
      :meth:`drain` at a safe point in the host loop (epoch boundary) to
      dispatch them locally — keeps consumers single-threaded, matching the
      reference's synchronous bus semantics.
    """

    CHANNEL = 'producer'

    def __init__(self, transport: Loopback | TcpTransport | None = None):
        super().__init__()
        self.transport = transport or Loopback()
        self.wired: tuple[type, ...] = ()
        self._inbox: queue.SimpleQueue = queue.SimpleQueue()
        self.transport.subscribe(self.CHANNEL, self._inbox.put)
        previous = self.transport.on_control

        def on_control(frame: tuple) -> None:
            if frame[0] == 'lost':
                self._inbox.put(WorkerLost(
                    rank=frame[1], last_seen=frame[2],
                    reason=frame[3] if len(frame) > 3 else 'socket'))
            elif frame[0] == 'joined':
                self._inbox.put(WorkerJoined(rank=frame[1]))
            if previous is not None:
                previous(frame)
        self.transport.on_control = on_control

    def register(self, *consumers: Consumer, primary_only: bool = False) -> None:
        if primary_only and self.transport.rank != 0:
            return
        super().register(*consumers)

    def wire(self, *event_types: type) -> None:
        self.wired = tuple(dict.fromkeys(self.wired + event_types))

    def dispatch(self, message: Any) -> None:
        super().dispatch(message)
        if isinstance(message, self.wired):
            self.transport.send_event(self.CHANNEL, message)

    def drain(self) -> int:
        """Dispatch queued remote events on the caller's thread; returns the
        number delivered. Call once per epoch/phase — never per step."""
        delivered = 0
        while True:
            try:
                message = self._inbox.get_nowait()
            except queue.Empty:
                return delivered
            super().dispatch(message)
            delivered += 1


class DistributedPublisher(Publisher):
    """Topic bus across hosts: wired topics forward ``(topic, message)``."""

    CHANNEL = 'publisher'

    def __init__(self, transport: Loopback | TcpTransport | None = None):
        super().__init__()
        self.transport = transport or Loopback()
        self.wired: frozenset[str] = frozenset()
        self._inbox: queue.SimpleQueue = queue.SimpleQueue()
        self.transport.subscribe(self.CHANNEL, self._inbox.put)

    def register(self, *subscribers: Subscriber, primary_only: bool = False) -> None:
        if primary_only and self.transport.rank != 0:
            return
        super().register(*subscribers)

    def wire(self, *topics: str) -> None:
        self.wired = self.wired | frozenset(topics)

    def publish(self, message: Any, topic: str) -> None:
        super().publish(message, topic)
        if topic in self.wired:
            self.transport.send_event(self.CHANNEL, (topic, message))

    def drain(self) -> int:
        delivered = 0
        while True:
            try:
                topic, message = self._inbox.get_nowait()
            except queue.Empty:
                return delivered
            super().publish(message, topic)
            delivered += 1


# ---------------------------------------------------------------------------
# agreement — the early-stop commit point


def agree(transport: Loopback | TcpTransport, flag: bool, op: str = 'or') -> bool:
    """Collectively agree a boolean across hosts.

    Early stopping in the reference is an exception unwinding one process
    (``torchsystem/domain/events.py:162-163``); on a pod every host must
    reach the same verdict *before* the next collective or the job
    deadlocks. Default ``op='or'``: any host wanting to stop stops all —
    call at the epoch boundary::

        stop = agree(transport, wants_stop)
        if stop: break
    """
    return bool(transport.allreduce(bool(flag), op=op))
