"""Deterministic fault injection for the control plane (chaos harness).

"Handles as many scenarios as you can imagine" (ROADMAP) is only true of
scenarios that are *exercised*. This module wraps the real
:class:`~tpusystem.parallel.multihost.TcpTransport` / ``Hub`` stack — not a
mock of it — with **seeded, deterministic** fault injection, so every
failure path the recovery machinery claims to survive is a replayable test
case instead of a hand-crafted one-off:

* dropped frames (events vanish in flight — the at-most-once contract);
* delayed frames (reordering pressure on the hub's collective bookkeeping);
* heartbeat stalls (a healthy-but-slow host crossing the liveness timeout);
* mid-collective socket kills (the crashed-host signature: EOF, no 'bye');
* worker death at a chosen global step (:class:`DieAtStep` — the
  kill-at-step-k → restart → step-granular-resume drill).

Determinism: every fault decision is drawn in frame order from one
``random.Random(seed)`` per :class:`Faults` instance, and frames of one
transport are serialized by its send lock — same seed, same faults. Frame
kinds carrying pod agreement (``hello``/``bye``/collective results) default
to spared so a scenario targets the traffic it means to; widen ``kinds``
deliberately when the test wants to hurt collectives themselves.

The harness is control-plane only, by design: the data plane (XLA
collectives) fails as a unit with the process, which is exactly what
:class:`DieAtStep` simulates.
"""

from __future__ import annotations

import os
import random
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from tpusystem.parallel.multihost import Hub, TcpTransport

__all__ = ['Faults', 'ChaosTransport', 'ChaosHub', 'DieAtStep', 'WorkerKilled']


@dataclass
class Faults:
    """Seeded fault plan consulted once per frame, in send order.

    Args:
        seed: the determinism anchor — same seed, same decisions.
        drop: probability a matching frame is silently discarded.
        delay: probability a matching frame is held for ``delay_seconds``.
        delay_seconds: hold time for delayed frames.
        kinds: frame kinds eligible for faults (None: every kind not in
            ``spare``). Transport frame kinds: ``event``, ``reduce``,
            ``gather``, ``hb``; hub fanout kinds add ``result``, ``lost``,
            ``joined``.
        spare: kinds never faulted. By default: handshake/teardown frames
            (drop those and a scenario tests the dialer's retry loop,
            usually not what it meant); ``result`` (dropping a collective's
            result fanout wedges every waiting rank into its full timeout
            — target it explicitly via ``kinds`` when a scenario wants
            that); and ``hb``: heartbeats ride their own thread, so
            probabilistic faults on them would interleave RNG draws
            scheduler-dependently and break the same-seed-same-faults
            contract — fault heartbeats with the *scripted*
            :meth:`stall_heartbeats` instead.
    """

    seed: int = 0
    drop: float = 0.0
    delay: float = 0.0
    delay_seconds: float = 0.02
    kinds: tuple[str, ...] | None = None
    spare: tuple[str, ...] = ('hello', 'bye', 'peer', 'standby', 'rejected',
                              'result', 'hb')

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._stall_until = 0.0
        self.dropped: list[str] = []    # observability for assertions
        self.delayed: list[str] = []

    def decide(self, kind: str) -> float | None:
        """One decision: None = drop the frame, 0.0 = pass, >0 = delay.

        An explicit ``kinds`` list overrides ``spare`` — naming a kind is
        the opt-in for faulting even default-spared traffic (``result``,
        ``hb``). Draws are taken for every eligible frame whether or not a
        fault fires, so the decision stream depends only on the frame
        sequence — not on which probabilities are enabled."""
        if self.kinds is not None:
            if kind not in self.kinds:
                return 0.0
        elif kind in self.spare:
            return 0.0
        with self._lock:
            roll = self._rng.random()
            if roll < self.drop:
                self.dropped.append(kind)
                return None
            if roll < self.drop + self.delay:
                self.delayed.append(kind)
                return self.delay_seconds
        return 0.0

    def stall_heartbeats(self, seconds: float) -> None:
        """Swallow outbound heartbeats for ``seconds`` — a host that is
        alive but unresponsive (GC pause, hung NFS, overloaded NIC), the
        scenario the hub's liveness timeout must classify as lost."""
        with self._lock:
            self._stall_until = time.monotonic() + seconds

    @property
    def heartbeats_stalled(self) -> bool:
        with self._lock:
            return time.monotonic() < self._stall_until


class ChaosTransport(TcpTransport):
    """A :class:`TcpTransport` whose outbound frames pass through a
    :class:`Faults` plan. The wire protocol, hub, and recovery machinery
    are the real ones — only the network misbehaves."""

    def __init__(self, address, rank: int, size: int, *,
                 faults: Faults | None = None, **kwargs: Any):
        self.faults = faults if faults is not None else Faults()
        super().__init__(address, rank, size, **kwargs)

    def _send(self, frame: tuple, op_key: tuple | None = None) -> None:
        kind = frame[0]
        if kind == 'hb' and self.faults.heartbeats_stalled:
            return                       # the beat never leaves the host
        verdict = self.faults.decide(kind)
        if verdict is None:
            return                       # dropped on the (virtual) wire
        if verdict > 0:
            time.sleep(verdict)
        super()._send(frame, op_key)

    def kill(self) -> None:
        """Abrupt socket death — the crashed-host signature the hub must
        classify as a loss (EOF with no 'bye'), usable mid-collective.

        Unlike :meth:`close`, nothing is flushed and no teardown runs: the
        transport object stays around exactly like the OS socket of a
        SIGKILLed process would."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class ChaosHub(Hub):
    """A :class:`Hub` whose fanout passes through a :class:`Faults` plan —
    faults on the router's side of the star (results and loss broadcasts
    included, when ``kinds`` says so)."""

    def __init__(self, size: int, *, faults: Faults | None = None,
                 **kwargs: Any):
        self.faults = faults if faults is not None else Faults()
        super().__init__(size, **kwargs)

    def _fanout(self, frame: tuple, exclude: int | None = None,
                live_only: bool = False) -> None:
        verdict = self.faults.decide(frame[0])
        if verdict is None:
            return
        if verdict > 0:
            time.sleep(verdict)
        super()._fanout(frame, exclude=exclude, live_only=live_only)


class WorkerKilled(RuntimeError):
    """In-process stand-in for a worker death (see :class:`DieAtStep`)."""

    def __init__(self, step: int):
        super().__init__(f'worker scripted to die at step {step}')
        self.step = step


@dataclass
class DieAtStep:
    """Scripted worker death at a chosen global step.

    Call it with the just-completed step number from the training loop::

        die = DieAtStep(step=7)                # in-process: raises
        for batch in loader:
            state, _ = step(state, *batch)
            checkpointer.save(identity, state.global_step, state, ...)
            die(state.global_step)

    ``action='raise'`` (default) raises :class:`WorkerKilled` — the
    in-process form, letting a test's "restart" run in the same process.
    ``action='exit'`` calls ``os._exit(code)`` — the cross-process form: no
    'bye' frame, no atexit, no flushing; the genuine article for
    subprocess chaos tests. A callable ``action`` runs verbatim (e.g.
    ``transport.kill`` to sever just the control plane).
    """

    step: int
    action: str | Callable[[], None] = 'raise'
    code: int = 1
    fired: bool = field(default=False, init=False)

    def __call__(self, current_step: int) -> None:
        if self.fired or current_step != self.step:
            return
        self.fired = True
        if callable(self.action):
            self.action()
        elif self.action == 'exit':
            os._exit(self.code)
        else:
            raise WorkerKilled(self.step)
