"""Deterministic fault injection for the control plane (chaos harness).

"Handles as many scenarios as you can imagine" (ROADMAP) is only true of
scenarios that are *exercised*. This module wraps the real
:class:`~tpusystem.parallel.multihost.TcpTransport` / ``Hub`` stack — not a
mock of it — with **seeded, deterministic** fault injection, so every
failure path the recovery machinery claims to survive is a replayable test
case instead of a hand-crafted one-off:

* dropped frames (events vanish in flight — the at-most-once contract);
* delayed frames (reordering pressure on the hub's collective bookkeeping);
* heartbeat stalls (a healthy-but-slow host crossing the liveness timeout);
* mid-collective socket kills (the crashed-host signature: EOF, no 'bye');
* worker death at a chosen global step (:class:`DieAtStep` — the
  kill-at-step-k → restart → step-granular-resume drill);
* blob-plane faults (dropped or truncated chunks of a hot-state replica,
  ``Faults(truncate=...)`` / ``kinds=('blob',)``) — the transfers the
  supervisor's memstore replication rides must *detect* every torn copy
  (the serving engine's request-journal replication rides the same plane,
  so the same faults drill it);
* serving-step stalls (:class:`StalledStep` — a decode step that hangs or
  runs anomalously slow at a chosen tick, the wedge the step watchdog
  must turn into a typed ``EngineStalled`` → restart-and-replay;
  :class:`DieAtStep` doubles as the kill-at-tick-k serving fault).

Determinism: every fault decision is drawn in frame order from one
``random.Random(seed)`` per :class:`Faults` instance, and frames of one
transport are serialized by its send lock — same seed, same faults. Frame
kinds carrying pod agreement (``hello``/``bye``/collective results) default
to spared so a scenario targets the traffic it means to; widen ``kinds``
deliberately when the test wants to hurt collectives themselves.

The harness is control-plane only, by design: the data plane (XLA
collectives) fails as a unit with the process, which is exactly what
:class:`DieAtStep` simulates.
"""

from __future__ import annotations

import os
import random
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from tpusystem.parallel.multihost import Hub, TcpTransport

__all__ = ['Faults', 'ChaosTransport', 'ChaosHub', 'DieAtStep', 'WorkerKilled',
           'PreemptionWave', 'StalledStep', 'CorruptGrads', 'CorruptBatch',
           'FlipParamBit', 'ChaosPick', 'pick_chaos', 'TenantChaosPick',
           'pick_tenant_chaos']


@dataclass
class Faults:
    """Seeded fault plan consulted once per frame, in send order.

    Args:
        seed: the determinism anchor — same seed, same decisions.
        drop: probability a matching frame is silently discarded.
        delay: probability a matching frame is held for ``delay_seconds``.
        delay_seconds: hold time for delayed frames.
        truncate: probability a matching ``blob`` chunk frame is sent with
            half its payload cut off — the torn-transfer signature the
            receiver's whole-blob digest must catch (non-blob frames treat
            a truncate verdict as a pass; the pickle framing would turn a
            torn control frame into a socket death, which ``kill()``
            already scripts precisely).
        kinds: frame kinds eligible for faults (None: every kind not in
            ``spare``). Transport frame kinds: ``event``, ``reduce``,
            ``gather``, ``hb``, plus the blob plane's ``blob`` /
            ``blob-req`` / ``blob-nak``; hub fanout kinds add ``result``,
            ``lost``, ``joined``.
        spare: kinds never faulted. By default: handshake/teardown frames
            (drop those and a scenario tests the dialer's retry loop,
            usually not what it meant); ``result`` (dropping a collective's
            result fanout wedges every waiting rank into its full timeout
            — target it explicitly via ``kinds`` when a scenario wants
            that); and ``hb``: heartbeats ride their own thread, so
            probabilistic faults on them would interleave RNG draws
            scheduler-dependently and break the same-seed-same-faults
            contract — fault heartbeats with the *scripted*
            :meth:`stall_heartbeats` instead.
    """

    seed: int = 0
    drop: float = 0.0
    delay: float = 0.0
    delay_seconds: float = 0.02
    truncate: float = 0.0
    kinds: tuple[str, ...] | None = None
    spare: tuple[str, ...] = ('hello', 'bye', 'peer', 'standby', 'rejected',
                              'result', 'hb')

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self._stall_until = 0.0
        self.dropped: list[str] = []    # observability for assertions
        self.delayed: list[str] = []
        self.truncated: list[str] = []

    def verdict(self, kind: str) -> str:
        """One decision: ``'drop'`` | ``'delay'`` | ``'truncate'`` |
        ``'pass'``.

        An explicit ``kinds`` list overrides ``spare`` — naming a kind is
        the opt-in for faulting even default-spared traffic (``result``,
        ``hb``). Draws are taken for every eligible frame whether or not a
        fault fires, so the decision stream depends only on the frame
        sequence — not on which probabilities are enabled."""
        if self.kinds is not None:
            if kind not in self.kinds:
                return 'pass'
        elif kind in self.spare:
            return 'pass'
        with self._lock:
            roll = self._rng.random()
            if roll < self.drop:
                self.dropped.append(kind)
                return 'drop'
            if roll < self.drop + self.delay:
                self.delayed.append(kind)
                return 'delay'
            if roll < self.drop + self.delay + self.truncate:
                self.truncated.append(kind)
                return 'truncate'
        return 'pass'

    def decide(self, kind: str) -> float | None:
        """Verdict as the legacy drop/delay encoding: None = drop the
        frame, 0.0 = pass, >0 = delay. (A ``truncate`` verdict on a
        non-blob path reads as pass — only :class:`ChaosTransport`'s blob
        frames can act on it.)"""
        verdict = self.verdict(kind)
        if verdict == 'drop':
            return None
        if verdict == 'delay':
            return self.delay_seconds
        return 0.0

    def stall_heartbeats(self, seconds: float) -> None:
        """Swallow outbound heartbeats for ``seconds`` — a host that is
        alive but unresponsive (GC pause, hung NFS, overloaded NIC), the
        scenario the hub's liveness timeout must classify as lost."""
        with self._lock:
            self._stall_until = time.monotonic() + seconds

    @property
    def heartbeats_stalled(self) -> bool:
        with self._lock:
            return time.monotonic() < self._stall_until


class ChaosTransport(TcpTransport):
    """A :class:`TcpTransport` whose outbound frames pass through a
    :class:`Faults` plan. The wire protocol, hub, and recovery machinery
    are the real ones — only the network misbehaves."""

    def __init__(self, address, rank: int, size: int, *,
                 faults: Faults | None = None, **kwargs: Any):
        self.faults = faults if faults is not None else Faults()
        super().__init__(address, rank, size, **kwargs)

    def _send(self, frame: tuple, op_key: tuple | None = None) -> None:
        kind = frame[0]
        if kind == 'hb' and self.faults.heartbeats_stalled:
            return                       # the beat never leaves the host
        verdict = self.faults.verdict(kind)
        if verdict == 'drop':
            return                       # dropped on the (virtual) wire
        if verdict == 'delay':
            time.sleep(self.faults.delay_seconds)
        if verdict == 'truncate' and kind == 'blob':
            # half the chunk's bytes never make it: the reassembled blob
            # fails its whole-blob digest at the receiver
            chunk = frame[-1]
            frame = frame[:-1] + (chunk[:len(chunk) // 2],)
        super()._send(frame, op_key)

    def kill(self) -> None:
        """Abrupt socket death — the crashed-host signature the hub must
        classify as a loss (EOF with no 'bye'), usable mid-collective.

        Unlike :meth:`close`, nothing is flushed and no teardown runs: the
        transport object stays around exactly like the OS socket of a
        SIGKILLed process would."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class ChaosHub(Hub):
    """A :class:`Hub` whose fanout passes through a :class:`Faults` plan —
    faults on the router's side of the star (results and loss broadcasts
    included, when ``kinds`` says so)."""

    def __init__(self, size: int, *, faults: Faults | None = None,
                 **kwargs: Any):
        self.faults = faults if faults is not None else Faults()
        super().__init__(size, **kwargs)

    def _fanout(self, frame: tuple, exclude: int | None = None,
                live_only: bool = False) -> None:
        verdict = self.faults.decide(frame[0])
        if verdict is None:
            return
        if verdict > 0:
            time.sleep(verdict)
        super()._fanout(frame, exclude=exclude, live_only=live_only)


@dataclass(frozen=True)
class ChaosPick:
    """One drawn fleet-chaos scenario: kill ``component`` after router
    tick ``step`` (see :func:`pick_chaos`)."""

    component: str
    step: int


def pick_chaos(seed: int, components: tuple[str, ...] | list[str], *,
               lo: int = 1, hi: int = 8) -> ChaosPick:
    """Draw the victim for one fleet chaos-certification run.

    The randomized half of ``certify_fleet`` (the other half is the
    invariant check): a uniformly-chosen component from ``components``
    (router, standby, a prefill or decode replica, the supervisor...) is
    killed after a uniformly-chosen router tick in ``[lo, hi]``. Both
    draws come from one ``random.Random(seed)`` in a fixed order, so a
    seed IS the scenario — a red run replays exactly from its seed, the
    same discipline as :class:`Faults`.
    """
    if not components:
        raise ValueError('need at least one component to pick from')
    if lo < 0 or hi < lo:
        raise ValueError(f'need 0 <= lo <= hi, got [{lo}, {hi}]')
    rng = random.Random(seed)
    component = components[rng.randrange(len(components))]
    return ChaosPick(component=component, step=rng.randint(lo, hi))


@dataclass(frozen=True)
class TenantChaosPick:
    """One drawn multi-tenant chaos scenario: inside ``tenant``, kill
    ``component`` after orchestrator tick ``step`` (see
    :func:`pick_tenant_chaos`)."""

    tenant: str
    component: str
    step: int


def pick_tenant_chaos(seed: int, tenants: tuple[str, ...] | list[str],
                      components: tuple[str, ...] | list[str], *,
                      lo: int = 1, hi: int = 8) -> TenantChaosPick:
    """Draw the victim for one multi-tenant chaos-certification run —
    :func:`pick_chaos` lifted one level: a uniformly-chosen tenant, then
    a uniformly-chosen component inside it, then a uniformly-chosen
    kill tick in ``[lo, hi]``. All three draws come from one
    ``random.Random(seed)`` in that fixed order, so a seed IS the
    scenario and a red cross-tenant drill replays exactly from it."""
    if not tenants:
        raise ValueError('need at least one tenant to pick from')
    if not components:
        raise ValueError('need at least one component to pick from')
    if lo < 0 or hi < lo:
        raise ValueError(f'need 0 <= lo <= hi, got [{lo}, {hi}]')
    rng = random.Random(seed)
    tenant = tenants[rng.randrange(len(tenants))]
    component = components[rng.randrange(len(components))]
    return TenantChaosPick(tenant=tenant, component=component,
                           step=rng.randint(lo, hi))


class WorkerKilled(RuntimeError):
    """In-process stand-in for a worker death (see :class:`DieAtStep`)."""

    def __init__(self, step: int):
        super().__init__(f'worker scripted to die at step {step}')
        self.step = step


@dataclass
class DieAtStep:
    """Scripted worker death at a chosen global step.

    Call it with the just-completed step number from the training loop::

        die = DieAtStep(step=7)                # in-process: raises
        for batch in loader:
            state, _ = step(state, *batch)
            checkpointer.save(identity, state.global_step, state, ...)
            die(state.global_step)

    ``action='raise'`` (default) raises :class:`WorkerKilled` — the
    in-process form, letting a test's "restart" run in the same process.
    ``action='exit'`` calls ``os._exit(code)`` — the cross-process form: no
    'bye' frame, no atexit, no flushing; the genuine article for
    subprocess chaos tests. A callable ``action`` runs verbatim (e.g.
    ``transport.kill`` to sever just the control plane).
    """

    step: int
    action: str | Callable[[], None] = 'raise'
    code: int = 1
    fired: bool = field(default=False, init=False)

    def __call__(self, current_step: int) -> None:
        if self.fired or current_step != self.step:
            return
        self.fired = True
        if callable(self.action):
            self.action()
        elif self.action == 'exit':
            os._exit(self.code)
        else:
            raise WorkerKilled(self.step)


@dataclass
class StalledStep:
    """Scripted serving-step stall at a chosen scheduler tick — the
    hung/anomalously-slow decode the step watchdog
    (:class:`tpusystem.serve.StepWatchdog`) must classify as
    ``EngineStalled`` instead of wedging forever.

    Wire it as a serving loop's fault seam (the 1-based upcoming tick,
    the :class:`DieAtStep` convention)::

        replica = ServingReplica(build, fault=StalledStep(tick=4,
                                                          seconds=2.0))

    ``action`` defaults to a real ``time.sleep(seconds)`` — the genuine
    article for wall-clock watchdogs. Tests pass a callable instead
    (advance a fake clock, or raise the stall directly) so tier-1 drills
    the verdict with zero real sleeps. Fires once.
    """

    tick: int
    seconds: float = 0.0
    action: Callable[[], None] | None = None
    fired: bool = field(default=False, init=False)

    def __call__(self, current_tick: int) -> None:
        if self.fired or current_tick != self.tick:
            return
        self.fired = True
        if self.action is not None:
            self.action()
        else:
            time.sleep(self.seconds)


@dataclass
class PreemptionWave:
    """Scripted multi-host loss at a chosen global step — the elastic
    drill's signature fault: k of n hosts die *together* (a spot-market
    reclaim, a rack power event), and the membership protocol must fold
    every loss into ONE resize (the settle window's job), never one
    resize per host.

    ``kills`` are callables fired in order (``transport.kill`` for a
    control-plane-only death, ``os.kill`` of a worker for the real
    thing, a fleet handle's
    :meth:`~tpusystem.serve.ReplicaHandle.kill` for the serving-fleet
    drill — the router tick is the ``step``); ``stagger`` seconds
    between them models losses spread inside a wave — pick it below the
    coordinator's settle window to assert the one-resize contract,
    above it to drill the two-epoch case (``sleep`` is injectable so a
    fake-clock drill staggers without real waits). Same fired-once
    discipline as :class:`DieAtStep`::

        wave = PreemptionWave(step=5, kills=(t2.kill, t3.kill))
        for batch in loader:
            state, _ = step(state, *batch)
            wave(int(state.step))
    """

    step: int
    kills: tuple = ()
    stagger: float = 0.0
    sleep: Callable[[float], None] = time.sleep
    fired: bool = field(default=False, init=False)

    def __call__(self, current_step: int) -> None:
        if self.fired or current_step != self.step:
            return
        self.fired = True
        for index, kill in enumerate(self.kills):
            if index and self.stagger:
                self.sleep(self.stagger)
            kill()


# ---------------------------------------------------------------------------
# internal faults: the divergence-sentinel drill kinds
#
# DieAtStep simulates the process failing as a unit; these simulate the
# *numerics* failing while the process lives — the faults the in-graph
# guard + Sentinel ladder (tpusystem.train.sentinel) must absorb. Same
# discipline as the frame faults above: deterministic (step-indexed, not
# sampled), so every drill is replayable.


@dataclass
class CorruptGrads:
    """In-graph gradient corruption over a step window (traced).

    Pass as ``build_train_step(..., fault=CorruptGrads(step=k))``: the
    corruption is compiled into the jitted step and fires when the step
    being computed (1-based, ``state.step + 1``) falls in
    ``[step, step + steps)`` — the device-side analogue of a bad batch or a
    flaky matmul unit. Because it is keyed on the *step counter*, the fault
    refires if a rollback rewinds the counter into the window — useful for
    drilling the bounded give-up; use :class:`CorruptBatch` for data-borne
    corruption that a skip-window genuinely escapes.

    Modes: ``'nan'`` / ``'inf'`` poison every gradient leaf (the finiteness
    check must suppress the update); ``'spike'`` scales the gradients by
    ``scale`` — finite, so only the EMA z-score detector catches it.
    """

    step: int
    steps: int = 1
    mode: str = 'nan'     # 'nan' | 'inf' | 'spike'
    scale: float = 1e4

    def __call__(self, current_step, grads, loss):
        import jax
        import jax.numpy as jnp
        fire = ((current_step >= self.step)
                & (current_step < self.step + self.steps))
        if self.mode == 'spike':
            corrupt = lambda leaf: leaf * jnp.asarray(self.scale, leaf.dtype)
        elif self.mode in ('nan', 'inf'):
            bad = float('nan') if self.mode == 'nan' else float('inf')
            corrupt = lambda leaf: jnp.full_like(leaf, bad)
        else:
            raise ValueError(f"mode must be 'nan', 'inf' or 'spike', "
                             f'got {self.mode!r}')
        grads = jax.tree.map(
            lambda leaf: jnp.where(fire, corrupt(leaf), leaf), grads)
        return grads, loss


@dataclass
class CorruptBatch:
    """Host-side data corruption of a window of the batch *stream*.

    The data-borne sibling of :class:`CorruptGrads`: poison the
    ``batch``-th through ``batch + steps - 1``-th batches **fed through
    this injector** (1-based count of calls; float leaves become ``value``,
    integer leaves are left alone), producing non-finite loss/grads the
    guard must suppress. The window is keyed on the data stream — NOT the
    step counter — because that is what real data-borne corruption does:
    after a sentinel rollback rewinds the step counter and skips the
    offending cursor range, the poisoned batches are never consumed again,
    so the fault does not refire. (Contrast :class:`CorruptGrads`, whose
    counter-keyed window deliberately refires across a rollback.)::

        for inputs, targets in loader:
            inputs = corrupt(inputs)
            state, (_, loss) = step(state, inputs, targets)
    """

    batch: int
    steps: int = 1
    value: float = float('nan')
    fed: int = field(default=0, init=False)

    def __call__(self, batch_tree: Any) -> Any:
        import jax
        import jax.numpy as jnp
        self.fed += 1
        if not (self.batch <= self.fed < self.batch + self.steps):
            return batch_tree
        return jax.tree.map(
            lambda leaf: (jnp.full_like(leaf, self.value)
                          if jnp.issubdtype(jnp.asarray(leaf).dtype,
                                            jnp.floating) else leaf),
            batch_tree)


@dataclass
class FlipParamBit:
    """Silent data corruption: flip one bit of one param leaf on ONE
    replica of a mesh axis — the cosmic-ray/bad-HBM signature the
    cross-replica parity check
    (:meth:`tpusystem.train.Sentinel.check_parity`) must catch before the
    next checkpoint commits.

    ``__call__(params, mesh)`` returns a copy of the pytree where exactly
    ONE device — coordinate ``replica`` on ``axis``, coordinate 0 on every
    other mesh axis — holds the flipped value of leaf ``leaf`` (index into
    ``jax.tree.leaves`` order) while every other device keeps the
    original: the replicas now silently disagree, exactly what a real SDC
    leaves behind. One device, one element, one bit — a cosmic ray does
    not coordinate across shards (and a multi-device flip could even
    cancel in an additive checksum: ``+2^b`` on one shard against ``-2^b``
    on another). The flip lands on the element at flat ``index`` of the
    victim device's *local shard*, bit ``bit`` (LSB-first within the
    element's bytes).
    """

    replica: int = 0
    leaf: int = 0
    index: int = 0
    bit: int = 0
    axis: str = 'data'

    def __call__(self, params: Any, mesh) -> Any:
        import jax
        import numpy as np
        leaves, treedef = jax.tree_util.tree_flatten(params)
        target = leaves[self.leaf]
        victim = mesh.devices[tuple(
            self.replica if name == self.axis else 0
            for name in mesh.axis_names)]
        pieces = []
        for shard in target.addressable_shards:
            if shard.device != victim:
                pieces.append(shard.data)
                continue
            host = np.asarray(shard.data)
            raw = bytearray(host.tobytes())
            offset = self.index * host.dtype.itemsize + self.bit // 8
            raw[offset] ^= 1 << (self.bit % 8)
            flipped = np.frombuffer(bytes(raw),
                                    dtype=host.dtype).reshape(host.shape)
            pieces.append(jax.device_put(flipped, shard.device))
        corrupted = jax.make_array_from_single_device_arrays(
            target.shape, target.sharding, pieces)
        leaves = list(leaves)
        leaves[self.leaf] = corrupted
        return jax.tree_util.tree_unflatten(treedef, leaves)
