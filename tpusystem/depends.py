"""Dependency-injection kernel.

FastAPI-style late-bound dependency injection: a parameter whose default is
``Depends(factory)`` is filled at call time by invoking ``factory`` (or the
override registered for it on the :class:`Provider`). Generator factories are
treated as managed resources — the value yielded is injected and the generator
is resumed once more for teardown after the call returns.

Behavioral parity with the reference DI kernel
(``torchsystem/depends.py:26-86``), with two deliberate extensions:

* dependencies may themselves declare ``Depends(...)`` parameters and are
  resolved recursively;
* a factory resolved more than once within a single call is invoked exactly
  once (per-call memoization), so e.g. a mesh provider shared by several
  dependencies yields one mesh object.

In the TPU build this kernel is how runtime facts — the
:class:`jax.sharding.Mesh`, the host/process topology, checkpoint stores —
reach services and compiler steps without the domain code importing them.
"""

from __future__ import annotations

from collections.abc import Callable, Generator
from contextlib import ExitStack, contextmanager
from inspect import signature
from functools import wraps
from typing import Any


class Provider:
    """Holds the dependency override table.

    Overrides are keyed by the *original* factory callable, exactly like the
    reference contract (``torchsystem/depends.py:26-31``): services, buses and
    compilers expose ``dependency_overrides`` mapping factory -> replacement.
    """

    def __init__(self) -> None:
        self.dependency_overrides: dict[Callable, Callable] = {}

    def override(self, dependency: Callable, override: Callable) -> None:
        self.dependency_overrides[dependency] = override


class Dependency:
    """Marker wrapper produced by :func:`Depends`."""

    __slots__ = ('factory',)

    def __init__(self, factory: Callable) -> None:
        self.factory = factory

    def __repr__(self) -> str:  # pragma: no cover
        return f'Depends({getattr(self.factory, "__name__", self.factory)!r})'


def Depends(factory: Callable) -> Any:
    """Declare a parameter default as an injected dependency.

    The factory may return a plain value or be a generator function; in the
    generator case the first yielded value is injected and the generator is
    finalized (resumed once) after the wrapped call returns, giving
    deterministic resource cleanup (reference contract
    ``torchsystem/depends.py:57-77``).
    """
    return Dependency(factory)


@contextmanager
def _managed(generator: Generator):
    try:
        value = next(generator)
        yield value
    finally:
        next(generator, None)


def _materialize(factory: Callable, provider: Provider, stack: ExitStack,
                 cache: dict[Callable, Any]) -> Any:
    """Invoke a dependency factory, recursively resolving its own deps."""
    factory = provider.dependency_overrides.get(factory, factory)
    if factory in cache:
        return cache[factory]
    bound, _ = _bind(factory, provider, stack, cache, (), {})
    produced = factory(*bound.args, **bound.kwargs)
    if isinstance(produced, Generator):
        produced = stack.enter_context(_managed(produced))
    cache[factory] = produced
    return produced


def _bind(function: Callable, provider: Provider, stack: ExitStack,
          cache: dict[Callable, Any], args: tuple, kwargs: dict):
    parameters = signature(function).parameters
    bound = signature(function).bind_partial(*args, **kwargs)
    for name, parameter in parameters.items():
        if name not in bound.arguments and isinstance(parameter.default, Dependency):
            bound.arguments[name] = _materialize(
                parameter.default.factory, provider, stack, cache)
    return bound, stack


def resolve(function: Callable, provider: Provider, *args, **kwargs):
    """Bind ``function``'s injected parameters; returns (bound_args, exit_stack).

    The caller is responsible for entering/closing the returned
    :class:`~contextlib.ExitStack` around the actual call so generator
    dependencies tear down afterwards.
    """
    stack = ExitStack()
    return _bind(function, provider, stack, {}, args, kwargs)


def inject(provider: Provider) -> Callable[[Callable], Callable]:
    """Decorator: resolve ``Depends`` parameters of the wrapped callable at
    every call, honoring the provider's current overrides (late binding)."""

    def decorator(function: Callable) -> Callable:
        @wraps(function)
        def wrapper(*args, **kwargs):
            bound, stack = resolve(function, provider, *args, **kwargs)
            with stack:
                return function(*bound.args, **bound.kwargs)
        return wrapper
    return decorator
