"""Gang-scheduling N jobs on one physical mesh, as isolated tenants.

The subsystems this repo has grown — supervised training, the serving
fleet, recsys, periodic eval — each assume they own the whole mesh. The
:class:`Orchestrator` removes that assumption: it carves one device set
into virtual submeshes (:func:`carve`), runs each job as a
:class:`Tenant` behind its own runner (its supervisor tree — the full
42/43/44/45/46/47/1 exit contract of :mod:`tpusystem.parallel.
recovery`), and arbitrates capacity between them. Three disciplines
carry the robustness story:

* **Blast radius** — a tenant that exits outside
  :data:`~tpusystem.parallel.recovery.RESTART_EXITS` (a 44 divergence,
  a 45 crash-loop, a 47 fencing, a plain 1) is *halted*: its devices
  return to the free pool, a typed ``JobHalted`` narrates the verdict,
  and nothing else happens — the other tenants' runners, buses
  (:mod:`tpusystem.orchestrator.namespace`), and device sets are never
  touched. Restartable exits (42/43/46) are the supervisor tree's
  business; the orchestrator deliberately does not react to them.
* **Preemptive arbitration** — :meth:`Orchestrator.request_capacity`
  fills a burst from the free pool first, then shrinks the
  lowest-priority *elastic* tenant through its runner's resize seam
  (the ``Supervisor.resize()`` / exit-46 path — the shrunk trainer
  resumes token-exact from hot shards and the move is reversible:
  :meth:`release_capacity` pays the recorded debt back on ebb, "never
  leave a chip idle" in both directions). Every decision is journaled
  **two-phase** (``decided`` before any resize executes, ``done``
  after) under the RouterJournal discipline
  (:mod:`tpusystem.orchestrator.journal`), so an orchestrator SIGKILL
  mid-arbitration recovers placements, priorities, debts, AND the
  in-flight resize — and *finishes* it instead of re-deciding.
* **Certification** — :mod:`tpusystem.orchestrator.certify` drills the
  whole story under seeded chaos: kill one (tenant × component ×
  kill-tick) draw, assert every other tenant's outputs are bitwise
  undisturbed.

Priority convention: **larger ``priority`` wins capacity**. The donor
search walks running elastic tenants from the smallest priority up and
never shrinks a tenant to satisfy an equal-or-lower-priority requester.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable

from tpusystem.parallel.recovery import (CRASH_LOOP_EXIT, DIVERGED_EXIT,
                                         RESTART_EXITS, ROUTER_FENCED_EXIT)
from tpusystem.orchestrator.journal import (OrchestratorJournal,
                                            recover_orchestrator_journal)
from tpusystem.orchestrator.namespace import TenantBus

logger = logging.getLogger('tpusystem.orchestrator')

__all__ = ['CapacityError', 'JobSpec', 'Submesh', 'carve', 'Tenant',
           'Orchestrator', 'SupervisedRunner', 'halt_reason']

# the typed vocabulary JobHalted speaks — the non-restartable half of
# the exit table (docs/multihost.md#restart-exit-code-table)
_HALT_REASONS = {DIVERGED_EXIT: 'diverged', CRASH_LOOP_EXIT: 'crash-loop',
                 ROUTER_FENCED_EXIT: 'fenced', 1: 'failure'}


def halt_reason(code: int) -> str:
    """The typed verdict for a non-restartable exit code."""
    return _HALT_REASONS.get(code, f'exit-{code}')


class CapacityError(RuntimeError):
    """The mesh cannot satisfy a placement or arbitration request —
    typed so callers degrade (queue the job, refuse the burst) instead
    of crashing the orchestrator."""


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One tenant's contract with the orchestrator.

    ``chips`` is the preferred submesh size; ``min_chips`` the floor an
    arbitration shrink may take it to. A spec with ``min_chips <
    chips`` is *elastic* — eligible as an arbitration donor (its runner
    must honor ``resize``); ``min_chips == chips`` pins the job.
    ``priority``: larger wins capacity (a burst never shrinks an
    equal-or-higher-priority tenant).
    """

    name: str
    kind: str                     # 'train' | 'serve' | 'recsys' | 'eval'...
    priority: int
    chips: int
    min_chips: int = 0

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError('a job needs a non-empty name')
        if self.chips < 1:
            raise ValueError(f'job {self.name!r} needs chips >= 1, got '
                             f'{self.chips}')
        min_chips = self.min_chips or self.chips
        if not 1 <= min_chips <= self.chips:
            raise ValueError(
                f'job {self.name!r} needs 1 <= min_chips <= chips, got '
                f'min_chips={self.min_chips} chips={self.chips}')
        object.__setattr__(self, 'min_chips', min_chips)

    @property
    def elastic(self) -> bool:
        return self.min_chips < self.chips


@dataclasses.dataclass(frozen=True)
class Submesh:
    """A virtual slice of the physical mesh: an ordered tuple of device
    ids (opaque to the orchestrator — ranks, jax device indices, host
    names). Contiguity is :func:`carve`'s policy, not a field."""

    devices: tuple

    def __post_init__(self) -> None:
        object.__setattr__(self, 'devices', tuple(self.devices))
        if len(set(self.devices)) != len(self.devices):
            raise ValueError(f'submesh has duplicate devices: '
                             f'{self.devices}')

    def __len__(self) -> int:
        return len(self.devices)


def carve(capacity: Any, specs: list[JobSpec]) -> dict[str, Submesh]:
    """Carve a device list into contiguous submeshes, one per spec, in
    priority order (highest first — ties keep submission order, so the
    placement is deterministic for a given spec list). Raises
    :exc:`CapacityError` when the specs oversubscribe ``capacity``;
    whatever is left stays in the orchestrator's free pool."""
    devices = list(capacity)
    if len(set(devices)) != len(devices):
        raise ValueError(f'capacity has duplicate devices: {devices}')
    names = [spec.name for spec in specs]
    if len(set(names)) != len(names):
        raise ValueError(f'duplicate job names: {names}')
    wanted = sum(spec.chips for spec in specs)
    if wanted > len(devices):
        raise CapacityError(
            f'{wanted} chips requested across {len(specs)} jobs but the '
            f'mesh has {len(devices)} — trim specs or shrink chips toward '
            f'min_chips')
    placements: dict[str, Submesh] = {}
    cursor = 0
    for spec in sorted(specs, key=lambda spec: -spec.priority):
        placements[spec.name] = Submesh(
            tuple(devices[cursor:cursor + spec.chips]))
        cursor += spec.chips
    return placements


@dataclasses.dataclass
class Tenant:
    """One admitted job at runtime: its spec, its current submesh, the
    runner driving its supervisor tree, its scoped bus, and the
    orchestrator's view of its lifecycle (``running`` → ``done`` |
    ``halted``)."""

    spec: JobSpec
    submesh: Submesh
    runner: Any
    bus: TenantBus | None = None
    state: str = 'running'
    exit_code: int | None = None

    @property
    def name(self) -> str:
        return self.spec.name


class Orchestrator:
    """The gang orchestrator: one device pool, N isolated tenants,
    journaled capacity arbitration.

    Runners are anything with the two-method seam the drills and the
    :class:`SupervisedRunner` adapter implement:

    * ``poll() -> int | None`` — the job's final exit code, or None
      while it runs. Restartable codes (42/43/46) are invisible here by
      design: the tenant's own supervisor tree absorbs them and
      ``poll`` keeps returning None until the tree gives a *final*
      verdict.
    * ``resize(devices: tuple) -> None`` — re-gang onto a new submesh
      (only called on elastic tenants; the exit-46 path).

    ``client`` is the memstore plane the journal replicates to (a
    :class:`~tpusystem.checkpoint.memstore.MemStore` in drills, a
    MemStoreClient on a pod, None to journal nothing). One orchestrator
    instance is single-threaded by contract — its lock only guards the
    arbitration critical section against runner callbacks.
    """

    def __init__(self, capacity: Any, *, name: str = 'orchestrator',
                 client: Any = None, cadence: int = 1,
                 producer: Any = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        devices = tuple(capacity)
        if len(set(devices)) != len(devices):
            raise ValueError(f'capacity has duplicate devices: {devices}')
        self.name = name
        self.capacity = devices
        self.free: list = list(devices)
        self.tenants: dict[str, Tenant] = {}
        self.producer = producer
        self.journal = OrchestratorJournal(name, client=client,
                                           cadence=cadence)
        self.clock = clock
        self.seq = 0                  # arbitration sequence number
        self.debts: list[dict] = []   # grow-back ledger, LIFO on release
        self.inflight: dict | None = None
        self._lock = threading.Lock()

    # ------------------------------------------------------- admission

    def admit(self, spec: JobSpec, runner: Any,
              submesh: Submesh | None = None) -> Tenant:
        """Admit one job: take its chips from the free pool (or seat it
        on an explicit ``submesh`` — the :func:`carve` path), wire its
        :class:`~tpusystem.orchestrator.namespace.TenantBus`, narrate
        ``JobAdmitted``."""
        if spec.name in self.tenants:
            raise ValueError(f'job {spec.name!r} is already admitted')
        if submesh is None:
            if spec.chips > len(self.free):
                raise CapacityError(
                    f'job {spec.name!r} wants {spec.chips} chips but only '
                    f'{len(self.free)} are free')
            submesh = Submesh(tuple(self.free[:spec.chips]))
        missing = [device for device in submesh.devices
                   if device not in self.free]
        if missing:
            raise CapacityError(
                f'job {spec.name!r} asked for devices not in the free '
                f'pool: {missing}')
        self.free = [device for device in self.free
                     if device not in set(submesh.devices)]
        bus = (TenantBus(self.producer, spec.name)
               if self.producer is not None else None)
        tenant = Tenant(spec, submesh, runner, bus=bus)
        self.tenants[spec.name] = tenant
        self._checkpoint()
        self._narrate_admitted(tenant)
        return tenant

    def _narrate_admitted(self, tenant: Tenant) -> None:
        if self.producer is None:
            return
        from tpusystem.observe.events import JobAdmitted
        self.producer.dispatch(JobAdmitted(
            job=tenant.name, kind=tenant.spec.kind,
            priority=tenant.spec.priority, chips=len(tenant.submesh)))

    # ------------------------------------------------------- lifecycle

    def step(self) -> list[Tenant]:
        """Poll every running tenant once; returns the tenants whose
        lifecycle changed this step. Exit contract: ``0`` retires the
        tenant (``done``, devices freed); a code in
        :data:`~tpusystem.parallel.recovery.RESTART_EXITS` is the
        supervisor tree's business (still ``running``); anything else
        halts ONLY that tenant — devices freed, typed ``JobHalted``,
        every other tenant untouched (the blast-radius contract the
        certifier drills bitwise)."""
        changed = []
        for tenant in list(self.tenants.values()):
            if tenant.state != 'running':
                continue
            code = tenant.runner.poll()
            if code is None or code in RESTART_EXITS:
                continue
            tenant.exit_code = code
            tenant.state = 'done' if code == 0 else 'halted'
            self.free.extend(tenant.submesh.devices)
            tenant.submesh = Submesh(())
            changed.append(tenant)
            if tenant.state == 'halted':
                reason = halt_reason(code)
                logger.warning(
                    'tenant %r halted (%s, exit %d); its devices return '
                    'to the pool and no other tenant is touched',
                    tenant.name, reason, code)
                if self.producer is not None:
                    from tpusystem.observe.events import JobHalted
                    self.producer.dispatch(JobHalted(
                        job=tenant.name, code=code, reason=reason))
        if changed:
            self._checkpoint()
        self.journal.observe_tick(self.snapshot)
        return changed

    # ----------------------------------------------------- arbitration

    def request_capacity(self, requester: str, chips: int = 1) -> tuple:
        """Grant ``chips`` more devices to ``requester``: free pool
        first, then shrink the lowest-priority elastic tenant below the
        requester through its resize seam. Returns the granted device
        tuple; raises :exc:`CapacityError` when no donor can cover the
        remainder (the caller's burst is refused typed, never partially
        applied).

        The decision is journaled ``phase='decided'`` BEFORE any resize
        executes and ``phase='done'`` after both sides re-gang — the
        recovery contract (:meth:`recover`) that makes a SIGKILL
        mid-arbitration finish the move instead of re-deciding it."""
        with self._lock:
            started = self.clock()
            tenant = self._running(requester)
            if chips < 1:
                raise ValueError(f'request_capacity needs chips >= 1, '
                                 f'got {chips}')
            taken_free = tuple(self.free[:chips])
            donor, donor_devices = None, ()
            if len(taken_free) < chips:
                need = chips - len(taken_free)
                donor = self._donor(tenant, need)
                donor_devices = donor.submesh.devices[-need:]
            decision = {
                'seq': self.seq, 'kind': 'grant', 'requester': requester,
                'donor': donor.name if donor is not None else None,
                'devices': taken_free + tuple(donor_devices),
                'donor_devices': tuple(donor_devices),
                'donor_after': tuple(
                    device for device in (donor.submesh.devices
                                          if donor is not None else ())
                    if device not in set(donor_devices)),
                'requester_after': (tenant.submesh.devices + taken_free
                                    + tuple(donor_devices)),
            }
            self.seq += 1
            self.inflight = decision
            self._checkpoint(flush=True)      # 'decided' hits the plane
            self._execute(decision)
            granted = decision['devices']
            seconds = self.clock() - started
            self._narrate_arbitrated(decision, seconds)
            return granted

    def release_capacity(self, requester: str) -> int:
        """The ebb: pay ``requester``'s most recent capacity debt back
        to its donor (LIFO — the reverse order the bursts arrived in).
        Returns the number of devices returned (0 = no debt). The
        grow-back is journaled two-phase exactly like the grant."""
        with self._lock:
            started = self.clock()
            for index in range(len(self.debts) - 1, -1, -1):
                debt = self.debts[index]
                if debt['from'] == requester:
                    break
            else:
                return 0
            tenant = self._running(requester)
            donor = self.tenants.get(debt['to'])
            devices = tuple(debt['devices'])
            decision = {
                'seq': self.seq, 'kind': 'release', 'requester': requester,
                'donor': debt['to'], 'devices': devices,
                'donor_after': ((donor.submesh.devices + devices)
                                if donor is not None
                                and donor.state == 'running' else ()),
                'requester_after': tuple(
                    device for device in tenant.submesh.devices
                    if device not in set(devices)),
                'debt_index': index,
            }
            self.seq += 1
            self.inflight = decision
            self._checkpoint(flush=True)
            self._execute(decision)
            seconds = self.clock() - started
            self._narrate_arbitrated(decision, seconds)
            return len(devices)

    def _running(self, name: str) -> Tenant:
        tenant = self.tenants.get(name)
        if tenant is None or tenant.state != 'running':
            raise CapacityError(
                f'job {name!r} is not a running tenant '
                f'({"unknown" if tenant is None else tenant.state})')
        return tenant

    def _donor(self, requester: Tenant, chips: int) -> Tenant:
        """The lowest-priority running elastic tenant strictly below
        the requester with ``chips`` of headroom above its floor."""
        candidates = sorted(
            (tenant for tenant in self.tenants.values()
             if tenant.state == 'running'
             and tenant.spec.elastic
             and tenant.spec.priority < requester.spec.priority
             and len(tenant.submesh) - chips >= tenant.spec.min_chips),
            key=lambda tenant: tenant.spec.priority)
        if not candidates:
            raise CapacityError(
                f'no donor for {chips} more chip(s): free pool is empty '
                f'and no lower-priority elastic tenant has headroom '
                f'above its min_chips floor')
        return candidates[0]

    def _execute(self, decision: dict) -> None:
        """Apply a journaled decision: resize the donor down (or up, on
        a release — the exit-46 path either way), move the devices,
        resize the requester, journal ``done``. Also the recovery
        re-entry point: :meth:`recover` calls it verbatim for an
        in-flight ``decided`` record, which is why it reads every fact
        from the decision instead of re-deriving any."""
        devices = set(decision['devices'])
        donor = (self.tenants.get(decision['donor'])
                 if decision['donor'] else None)
        tenant = self.tenants.get(decision['requester'])
        if donor is not None and donor.state == 'running':
            donor.submesh = Submesh(tuple(decision['donor_after']))
            donor.runner.resize(donor.submesh.devices)
            if self.producer is not None and decision['kind'] == 'grant':
                from tpusystem.observe.events import JobPreempted
                self.producer.dispatch(JobPreempted(
                    job=donor.name,
                    chips=len(decision.get('donor_devices',
                                           decision['devices'])),
                    to=decision['requester']))
        self.free = [device for device in self.free
                     if device not in devices]
        if decision['kind'] == 'release':
            # devices leave the requester; a dead donor's share goes
            # back to the pool instead of vanishing
            if donor is None or donor.state != 'running':
                self.free.extend(decision['devices'])
            index = decision.get('debt_index')
            if index is not None and index < len(self.debts):
                del self.debts[index]
        else:
            if decision['donor']:
                self.debts.append({
                    'from': decision['requester'],
                    'to': decision['donor'],
                    'devices': tuple(decision.get(
                        'donor_devices', decision['devices']))})
        if tenant is not None and tenant.state == 'running':
            tenant.submesh = Submesh(tuple(decision['requester_after']))
            resize = getattr(tenant.runner, 'resize', None)
            if resize is not None:
                resize(tenant.submesh.devices)
        self.inflight = None
        self._checkpoint(flush=True)          # 'done' hits the plane

    def _narrate_arbitrated(self, decision: dict, seconds: float) -> None:
        if self.producer is None:
            return
        from tpusystem.observe.events import CapacityArbitrated
        self.producer.dispatch(CapacityArbitrated(
            kind=decision['kind'], requester=decision['requester'],
            donor=decision['donor'], chips=len(decision['devices']),
            seconds=seconds))

    # the AutoscalePolicy seam: a Router wired with these callables
    # bursts through the orchestrator instead of assuming spare chips
    def capacity_hooks(self, job: str, *, chips: int = 1
                       ) -> tuple[Callable, Callable]:
        """``(provision, release)`` closures for
        :class:`~tpusystem.serve.fleet.AutoscalePolicy` wiring: the
        fleet's grow verdict becomes :meth:`request_capacity`, its
        shrink verdict :meth:`release_capacity`."""

        def provision(**_ignored: Any) -> tuple:
            return self.request_capacity(job, chips)

        def release(**_ignored: Any) -> int:
            return self.release_capacity(job)

        return provision, release

    # ----------------------------------------------------- persistence

    def snapshot(self) -> dict:
        """The journal payload: pure-host state, everything a fresh
        orchestrator needs to take over without re-deciding anything."""
        return {
            'capacity': self.capacity,
            'free': tuple(self.free),
            'placements': {name: tenant.submesh.devices
                           for name, tenant in self.tenants.items()},
            'specs': {name: dataclasses.asdict(tenant.spec)
                      for name, tenant in self.tenants.items()},
            'states': {name: (tenant.state, tenant.exit_code)
                       for name, tenant in self.tenants.items()},
            'debts': [dict(debt) for debt in self.debts],
            'inflight': dict(self.inflight) if self.inflight else None,
            'seq': self.seq,
            'term': self.journal.term,
        }

    def _checkpoint(self, flush: bool = False) -> None:
        if self.journal.client is None:
            return
        if flush:
            self.journal.tick += 1
            self.journal.replicate(self.snapshot())
        else:
            self.journal.observe_tick(self.snapshot)

    def recover(self, clients: Any, runners: dict[str, Any]) -> bool:
        """Rebuild this (fresh, empty) orchestrator from the newest
        intact journal on ``clients`` — placements, priorities, debts,
        sequence — under a bumped term so the predecessor's late pushes
        are fenced. ``runners`` re-attaches each surviving tenant by
        name (a name with no runner recovers as state-only — pollable
        never, resizable never — which is still enough to finish an
        in-flight decision's bookkeeping).

        An in-flight ``decided`` record is *completed* via the same
        :meth:`_execute` path the live orchestrator runs — the recorded
        plan, not a fresh decision — closing the SIGKILL-mid-arbitration
        window. Returns True when a journal was recovered."""
        if self.tenants:
            raise RuntimeError('recover() needs a fresh orchestrator — '
                               'this one already has tenants')
        recovered = recover_orchestrator_journal(self.name, clients)
        if recovered is None:
            return False
        tick, state = recovered
        self.journal.tick = tick
        self.journal.term = int(state.get('term', 0)) + 1
        self.capacity = tuple(state['capacity'])
        self.free = list(state['free'])
        self.seq = int(state['seq'])
        self.debts = [dict(debt) for debt in state.get('debts', [])]
        for name, spec_fields in state['specs'].items():
            spec = JobSpec(**spec_fields)
            tenant_state, exit_code = state['states'][name]
            tenant = Tenant(
                spec, Submesh(tuple(state['placements'][name])),
                runners.get(name, _StateOnlyRunner()),
                bus=(TenantBus(self.producer, name)
                     if self.producer is not None else None),
                state=tenant_state, exit_code=exit_code)
            self.tenants[name] = tenant
        inflight = state.get('inflight')
        if inflight is not None:
            logger.warning(
                'orchestrator %r recovered an in-flight %s decision '
                '(seq %d, %s -> %s); completing it from the journal '
                'without re-deciding', self.name, inflight['kind'],
                inflight['seq'], inflight['donor'], inflight['requester'])
            self.inflight = dict(inflight)
            self._execute(self.inflight)
        else:
            self._checkpoint(flush=True)      # stamp the new term
        return True


class _StateOnlyRunner:
    """A recovered tenant whose runner did not survive: pollable
    forever-running, resize is a narrated no-op. Keeps recovery's
    bookkeeping total without inventing a process."""

    def poll(self) -> None:
        return None

    def resize(self, devices: tuple) -> None:
        logger.warning('state-only runner asked to resize to %d '
                       'device(s); re-attach a real runner', len(devices))


class SupervisedRunner:
    """Adapter from the orchestrator's runner seam to one
    :class:`~tpusystem.parallel.Supervisor` tree.

    ``run()`` (blocking — the supervisor's restart loop) is driven on a
    daemon thread; ``poll`` reports its final exit code. ``resize``
    re-gangs the worker through
    :meth:`~tpusystem.parallel.Supervisor.resize` with a fresh
    :class:`~tpusystem.parallel.elastic.ResizeDecision` env — the
    exit-46 path; epochs advance monotonically per runner.
    """

    def __init__(self, supervisor: Any, member: int = 0, *,
                 epoch: int = 0) -> None:
        self.supervisor = supervisor
        self.member = member
        self.epoch = epoch
        self.code: int | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> 'SupervisedRunner':
        def drive() -> None:
            self.code = self.supervisor.run()

        self._thread = threading.Thread(
            target=drive, name=f'orchestrator-runner-{self.member}',
            daemon=True)
        self._thread.start()
        return self

    def poll(self) -> int | None:
        return self.code

    def resize(self, devices: tuple) -> None:
        from tpusystem.parallel.elastic import ResizeDecision
        self.epoch += 1
        decision = ResizeDecision(self.epoch, tuple(devices))
        self.supervisor.resize(env=decision.env(self.member))
