"""Per-tenant control-plane namespaces: the blast-radius isolation layer.

One physical mesh, N jobs, ONE shared event bus — the first thing a
multi-tenant orchestrator must guarantee is that tenant A's events never
fire tenant B's handlers. The pattern already exists in miniature:
``recsys.eval.evaluation_consumer(subject=)`` guards its ``Trained``
handler with *"is this event about my model?"* so two models sharing a
bus cannot cross-evaluate. This module generalizes that guard from one
handler to ANY consumer and stamps the subject at dispatch, so it works
for events that carry no ``model`` field at all (the whole serving
lifecycle — ``RequestCompleted`` has only an id):

* :func:`subject_of` — where an event's tenant identity lives: the
  ``tenant`` attribute a :class:`TenantBus` stamps, falling back to the
  ``model``/``model.id`` convention ``evaluation_consumer`` reads.
* :func:`scoped` — wrap any :class:`~tpusystem.services.prodcon.
  Consumer` so it only ever consumes its own tenant's events. Foreign
  and *unattributed* events are both dropped — on a multi-tenant bus an
  event nobody claimed is a wiring bug, and delivering it to everyone
  would be exactly the cross-job leak this layer exists to prevent.
* :class:`TenantBus` — one tenant's facade over the shared
  :class:`~tpusystem.services.prodcon.Producer`: ``dispatch`` stamps the
  tenant onto the event, ``register`` scopes the consumer. A job wired
  through its bus cannot observe (or be observed by) another job, yet
  fleet-wide taps (ledger, flight recorder) on the underlying producer
  still see the whole narrative.
* :class:`LeakAudit` — the certification witness: records every
  delivery whose subject is NOT the expected tenant, so the cross-tenant
  chaos drill can assert ``leaks == []`` instead of trusting the filter.
* :class:`NamespacedWriter` — the TensorBoard face of the same idea: a
  tag-prefixing wrapper over :class:`~tpusystem.observe.tensorboard.
  SummaryWriter`, so every tenant's ``serve/*``/``supervisor/*`` charts
  land under ``{tenant}/...`` in ONE logdir instead of overwriting each
  other.
"""

from __future__ import annotations

from typing import Any

from tpusystem.services.prodcon import Consumer, Producer

__all__ = ['subject_of', 'scoped', 'ScopedConsumer', 'TenantBus',
           'LeakAudit', 'NamespacedWriter']


def subject_of(event: Any) -> Any:
    """The tenant identity an event is about, or None when unattributed.

    Resolution order: the ``tenant`` attribute stamped by
    :meth:`TenantBus.dispatch`, then the ``model`` aggregate's ``id``
    (the ``evaluation_consumer`` convention), then the ``model`` object
    itself. Events shaped like neither (a bare ``RequestCompleted`` on a
    single-job bus) resolve to None — attributable only by stamping.
    """
    tenant = getattr(event, 'tenant', None)
    if tenant is not None:
        return tenant
    model = getattr(event, 'model', None)
    if model is None:
        return None
    return getattr(model, 'id', model)


class ScopedConsumer:
    """A consumer that only consumes its own tenant's events.

    Quacks like :class:`~tpusystem.services.prodcon.Consumer` for the
    producer's purposes (``consume`` is the whole fan-out surface);
    ``handlers``/``types``/``dependency_overrides`` proxy through so
    composition roots can keep wiring the inner consumer's DI seams
    after scoping it.
    """

    def __init__(self, inner: Consumer, subject: Any) -> None:
        self.inner = inner
        self.subject = subject
        self.name = f'{getattr(inner, "name", None) or "consumer"}' \
                    f'@{subject}'

    @property
    def handlers(self):
        return self.inner.handlers

    @property
    def types(self):
        return self.inner.types

    @property
    def dependency_overrides(self):
        return self.inner.dependency_overrides

    def matches(self, event: Any) -> bool:
        subject = subject_of(event)
        if subject is None:
            return False             # unattributed: nobody's business
        return subject is self.subject or subject == self.subject

    def consume(self, event: Any) -> None:
        if self.matches(event):
            self.inner.consume(event)


def scoped(consumer: Consumer, subject: Any) -> ScopedConsumer:
    """Scope ``consumer`` to one tenant on a shared bus — the
    ``evaluation_consumer(subject=)`` guard generalized to any consumer
    (serve metrics, sentinel charts, tensorboard, ...). Events whose
    :func:`subject_of` is a different tenant — or None — never reach the
    inner handlers."""
    return ScopedConsumer(consumer, subject)


class TenantBus:
    """One tenant's view of the shared control plane.

    ``dispatch`` stamps ``event.tenant = tenant`` before handing the
    event to the shared producer (events are plain dataclasses — the
    stamp rides the instance and packs with it through journals and
    ledgers); ``register`` scopes every consumer with :func:`scoped`.
    The result: a job wired entirely through its bus emits and observes
    exactly its own namespace, while taps on the shared producer (the
    hash-chain ledger, the flight recorder) still witness the fleet-wide
    stream in one order.

    Events that already carry a *different* tenant stamp are refused
    (``ValueError``) rather than silently re-stamped — re-attributing
    another job's event is precisely the corruption this layer guards
    against.
    """

    def __init__(self, producer: Producer, tenant: Any) -> None:
        if tenant is None:
            raise ValueError('a tenant bus needs a non-None tenant '
                             'identity — None is the "unattributed" '
                             'sentinel scoped consumers drop')
        self.producer = producer
        self.tenant = tenant

    def dispatch(self, event: Any) -> None:
        stamped = getattr(event, 'tenant', None)
        if stamped is not None and stamped != self.tenant:
            raise ValueError(
                f'event {type(event).__name__} already belongs to tenant '
                f'{stamped!r}; refusing to re-stamp it as {self.tenant!r}')
        try:
            event.tenant = self.tenant
        except AttributeError:       # frozen/slotted payloads still route
            object.__setattr__(event, 'tenant', self.tenant)
        self.producer.dispatch(event)

    def register(self, *consumers: Consumer) -> None:
        self.producer.register(*(scoped(consumer, self.tenant)
                                 for consumer in consumers))


class LeakAudit:
    """The negative witness for the chaos certifier: a consumer that
    records every event delivered to it whose subject is NOT ``tenant``.

    Register it UNSCOPED next to a tenant's scoped consumers — on a
    correctly namespaced bus it sees the whole stream and its ``leaks``
    list stays empty of that tenant's *deliveries* only if the scoped
    consumers were the ones filtering. The certifier instead registers
    it through the tenant's own wiring path: any foreign event that
    reaches it IS a cross-tenant leak, reported as
    ``(tenant, foreign_subject, event_type)``.
    """

    def __init__(self, tenant: Any) -> None:
        self.tenant = tenant
        self.leaks: list = []
        self.seen = 0

    def consume(self, event: Any) -> None:
        self.seen += 1
        subject = subject_of(event)
        if not (subject is self.tenant or subject == self.tenant):
            self.leaks.append((self.tenant, subject,
                               type(event).__name__))


class NamespacedWriter:
    """Tag-prefixing wrapper over a shared
    :class:`~tpusystem.observe.tensorboard.SummaryWriter`: every
    ``add_scalar('serve/tok_s', ...)`` lands as
    ``{prefix}/serve/tok_s``, so N tenants chart into one logdir
    without colliding. ``close`` only flushes — the underlying writer
    is shared and owned by the composition root."""

    def __init__(self, board: Any, prefix: str) -> None:
        if not prefix:
            raise ValueError('a namespaced writer needs a non-empty '
                             'prefix (the tenant name)')
        self.board = board
        self.prefix = prefix

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self.board.add_scalar(f'{self.prefix}/{tag}', value, step)

    def add_scalars(self, main_tag: str, values: dict, step: int) -> None:
        self.board.add_scalars(f'{self.prefix}/{main_tag}', values, step)

    def flush(self) -> None:
        self.board.flush()

    close = flush
