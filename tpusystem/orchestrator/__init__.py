"""Multi-tenant gang orchestration on one physical mesh.

One device pool, N jobs (train, serve fleet, recsys, periodic eval) as
isolated tenants — each under its own supervisor tree and control-plane
namespace, with journaled preemptive capacity arbitration between them
and a seeded cross-tenant chaos certifier over the lot. See
ROADMAP item 4 and docs/orchestrator coverage in docs/api/.

Layout mirrors the serve package's split:

* :mod:`~tpusystem.orchestrator.namespace` — blast-radius isolation:
  scoped consumers, tenant buses, leak audits, namespaced TB writers.
* :mod:`~tpusystem.orchestrator.gang` — specs, the carve planner, the
  :class:`Orchestrator` with two-phase journaled arbitration and
  SIGKILL recovery, the :class:`SupervisedRunner` adapter.
* :mod:`~tpusystem.orchestrator.journal` — the RouterJournal discipline
  under the ``orch:{name}`` identity namespace.
* :mod:`~tpusystem.orchestrator.certify` — the fleet-of-jobs chaos
  drill (seeded tenant × component × kill-tick).
"""

from tpusystem.orchestrator.certify import (TenantCertifyReport,
                                            TenantHarness, certify_tenants)
from tpusystem.orchestrator.gang import (CapacityError, JobSpec,
                                         Orchestrator, Submesh,
                                         SupervisedRunner, Tenant, carve,
                                         halt_reason)
from tpusystem.orchestrator.journal import (OrchestratorJournal,
                                            orchestrator_identity,
                                            recover_orchestrator_journal)
from tpusystem.orchestrator.namespace import (LeakAudit, NamespacedWriter,
                                              ScopedConsumer, TenantBus,
                                              scoped, subject_of)

__all__ = [
    'CapacityError', 'JobSpec', 'Submesh', 'carve', 'Tenant',
    'Orchestrator', 'SupervisedRunner', 'halt_reason',
    'OrchestratorJournal', 'orchestrator_identity',
    'recover_orchestrator_journal',
    'ScopedConsumer', 'scoped', 'subject_of', 'TenantBus', 'LeakAudit',
    'NamespacedWriter',
    'TenantHarness', 'TenantCertifyReport', 'certify_tenants',
]
