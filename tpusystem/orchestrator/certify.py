"""Cross-tenant chaos certification: kill one tenant's component, prove
every OTHER tenant never noticed.

:func:`~tpusystem.serve.certify.certify_fleet` certifies one fleet
against an undisturbed twin. This module lifts that drill one level to
the gang orchestrator's headline invariant — **blast radius**:

    for a seeded (tenant × component × kill-tick) draw, every
    *non-victim* tenant's final outputs (losses, token streams) are
    **bitwise-identical** to an undisturbed reference run, no tenant
    hangs, nothing settles twice, and no event crosses a tenant
    namespace; the victim itself either recovers bitwise or degrades
    **typed** (a halt verdict from the exit table, or a
    :data:`~tpusystem.serve.certify._DEGRADED_REASONS`-style reason on
    individual outputs).

All three draws come from one ``random.Random(seed)``
(:func:`~tpusystem.parallel.chaos.pick_tenant_chaos`), so the seed IS
the scenario — tier-1 pins a handful, the dryrun stage adds more, and a
red run replays exactly from the seed in its failure message.

The harness seam (:class:`TenantHarness`) keeps the certifier
environment-agnostic, like :class:`~tpusystem.serve.certify.
FleetHarness` before it: jobs are any drivers with ``step()`` /
``idle`` / ``outputs()``, kills are thunks, and the leak witness is
whatever the harness wires (typically
:class:`~tpusystem.orchestrator.namespace.LeakAudit` rows registered
through each tenant's bus).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable

from tpusystem.parallel.chaos import TenantChaosPick, pick_tenant_chaos

logger = logging.getLogger('tpusystem.orchestrator.certify')

__all__ = ['TenantHarness', 'TenantCertifyReport', 'certify_tenants']

# victim-output reasons that count as a typed degrade rather than a
# completion — the serve certifier's vocabulary plus the orchestrator's
# halt verdicts (docs/multihost.md#restart-exit-code-table)
_DEGRADED_REASONS = ('expired', 'shed', 'cancelled', 'diverged',
                     'crash-loop', 'fenced', 'failure', 'halted')


@dataclasses.dataclass
class TenantHarness:
    """One certifiable fleet-of-jobs.

    ``jobs`` maps tenant name -> driver. A driver exposes:

    * ``step()`` — advance the job one tick (a training step, a fleet
      router tick, a supervisor poll);
    * ``idle`` — True once the job finished its scripted work;
    * ``outputs() -> dict[key, (reason, tokens)]`` — the job's final
      observable record: losses keyed by step, completions keyed by
      request id — any ``(reason, value-tuple)`` shape, compared
      bitwise against the reference;
    * optionally ``duplicates`` (keys settled more than once) and
      ``verdict`` (the victim's typed terminal verdict, e.g.
      ``'halted'``/``'diverged'``, or None while healthy).

    ``kills`` maps tenant -> {component -> kill thunk}; every tenant
    must wire the SAME component set, so the seeded component draw is
    meaningful whichever tenant is the victim. ``advance`` runs once
    per drain tick (fake clocks breathe without real sleeps);
    ``leaks`` returns the cross-namespace deliveries witnessed so far
    (:class:`~tpusystem.orchestrator.namespace.LeakAudit` rows) —
    MUST stay empty."""

    jobs: dict[str, Any]
    kills: dict[str, dict[str, Callable[[], Any]]]
    advance: Callable[[], None] | None = None
    leaks: Callable[[], list] | None = None


@dataclasses.dataclass
class TenantCertifyReport:
    """One cross-tenant certification verdict; the seed replays it."""

    seed: int
    tenant: str                      # the victim tenant
    component: str                   # the component killed inside it
    step: int                        # the drain tick it died after
    exact: int                       # non-victim outputs bitwise-equal
    victim_exact: bool               # victim recovered bitwise
    victim_verdict: Any              # or its typed degrade verdict
    degraded: list                   # victim keys that failed typed
    mismatches: list                 # (tenant, key, why) — MUST be empty
    duplicates: list                 # (tenant, key) settled twice
    hung: list                       # tenants never idle in max_steps
    leaked: list                     # cross-namespace deliveries

    @property
    def ok(self) -> bool:
        victim_ok = self.victim_exact or self.victim_verdict is not None
        return victim_ok and not (self.mismatches or self.duplicates
                                  or self.hung or self.leaked)

    def summary(self) -> str:
        verdict = 'PASS' if self.ok else 'FAIL'
        victim = ('bitwise' if self.victim_exact
                  else f'degraded:{self.victim_verdict}')
        return (f'[{verdict}] seed={self.seed} '
                f'kill={self.tenant}/{self.component}@tick{self.step}: '
                f'{self.exact} non-victim outputs exact, victim {victim} '
                f'({len(self.degraded)} typed-degraded keys), '
                f'{len(self.mismatches)} mismatched, '
                f'{len(self.duplicates)} duplicated, '
                f'{len(self.hung)} hung, {len(self.leaked)} leaked')


def _drain(harness: TenantHarness, pick: TenantChaosPick | None,
           max_steps: int) -> dict:
    """Round-robin every job to idle, firing the pick's kill after its
    tick. The tick is the *drain loop's* (one pass over all jobs), so
    the kill lands at the same global moment whichever tenant it hits."""
    fired = pick is None
    ticks = 0
    for _ in range(max_steps):
        busy = [name for name, job in harness.jobs.items() if not job.idle]
        if not busy and fired:
            break
        for name in busy:
            harness.jobs[name].step()
        ticks += 1
        if not fired and ticks >= pick.step:
            fired = True
            logger.info('chaos: killing %r inside tenant %r after tick %d',
                        pick.component, pick.tenant, ticks)
            harness.kills[pick.tenant][pick.component]()
        if harness.advance is not None:
            harness.advance()
    hung = sorted(name for name, job in harness.jobs.items()
                  if not job.idle)
    outputs = {name: dict(job.outputs())
               for name, job in harness.jobs.items()}
    duplicates = sorted(
        (name, key) for name, job in harness.jobs.items()
        for key in getattr(job, 'duplicates', ()) or ())
    verdicts = {name: getattr(job, 'verdict', None)
                for name, job in harness.jobs.items()}
    leaked = list(harness.leaks()) if harness.leaks is not None else []
    return dict(outputs=outputs, hung=hung, duplicates=duplicates,
                verdicts=verdicts, leaked=leaked)


def certify_tenants(build: Callable[[], TenantHarness], *, seed: int,
                    components: tuple[str, ...] | None = None,
                    lo: int = 1, hi: int = 8,
                    max_steps: int = 10_000) -> TenantCertifyReport:
    """Certify one seeded cross-tenant chaos scenario against an
    undisturbed twin.

    ``build()`` constructs a fresh :class:`TenantHarness` — called
    twice, once for the reference (never killed; it MUST drain clean or
    the harness itself is broken) and once for chaos, so the two runs
    start bit-identical. The victim draw is
    :func:`~tpusystem.parallel.chaos.pick_tenant_chaos` over the
    harness's tenant names (sorted) and the shared component set;
    ``lo >= 1`` keeps the kill after every job has started. Returns a
    :class:`TenantCertifyReport`; red runs replay from ``seed`` alone.
    """
    if lo < 1:
        raise ValueError('lo must be >= 1: the kill lands after every '
                         'tenant has taken its first step, or start-up '
                         'itself races the chaos')
    reference = _drain(build(), None, max_steps)
    if reference['hung']:
        raise RuntimeError(
            f'the UNDISTURBED reference run never drained '
            f'({reference["hung"]}) — fix the harness before certifying '
            f'chaos against it')
    harness = build()
    tenants = tuple(sorted(harness.jobs))
    if sorted(harness.kills) != list(tenants):
        raise ValueError(
            f'kills must cover every tenant: jobs {list(tenants)} vs '
            f'kills {sorted(harness.kills)}')
    shared = {name: tuple(sorted(kills))
              for name, kills in harness.kills.items()}
    if len(set(shared.values())) != 1:
        raise ValueError(
            f'every tenant must wire the SAME component set so the '
            f'seeded component draw is meaningful for any victim; got '
            f'{shared}')
    available = (tuple(components) if components
                 else next(iter(shared.values())))
    missing = [name for name in available
               if name not in next(iter(shared.values()))]
    if missing:
        raise ValueError(f'harness has no kill thunk for {missing}; '
                         f'wired: {next(iter(shared.values()))}')
    pick = pick_tenant_chaos(seed, tenants, available, lo=lo, hi=hi)
    chaos = _drain(harness, pick, max_steps)

    mismatches: list = []
    degraded: list = []
    victim_missing: list = []
    exact = 0
    victim_exact = True
    victim_verdict = chaos['verdicts'].get(pick.tenant)
    for name in tenants:
        expected = reference['outputs'].get(name, {})
        observed = chaos['outputs'].get(name, {})
        victim = name == pick.tenant
        if not victim and set(observed) != set(expected):
            extra = sorted(set(observed) - set(expected))
            lost = sorted(set(expected) - set(observed))
            mismatches.append((name, '(keys)',
                               f'non-victim output keys diverged: '
                               f'+{extra} -{lost}'))
        for key, expected_row in expected.items():
            observed_row = observed.get(key)
            if observed_row is None:
                if victim:
                    victim_exact = False
                    victim_missing.append(key)
                else:
                    mismatches.append((name, key, 'missing under chaos'))
                continue
            reason, tokens = observed_row
            expected_reason, expected_tokens = expected_row
            if (reason, tuple(tokens)) == (expected_reason,
                                           tuple(expected_tokens)):
                if not victim:
                    exact += 1
                continue
            if victim:
                victim_exact = False
                if reason in _DEGRADED_REASONS:
                    degraded.append(key)    # a truthful typed downgrade
                else:
                    mismatches.append((name, key,
                                       f'untyped divergence: {reason!r} '
                                       f'vs {expected_reason!r}'))
            else:
                mismatches.append((name, key,
                                   f'non-victim output diverged: '
                                   f'{reason!r} vs {expected_reason!r} '
                                   f'or tokens differ'))
    if victim_missing:
        # a missing output is excused ONLY by the driver's own typed
        # verdict (a halt, a divergence) — never inferred, else a
        # silently dropped result would read as a degrade
        if victim_verdict is not None:
            degraded.extend(victim_missing)
        else:
            mismatches.extend(
                (pick.tenant, key, 'missing without a typed verdict')
                for key in victim_missing)
    if not victim_exact and degraded and victim_verdict is None:
        # per-key typed degrades are themselves a verdict
        victim_verdict = 'degraded'

    report = TenantCertifyReport(
        seed=seed, tenant=pick.tenant, component=pick.component,
        step=pick.step, exact=exact, victim_exact=victim_exact,
        victim_verdict=victim_verdict, degraded=sorted(degraded),
        mismatches=mismatches, duplicates=chaos['duplicates'],
        hung=chaos['hung'], leaked=chaos['leaked'])
    logger.info('%s', report.summary())
    return report
