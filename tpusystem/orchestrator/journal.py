"""The orchestrator's crash journal: RouterJournal discipline, new
identity namespace.

The gang orchestrator makes placement and arbitration *decisions* —
which tenant owns which devices, who is mid-shrink, what capacity debt
is owed back on ebb. Losing those to an orchestrator SIGKILL and
re-deciding them from scratch is exactly the double-resize hazard the
Router's journal was built to prevent for handoffs, so the orchestrator
rides the same machinery verbatim: digest-framed pickle (a torn copy
reads as :exc:`~tpusystem.serve.failover.JournalCorrupt`, i.e. absent),
a journal-owned monotonic tick, term-fenced store steps
(``term * 1_000_000 + tick`` — a deposed orchestrator's late pushes can
never overwrite its successor's), cadence-gated replication with
log-once degrade. Only the *identity namespace* is new:
``orch:{name}`` beside ``router:{name}`` and ``journal:{identity}``,
so the three planes never collide in one memstore.

Arbitration writes are journaled **two-phase**: the orchestrator
replicates a ``phase='decided'`` record *before* executing a resize and
a ``phase='done'`` record after — so recovery finds either a completed
decision to re-apply idempotently or an in-flight one to *finish*,
never a blank that would tempt it to re-decide (see
:meth:`tpusystem.orchestrator.gang.Orchestrator.recover`).
"""

from __future__ import annotations

from typing import Any

from tpusystem.serve.failover import (JournalCorrupt, RouterJournal,
                                      recover_router_journal)

__all__ = ['orchestrator_identity', 'OrchestratorJournal',
           'recover_orchestrator_journal', 'JournalCorrupt']


def orchestrator_identity(name: str = 'orchestrator') -> str:
    """The memstore identity an orchestrator's journal travels under —
    its own namespace (``orch:{name}``) beside ``router:{name}`` and
    ``journal:{identity}``, riding the identical push/replicate/buddy
    machinery."""
    return f'orch:{name}'


class OrchestratorJournal(RouterJournal):
    """:class:`~tpusystem.serve.failover.RouterJournal` under the
    orchestrator's identity namespace. The schema is the orchestrator's
    business (:meth:`tpusystem.orchestrator.gang.Orchestrator.snapshot`
    builds the state dict); this class inherits the framing, tick, term
    fencing, and degrade disciplines unchanged."""

    def __init__(self, name: str = 'orchestrator', *, client: Any = None,
                 cadence: int = 1) -> None:
        super().__init__(name, client=client, cadence=cadence)
        self.identity = orchestrator_identity(name)


def recover_orchestrator_journal(name: str,
                                 clients: Any) -> tuple[int, dict] | None:
    """Fetch and verify the newest orchestrator journal for ``name``
    from the first client with an intact copy — ``clients`` in
    preference order, :func:`~tpusystem.serve.failover.recover_journal`'s
    contract: a corrupt copy logs and falls through, never restores."""

    class _Scoped:
        """Adapter presenting ``router_identity``-keyed fetches under
        the orchestrator namespace, so the recover loop is reused
        byte-for-byte."""

        def __init__(self, client: Any) -> None:
            self.client = client

        def fetch(self, identity: str) -> Any:
            name_part = identity.split(':', 1)[1]
            return self.client.fetch(orchestrator_identity(name_part))

    return recover_router_journal(
        name, [None if client is None else _Scoped(client)
               for client in clients])
