"""Document-store adapters for the storage ports.

Reference parity: the TinyDB adapters
(``examples/tinysys/tinysys/adapters/*.py``) including the latest-hash
upsert semantics of ``Modules.put`` (``adapters/modules.py:33-41``) and the
phase-keyed upsert of ``Iterations.put`` (``adapters/iterations.py:22-29``).
"""

from __future__ import annotations

from tpusystem.storage.documents import DocumentStore, where
from tpusystem.storage import ports
from tpusystem.storage.ports import (
    Experiment, Iteration, Metric, Model, Module, structure, unstructure,
)


class DocumentExperiments(ports.Experiments):
    def __init__(self, store: DocumentStore) -> None:
        self.table = store.table('experiments')

    def create(self, experiment: Experiment) -> int:
        existing = self.table.get(where(name=experiment.name))
        if existing is not None:
            return existing['id']
        payload = unstructure(experiment)
        payload['id'] = self.table.insert(payload)
        self.table.update({'id': payload['id']}, where(name=experiment.name))
        return payload['id']

    def get(self, name: str) -> Experiment | None:
        payload = self.table.get(where(name=name))
        return structure(payload, Experiment) if payload else None

    def list(self) -> list[Experiment]:
        return [structure(payload, Experiment) for payload in self.table.all()]

    def remove(self, name: str) -> None:
        self.table.remove(where(name=name))


class DocumentModels(ports.Models):
    def __init__(self, store: DocumentStore) -> None:
        self.table = store.table('models')

    def create(self, model: Model) -> None:
        if self.read(model.hash, model.experiment) is None:
            self.table.insert(unstructure(model))

    def read(self, hash: str, experiment: str) -> Model | None:
        payload = self.table.get(where(hash=hash, experiment=experiment))
        return structure(payload, Model) if payload else None

    def update(self, model: Model) -> None:
        matched = self.table.update(
            {'epoch': model.epoch}, where(hash=model.hash, experiment=model.experiment))
        if not matched:
            self.table.insert(unstructure(model))

    def delete(self, hash: str, experiment: str) -> None:
        self.table.remove(where(hash=hash, experiment=experiment))

    def list(self, experiment: str) -> list[Model]:
        return [structure(payload, Model)
                for payload in self.table.search(where(experiment=experiment))]


class DocumentModules(ports.Modules):
    def __init__(self, store: DocumentStore) -> None:
        self.table = store.table('modules')

    def put(self, module: Module) -> None:
        rows = self.table.search(where(model=module.model, kind=module.kind))
        if rows and rows[-1]['hash'] == module.hash:
            # bump only the *latest* row: earlier rows with the same hash are
            # history (hyperparameters changed away and back) and must keep
            # the epochs at which they were recorded
            self.table.update_last(
                {'epoch': module.epoch},
                where(model=module.model, kind=module.kind, hash=module.hash))
        else:
            self.table.insert(unstructure(module))

    def list(self, model: str) -> list[Module]:
        return [structure(payload, Module)
                for payload in self.table.search(where(model=model))]


class DocumentMetrics(ports.Metrics):
    def __init__(self, store: DocumentStore) -> None:
        self.table = store.table('metrics')

    def add(self, metric: Metric) -> None:
        self.table.insert(unstructure(metric))

    def list(self, model: str) -> list[Metric]:
        return [structure(payload, Metric)
                for payload in self.table.search(where(model=model))]

    def clear(self, model: str) -> None:
        self.table.remove(where(model=model))


class DocumentIterations(ports.Iterations):
    def __init__(self, store: DocumentStore) -> None:
        self.table = store.table('iterations')

    def put(self, iteration: Iteration) -> None:
        rows = self.table.search(where(model=iteration.model, phase=iteration.phase))
        if rows and rows[-1]['hash'] == iteration.hash:
            self.table.update_last(
                {'epoch': iteration.epoch},
                where(model=iteration.model, phase=iteration.phase,
                      hash=iteration.hash))
        else:
            self.table.insert(unstructure(iteration))

    def list(self, model: str) -> list[Iteration]:
        return [structure(payload, Iteration)
                for payload in self.table.search(where(model=model))]
