"""Ports: typed records + abstract storage interfaces.

Reference parity: ``examples/tinysys/tinysys/ports/{models,modules,metrics,
iterations,experiments}.py`` define ``attrs`` records and ABCs; services and
consumers depend only on these, adapters implement them. Here the records
are stdlib dataclasses and ``structure``/``unstructure`` replace cattrs.

All records key on the **registry hash** (deterministic identity —
:func:`tpusystem.registry.gethash`), so rows written on one host of a pod
are meaningful to every other host and to post-hoc analysis tools.
"""

from __future__ import annotations

import dataclasses
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, TypeVar

R = TypeVar('R')


def unstructure(record: Any) -> dict[str, Any]:
    """Record -> plain JSON-ready dict."""
    return dataclasses.asdict(record)


def structure(payload: dict[str, Any], kind: type[R]) -> R:
    """Plain dict -> record, ignoring unknown keys (forward compatibility)."""
    names = {f.name for f in dataclasses.fields(kind)}
    return kind(**{key: value for key, value in payload.items() if key in names})


@dataclass
class Experiment:
    """A named collection of model runs (``ports/experiments.py:11-25``)."""
    name: str
    id: int | None = None


@dataclass
class Model:
    """One trainable entity inside an experiment: its identity hash and the
    last completed epoch (``ports/models.py:20-41``)."""
    hash: str
    experiment: str
    epoch: int = 0


@dataclass
class Module:
    """Captured metadata of a network/criterion/optimizer attached to a
    model row (``ports/modules.py:14-25``)."""
    model: str                      # owning model's hash
    kind: str                       # 'nn' | 'criterion' | 'optimizer' | ...
    hash: str | None
    name: str
    arguments: dict[str, Any] = field(default_factory=dict)
    epoch: int = 0


@dataclass
class Metric:
    """One scalar metric point (``ports/metrics.py:11-19``)."""
    model: str
    name: str
    value: float
    epoch: int
    phase: str


@dataclass
class Iteration:
    """Data-pipeline configuration used for a phase at an epoch
    (``ports/iterations.py:12-23``)."""
    model: str
    phase: str
    hash: str | None
    name: str
    arguments: dict[str, Any] = field(default_factory=dict)
    epoch: int = 0


class Experiments(ABC):
    @abstractmethod
    def create(self, experiment: Experiment) -> int: ...

    @abstractmethod
    def get(self, name: str) -> Experiment | None: ...

    @abstractmethod
    def list(self) -> list[Experiment]: ...

    @abstractmethod
    def remove(self, name: str) -> None: ...


class Models(ABC):
    @abstractmethod
    def create(self, model: Model) -> None: ...

    @abstractmethod
    def read(self, hash: str, experiment: str) -> Model | None: ...

    @abstractmethod
    def update(self, model: Model) -> None: ...

    @abstractmethod
    def delete(self, hash: str, experiment: str) -> None: ...

    @abstractmethod
    def list(self, experiment: str) -> list[Model]: ...


class Modules(ABC):
    @abstractmethod
    def put(self, module: Module) -> None:
        """Upsert: when the latest stored row for (model, kind) carries the
        same hash, update its epoch in place; otherwise insert a new row —
        the reference's dedupe contract (``adapters/modules.py:33-41``),
        which records *when hyperparameters changed* rather than one row per
        epoch."""

    @abstractmethod
    def list(self, model: str) -> list[Module]: ...


class Metrics(ABC):
    @abstractmethod
    def add(self, metric: Metric) -> None: ...

    @abstractmethod
    def list(self, model: str) -> list[Metric]: ...

    @abstractmethod
    def clear(self, model: str) -> None: ...


class Iterations(ABC):
    @abstractmethod
    def put(self, iteration: Iteration) -> None:
        """Upsert keyed by (model, phase) with the same latest-hash dedupe as
        :meth:`Modules.put` (``adapters/iterations.py:22-29``)."""

    @abstractmethod
    def list(self, model: str) -> list[Iteration]: ...
