"""Zero-dependency JSON document store (the TinyDB seam, rebuilt).

The reference's adapters sit on TinyDB tables
(``examples/tinysys/tinysys/adapters/*.py``); this environment ships no
TinyDB, and the framework should not depend on one — the store is ~100
lines: named tables of JSON documents with insert/search/update/remove,
each document addressed by a monotonically increasing integer id.

Durability: every mutation rewrites the file atomically (temp file +
``os.replace``), so a preempted TPU-VM worker never leaves a torn database —
relevant because checkpoint-resume decisions read these rows
(SURVEY.md §3.5). For metric streams at scale prefer batched writes
(``Table.insert_many``).
"""

from __future__ import annotations

import json
import os
import pathlib
import threading
from collections.abc import Callable, Iterable
from typing import Any

Document = dict[str, Any]
Predicate = Callable[[Document], bool]


def where(**fields: Any) -> Predicate:
    """Predicate matching documents whose fields equal the given values."""
    def match(doc: Document) -> bool:
        return all(doc.get(key) == value for key, value in fields.items())
    return match


class Table:
    """One named collection of documents inside a :class:`DocumentStore`."""

    def __init__(self, store: 'DocumentStore', name: str) -> None:
        self._store = store
        self.name = name

    def _data(self) -> dict[str, Document]:
        return self._store._tables.setdefault(self.name, {})

    def insert(self, document: Document) -> int:
        """Insert a document; returns its id."""
        return self.insert_many([document])[0]

    def insert_many(self, documents: Iterable[Document]) -> list[int]:
        with self._store._lock:
            table = self._data()
            ids = []
            for document in documents:
                identifier = self._store._next_id(self.name)
                table[str(identifier)] = dict(document)
                ids.append(identifier)
            self._store._flush()
            return ids

    def search(self, predicate: Predicate) -> list[Document]:
        with self._store._lock:
            return [dict(doc) for doc in self._data().values() if predicate(doc)]

    def get(self, predicate: Predicate) -> Document | None:
        found = self.search(predicate)
        return found[0] if found else None

    def all(self) -> list[Document]:
        with self._store._lock:
            return [dict(doc) for doc in self._data().values()]

    def update_last(self, changes: Document, predicate: Predicate) -> int:
        """Apply field changes to the *latest* matching document only (the
        one with the highest id); returns 1 if a document matched, else 0."""
        with self._store._lock:
            matched = [key for key, doc in self._data().items()
                       if predicate(doc)]
            if not matched:
                return 0
            last = max(matched, key=int)
            self._data()[last].update(changes)
            self._store._flush()
            return 1

    def update(self, changes: Document, predicate: Predicate) -> int:
        """Apply field changes to matching documents; returns match count."""
        with self._store._lock:
            count = 0
            for doc in self._data().values():
                if predicate(doc):
                    doc.update(changes)
                    count += 1
            if count:
                self._store._flush()
            return count

    def remove(self, predicate: Predicate) -> int:
        with self._store._lock:
            table = self._data()
            doomed = [key for key, doc in table.items() if predicate(doc)]
            for key in doomed:
                del table[key]
            if doomed:
                self._store._flush()
            return len(doomed)

    def clear(self) -> None:
        with self._store._lock:
            self._data().clear()
            self._store._flush()

    def __len__(self) -> int:
        with self._store._lock:
            return len(self._data())


class DocumentStore:
    """A JSON file of named tables; safe for concurrent in-process use."""

    def __init__(self, path: str | os.PathLike) -> None:
        self.path = pathlib.Path(path)
        self._lock = threading.RLock()
        self._tables: dict[str, dict[str, Document]] = {}
        self._counters: dict[str, int] = {}
        if self.path.exists():
            with open(self.path) as handle:
                payload = json.load(handle)
            self._tables = payload.get('tables', {})
            self._counters = payload.get('counters', {})

    def table(self, name: str) -> Table:
        return Table(self, name)

    def _next_id(self, table: str) -> int:
        nxt = self._counters.get(table, 0) + 1
        self._counters[table] = nxt
        return nxt

    def _flush(self) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        scratch = self.path.with_suffix(self.path.suffix + '.tmp')
        with open(scratch, 'w') as handle:
            json.dump({'tables': self._tables, 'counters': self._counters}, handle)
        os.replace(scratch, self.path)

    def close(self) -> None:
        with self._lock:
            self._flush()
