"""Experiment-tracking storage.

The reference persists experiment state (model rows, module metadata, metric
curves, loader configs) through a ports-and-adapters boundary: ``attrs``
records + ABCs in ``examples/tinysys/tinysys/ports/`` and TinyDB tables in
``examples/tinysys/tinysys/adapters/``. Here the same boundary is part of
the framework: typed records + port protocols (:mod:`tpusystem.storage.ports`)
and a zero-dependency JSON document store
(:mod:`tpusystem.storage.documents`) backing the default adapters
(:mod:`tpusystem.storage.adapters`).

Multi-host note: storage is a *host-side, rank-0 concern* — consumer
placement (:mod:`tpusystem.runtime`) routes storage consumers to one process
so a pod never writes the same row N times.
"""

from tpusystem.storage.documents import DocumentStore
from tpusystem.storage.ports import (
    Experiment, Experiments, Iteration, Iterations, Metric, Metrics,
    Model, Models, Module, Modules, structure, unstructure,
)
from tpusystem.storage.adapters import (
    DocumentExperiments, DocumentIterations, DocumentMetrics,
    DocumentModels, DocumentModules,
)

__all__ = [
    'DocumentStore',
    'Experiment', 'Model', 'Module', 'Metric', 'Iteration',
    'Experiments', 'Models', 'Modules', 'Metrics', 'Iterations',
    'DocumentExperiments', 'DocumentModels', 'DocumentModules',
    'DocumentMetrics', 'DocumentIterations',
    'structure', 'unstructure',
]
