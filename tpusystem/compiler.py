"""Aggregate compilation pipeline.

Building an aggregate for TPU execution is a staged process: construct the
module tree, place parameters on the device mesh with their shardings, lower
the train/eval steps through ``jax.jit``/GSPMD, then restore state from a
checkpoint keyed by the aggregate's identity. Each stage may need runtime
facts (the mesh, the checkpoint store, the resume epoch) that only exist at
composition time — so steps are DI-injected callables, mirroring the
reference ``Compiler`` (``torchsystem/compiler.py:105-168``).

Chaining contract: the first step receives ``compile(*args)``'s arguments; a
step returning a tuple is splatted into the next step; any other value is
passed as the single argument. A step returning ``None`` is treated as a
side-effect stage: the next step receives the latest produced value — or the
original ``compile(*args, **kwargs)`` arguments when no step has produced a
value yet. This is a deliberate cleanup of the reference's falsy-result quirk
(``torchsystem/compiler.py:164`` re-sends the original args whenever a step
returns *any* falsy value; here only ``None`` passes through).
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any, Generic, TypeVar

import jax

from tpusystem.depends import Depends as Depends  # re-export for pipelines
from tpusystem.depends import Provider, inject

T = TypeVar('T')

_PENDING = object()  # no step has produced a value yet

# The TPU analogue of the reference re-exporting ``torch.compile``
# (``torchsystem/compiler.py:22``): pipelines call ``compile(step_fn, ...)``
# to lower pure step functions for the mesh.
compile = jax.jit


class Compiler(Generic[T]):
    """DI-aware pipeline of build steps producing a compiled aggregate."""

    def __init__(self, *, provider: Provider | None = None) -> None:
        self.steps: list[Callable] = []
        self.provider = provider or Provider()

    @property
    def dependency_overrides(self) -> dict:
        """Override table for late-binding runtime facts into steps.

        Example::

            compiler.dependency_overrides[mesh] = lambda: Mesh(jax.devices(), ('data',))
        """
        return self.provider.dependency_overrides

    def step(self, callable: Callable) -> Callable:
        """Register a pipeline stage (decorator). Returns the injected fn."""
        injected = inject(self.provider)(callable)
        self.steps.append(injected)
        return injected

    def compile(self, *args, **kwargs) -> T | Any | None:
        """Run the pipeline; the last stage's product is the aggregate."""
        current: Any = _PENDING
        for step in self.steps:
            if current is _PENDING:
                produced = step(*args, **kwargs)
            elif isinstance(current, tuple):
                produced = step(*current)
            else:
                produced = step(current)
            if produced is not None:
                current = produced
        return None if current is _PENDING else current
