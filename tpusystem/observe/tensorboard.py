"""TensorBoard scalar logging without the tensorboard package.

The reference uses ``torch.utils.tensorboard.SummaryWriter``
(``examples/tinysys/tinysys/services/tensorboard.py``); this environment
ships no tensorboard, so the writer speaks the on-disk format directly —
it is small and stable:

* an event file is a **TFRecord** stream: for each record,
  ``uint64 length | uint32 masked-crc32c(length) | payload |
  uint32 masked-crc32c(payload)``;
* each payload is a serialized ``tensorflow.Event`` protobuf: field 1
  ``wall_time`` (double), field 2 ``step`` (int64), field 3
  ``file_version`` (string, first record only), field 5 ``summary`` —
  a ``Summary`` of repeated ``Summary.Value`` {tag: field 1, simple_value:
  field 2}.

Both are hand-encoded here (varint/fixed encoders + a table-driven CRC32C),
so any TensorBoard install can read the runs this framework writes.
"""

from __future__ import annotations

import os
import pathlib
import socket
import struct
import time

from tpusystem.observe.events import (AnomalyDetected, BackoffApplied,
                                      Backpressure, CapacityArbitrated,
                                      ElasticTimeline, EngineRestarted,
                                      FleetResized, HandoffCorrupted,
                                      JobAdmitted, JobHalted, JobPreempted,
                                      LoadShed, PrefillHandoff,
                                      RecoveryTimeline, RecsysEvaluated,
                                      ReplicaDiverged, ReplicaUnhealthy,
                                      RequestAdmitted, RequestExpired,
                                      RequestRerouted, RoleMismatched,
                                      RolledBack, RouterTakeover,
                                      ServeStepped, Trained, Validated,
                                      WorkerExited, WorldResized)
from tpusystem.services.prodcon import Consumer, Depends

# ---------------------------------------------------------------- crc32c ---

_CRC_TABLE = []
for _index in range(256):
    _crc = _index
    for _ in range(8):
        _crc = (_crc >> 1) ^ (0x82F63B78 if _crc & 1 else 0)  # Castagnoli poly
    _CRC_TABLE.append(_crc)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for byte in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ byte) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- protobuf ---

def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _double_field(field: int, value: float) -> bytes:
    return _tag(field, 1) + struct.pack('<d', value)


def _float_field(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack('<f', value)


def _int_field(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value & 0xFFFFFFFFFFFFFFFF)


def _bytes_field(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


def _scalar_event(tag: str, value: float, step: int, wall_time: float) -> bytes:
    summary_value = (_bytes_field(1, tag.encode()) + _float_field(2, value))
    summary = _bytes_field(1, summary_value)
    return (_double_field(1, wall_time) + _int_field(2, step)
            + _bytes_field(5, summary))


def _version_event(wall_time: float) -> bytes:
    return _double_field(1, wall_time) + _bytes_field(3, b'brain.Event:2')


# --------------------------------------------------------------- writer ---

class SummaryWriter:
    """Append-only TensorBoard event-file writer for scalar curves."""

    def __init__(self, logdir: str | os.PathLike) -> None:
        self.logdir = pathlib.Path(logdir)
        self.logdir.mkdir(parents=True, exist_ok=True)
        stamp = time.time()
        name = f'events.out.tfevents.{stamp:.0f}.{socket.gethostname()}.{os.getpid()}'
        self._handle = open(self.logdir / name, 'ab')
        self._record(_version_event(stamp))
        self.flush()

    def _record(self, payload: bytes) -> None:
        header = struct.pack('<Q', len(payload))
        self._handle.write(header)
        self._handle.write(struct.pack('<I', _masked_crc(header)))
        self._handle.write(payload)
        self._handle.write(struct.pack('<I', _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._record(_scalar_event(tag, float(value), int(step), time.time()))

    def add_scalars(self, main_tag: str, values: dict[str, float], step: int) -> None:
        """Scalars under ``{main_tag}/{name}`` (flat-file variant of the
        torch API the reference calls — ``tensorboard.py:17-19``)."""
        for name, value in values.items():
            self.add_scalar(f'{main_tag}/{name}', value, step)

    def flush(self) -> None:
        self._handle.flush()

    def close(self) -> None:
        self._handle.flush()
        self._handle.close()


# ------------------------------------------------------------- consumer ---

def writer() -> SummaryWriter:
    """DI seam for the summary writer — override at the composition root::

        def tensorboard():            # generator dep: flushes on teardown
            writer = SummaryWriter('data/runs')
            yield writer
            writer.close()
        consumer.dependency_overrides[writer] = tensorboard
    """
    raise NotImplementedError('override the tensorboard writer dependency')


def tensorboard_consumer() -> Consumer:
    """Consumer charting ``{model.id}/{metric}`` per phase at each epoch."""
    consumer = Consumer('tensorboard')

    @consumer.handler
    def on_metrics(event: Trained | Validated,
                   board: SummaryWriter = Depends(writer)) -> None:
        phase = 'train' if isinstance(event, Trained) else 'evaluation'
        for name, value in event.metrics.items():
            board.add_scalar(f'{event.model.id}/{name}/{phase}', value,
                             getattr(event.model, 'epoch', 0))

    def _subject(model) -> str:
        return str(getattr(model, 'id', model))

    # recommender quality at phase cadence: the streaming evaluator's
    # rank metrics (auc / recall@k) charted per epoch next to the loss,
    # so a ranking regression reads straight off the dashboard

    @consumer.handler
    def on_recsys_evaluated(event: RecsysEvaluated,
                            board: SummaryWriter = Depends(writer)) -> None:
        epoch = getattr(event.model, 'epoch', 0)
        for name, value in event.metrics.items():
            board.add_scalar(f'{_subject(event.model)}/recsys/{name}',
                             value, epoch)

    # sentinel ladder: each transition charted at its global step, so a
    # loss-spike investigation reads straight off the run's dashboard

    @consumer.handler
    def on_anomaly(event: AnomalyDetected,
                   board: SummaryWriter = Depends(writer)) -> None:
        tag = f'{_subject(event.model)}/sentinel'
        board.add_scalar(f'{tag}/anomaly', 1.0, event.step)
        if event.kind == 'spike':     # non-finite values break TB charts
            board.add_scalar(f'{tag}/spike_zscore', event.zscore, event.step)

    @consumer.handler
    def on_backoff(event: BackoffApplied,
                   board: SummaryWriter = Depends(writer)) -> None:
        board.add_scalar(f'{_subject(event.model)}/sentinel/lr_scale',
                         event.scale, event.step)

    @consumer.handler
    def on_rollback(event: RolledBack,
                    board: SummaryWriter = Depends(writer)) -> None:
        board.add_scalar(f'{_subject(event.model)}/sentinel/rollback_to',
                         float(event.to_step), event.step)

    @consumer.handler
    def on_replica_diverged(event: ReplicaDiverged,
                            board: SummaryWriter = Depends(writer)) -> None:
        board.add_scalar(f'{_subject(event.model)}/sentinel/sdc_replicas',
                         float(len(event.replicas)), event.step or 0)

    # supervisor recovery loop: worker exits and full detect→first-step
    # MTTR, charted per rank so a restart storm or a slow restore reads
    # straight off the dashboard. Exits have no global step, so they are
    # charted against a per-rank exit counter — ten crash-loop exits read
    # as ten points, not one overplotted pile at x=0.
    exit_counts: dict[int, int] = {}

    @consumer.handler
    def on_worker_exited(event: WorkerExited,
                         board: SummaryWriter = Depends(writer)) -> None:
        exit_counts[event.rank] = exit_counts.get(event.rank, 0) + 1
        board.add_scalar(f'supervisor/rank{event.rank}/exit_code',
                         float(event.code), exit_counts[event.rank])

    # serving engine: queue depth and throughput per scheduler step, and
    # time-to-first-token per admission (charted against an admission
    # counter — requests have no global step), so a latency or backlog
    # incident reads straight off the dashboard
    admit_counts = [0]

    @consumer.handler
    def on_request_admitted(event: RequestAdmitted,
                            board: SummaryWriter = Depends(writer)) -> None:
        admit_counts[0] += 1
        board.add_scalar('serve/ttft_seconds', event.ttft, admit_counts[0])
        board.add_scalar('serve/queue_depth_at_admit',
                         float(event.queue_depth), admit_counts[0])

    @consumer.handler
    def on_serve_stepped(event: ServeStepped,
                         board: SummaryWriter = Depends(writer)) -> None:
        board.add_scalar('serve/queue_depth', float(event.queue_depth),
                         event.step)
        board.add_scalar('serve/active_rows', float(event.active),
                         event.step)
        board.add_scalar('serve/tok_s', event.tokens_per_sec, event.step)
        # sampled-traffic gauge (getattr: replayed event streams may
        # carry pre-sampling ServeStepped payloads without the field)
        board.add_scalar('serve/sampled_rows',
                         float(getattr(event, 'sampled', 0)), event.step)

    # deadline expiries: charted against an expiry counter (requests have
    # no global step), split by where the request died — a queue full of
    # expiries reads as saturation, active expiries as slow decode
    expire_counts = [0]

    @consumer.handler
    def on_request_expired(event: RequestExpired,
                           board: SummaryWriter = Depends(writer)) -> None:
        expire_counts[0] += 1
        board.add_scalar('serve/expired_total', float(expire_counts[0]),
                         expire_counts[0])
        board.add_scalar(f'serve/expired_waited_{event.where}',
                         event.waited, expire_counts[0])

    # serving failover: engine relaunches (recovery MTTR + how many rows
    # replayed hot vs resubmitted cold), watermark sheds, and the
    # backpressure flag — a chaos incident or an overload wave reads
    # straight off the dashboard. Restarts and sheds have no global
    # step, so they chart against their own counters.
    restart_counts = [0]
    shed_counts = [0]
    backpressure_counts = [0]

    @consumer.handler
    def on_engine_restarted(event: EngineRestarted,
                            board: SummaryWriter = Depends(writer)) -> None:
        restart_counts[0] += 1
        board.add_scalar('serve/recovery_seconds', event.seconds,
                         restart_counts[0])
        board.add_scalar('serve/replayed', float(event.replayed),
                         restart_counts[0])
        board.add_scalar('serve/resubmitted', float(event.resubmitted),
                         restart_counts[0])

    @consumer.handler
    def on_load_shed(event: LoadShed,
                     board: SummaryWriter = Depends(writer)) -> None:
        # per shed event (x = shed counter): the queue depth that
        # triggered it — how overloaded the replica actually was — and
        # the victim's remaining deadline slack where it had one
        shed_counts[0] += 1
        board.add_scalar('serve/shed', float(event.queue_depth or 0),
                         shed_counts[0])
        if event.slack is not None:
            board.add_scalar('serve/shed_slack', event.slack,
                             shed_counts[0])

    @consumer.handler
    def on_backpressure(event: Backpressure,
                        board: SummaryWriter = Depends(writer)) -> None:
        backpressure_counts[0] += 1
        board.add_scalar('serve/backpressure',
                         1.0 if event.engaged else 0.0,
                         backpressure_counts[0])

    # fleet tier: health verdicts, reroutes and resizes have no global
    # step, so each charts against its own counter — a failover incident
    # (verdict → N reroutes → maybe a grow) reads straight off the
    # fleet/* dashboard next to the per-replica serve/* rows
    unhealthy_counts = [0]
    reroute_counts = [0]
    resize_counts = [0]

    @consumer.handler
    def on_replica_unhealthy(event: ReplicaUnhealthy,
                             board: SummaryWriter = Depends(writer)) -> None:
        unhealthy_counts[0] += 1
        board.add_scalar('fleet/unhealthy_total', float(unhealthy_counts[0]),
                         unhealthy_counts[0])
        board.add_scalar('fleet/rehomed_requests', float(event.routed),
                         unhealthy_counts[0])

    @consumer.handler
    def on_request_rerouted(event: RequestRerouted,
                            board: SummaryWriter = Depends(writer)) -> None:
        reroute_counts[0] += 1
        board.add_scalar('fleet/rerouted_total', float(reroute_counts[0]),
                         reroute_counts[0])
        # per reroute: how much already-emitted work the hot handoff
        # carried over (0 = a cold re-submit re-decodes everything)
        board.add_scalar('fleet/reroute_prefix', float(event.prefix),
                         reroute_counts[0])

    @consumer.handler
    def on_fleet_resized(event: FleetResized,
                         board: SummaryWriter = Depends(writer)) -> None:
        resize_counts[0] += 1
        board.add_scalar('fleet/replicas', float(event.replicas),
                         resize_counts[0])

    handoff_counts = [0]

    @consumer.handler
    def on_prefill_handoff(event: PrefillHandoff,
                           board: SummaryWriter = Depends(writer)) -> None:
        handoff_counts[0] += 1
        board.add_scalar('fleet/handoffs_total', float(handoff_counts[0]),
                         handoff_counts[0])
        # the KV weight each disaggregated move ships over the blob
        # plane — the interconnect cost of splitting prefill from decode
        board.add_scalar('fleet/handoff_bytes', float(event.bytes),
                         handoff_counts[0])

    # disaggregation integrity + router takeover: each of these SHOULD
    # chart flat at zero (corrupt handoffs and role mismatches are
    # recovered typed, but a rising rate means the blob plane or the
    # role map is sick); a takeover charts its MTTR ingredients
    corrupt_counts = [0]
    mismatch_counts = [0]
    takeover_counts = [0]

    @consumer.handler
    def on_handoff_corrupted(event: HandoffCorrupted,
                             board: SummaryWriter = Depends(writer)) -> None:
        corrupt_counts[0] += 1
        board.add_scalar('serve/handoff_corrupt', float(corrupt_counts[0]),
                         corrupt_counts[0])

    @consumer.handler
    def on_role_mismatched(event: RoleMismatched,
                           board: SummaryWriter = Depends(writer)) -> None:
        mismatch_counts[0] += 1
        board.add_scalar('serve/role_mismatch', float(mismatch_counts[0]),
                         mismatch_counts[0])

    @consumer.handler
    def on_router_takeover(event: RouterTakeover,
                           board: SummaryWriter = Depends(writer)) -> None:
        takeover_counts[0] += 1
        board.add_scalar('fleet/takeover_seconds', event.seconds,
                         takeover_counts[0])
        board.add_scalar('fleet/takeover_reseated', float(event.reseated),
                         takeover_counts[0])
        board.add_scalar('fleet/takeover_replaced', float(event.replaced),
                         takeover_counts[0])
        # 1.0 = the router journal survived (hot rebuild); 0.0 = the
        # health sweep alone rebuilt the tables (cold)
        board.add_scalar('fleet/takeover_hot',
                         1.0 if event.source == 'journal' else 0.0,
                         takeover_counts[0])

    @consumer.handler
    def on_recovery(event: RecoveryTimeline,
                    board: SummaryWriter = Depends(writer)) -> None:
        tag = f'supervisor/rank{event.rank}/recovery_seconds'
        board.add_scalar(tag, event.seconds, event.step or 0)
        if event.source is not None:     # 1.0 = hot (RAM), 0.0 = disk
            board.add_scalar(f'supervisor/rank{event.rank}/restore_hot',
                             1.0 if event.source == 'hot' else 0.0,
                             event.step or 0)

    # elastic resizes: world size over membership epochs plus the two
    # latencies that matter — wave-open → commit (the settle/agreement
    # cost) and wave-open → resumed (the whole reshard) — so a
    # preemption-wave incident reads straight off the dashboard
    @consumer.handler
    def on_world_resized(event: WorldResized,
                         board: SummaryWriter = Depends(writer)) -> None:
        board.add_scalar('elastic/world_size', float(event.size), event.epoch)
        board.add_scalar('elastic/commit_seconds', event.seconds, event.epoch)

    @consumer.handler
    def on_elastic_timeline(event: ElasticTimeline,
                            board: SummaryWriter = Depends(writer)) -> None:
        board.add_scalar('elastic/resize_seconds', event.seconds, event.epoch)
        if event.source is not None:   # 1.0 = hot reshard (RAM), 0.0 = disk
            board.add_scalar('elastic/reshard_hot',
                             1.0 if event.source == 'hot-reshard' else 0.0,
                             event.epoch)

    # orchestrator/* dashboard — the multi-tenant gang narrative; the
    # events are step-less (admissions and arbitrations are sparse), so
    # they chart against closure counters like the fleet/* rows. Wire
    # one tensorboard_consumer per tenant through a NamespacedWriter
    # override ({tenant}/serve/..., {tenant}/train/...) for per-tenant
    # charts; the orchestrator/* rows below are fleet-of-jobs facts and
    # belong on the shared (un-prefixed) board.
    admit_counts = [0]
    halt_counts = [0]
    preempt_counts = [0]
    arbitrate_counts = [0]

    @consumer.handler
    def on_job_admitted(event: JobAdmitted,
                        board: SummaryWriter = Depends(writer)) -> None:
        admit_counts[0] += 1
        board.add_scalar('orchestrator/jobs_admitted',
                         float(admit_counts[0]), admit_counts[0])
        board.add_scalar('orchestrator/admitted_chips', float(event.chips),
                         admit_counts[0])

    @consumer.handler
    def on_job_halted(event: JobHalted,
                      board: SummaryWriter = Depends(writer)) -> None:
        halt_counts[0] += 1
        board.add_scalar('orchestrator/jobs_halted', float(halt_counts[0]),
                         halt_counts[0])
        board.add_scalar('orchestrator/halt_code', float(event.code),
                         halt_counts[0])

    @consumer.handler
    def on_job_preempted(event: JobPreempted,
                         board: SummaryWriter = Depends(writer)) -> None:
        preempt_counts[0] += 1
        board.add_scalar('orchestrator/preemptions',
                         float(preempt_counts[0]), preempt_counts[0])
        board.add_scalar('orchestrator/preempted_chips', float(event.chips),
                         preempt_counts[0])

    @consumer.handler
    def on_capacity_arbitrated(event: CapacityArbitrated,
                               board: SummaryWriter = Depends(writer)
                               ) -> None:
        arbitrate_counts[0] += 1
        board.add_scalar('orchestrator/arbitrations',
                         float(arbitrate_counts[0]), arbitrate_counts[0])
        board.add_scalar('orchestrator/arbitration_seconds', event.seconds,
                         arbitrate_counts[0])

    return consumer
