"""Crash flight recorder: the last seconds of a process, on disk.

A worker that exits 42/43/44/45 — or is SIGKILLed outright — takes its
final state with it: which requests were mid-decode, what the last ticks
emitted, which span was open. The supervisor recovers the *journal*, but
nothing showed what the worker itself saw. This module is the black box:

* a **bounded ring** of recent entries (events off the bus via
  :meth:`FlightRecorder.tap`, finished spans via :meth:`watch`, manual
  :meth:`note` breadcrumbs) — small host values only, O(capacity) RAM;
* **write-ahead persistence** — every ``cadence`` entries the ring is
  dumped to ``path`` by atomic rename, so even a SIGKILL (which runs no
  handler at all) leaves the last-dumped state on disk. The acceptance
  drill SIGKILLs a serving worker and matches the post-mortem's last
  entries against the journal the Supervisor recovered;
* **dump on the restart contract** — :meth:`install` registers the
  recorder so :func:`tpusystem.parallel.recovery.exit_for_restart`
  flushes it (with the exit verdict stamped) on every typed 42/43/44
  exit, and the serving watchdog path
  (:class:`~tpusystem.serve.ServingReplica`) dumps on an
  ``EngineStalled`` verdict;
* the **Supervisor attaches it** — pass ``flight_path=`` to
  :class:`~tpusystem.parallel.Supervisor` and the worker inherits the
  path via ``TPUSYSTEM_FLIGHT`` (:meth:`FlightRecorder.from_env`); after
  every worker exit the supervisor reads the post-mortem back and
  carries it on the ``WorkerExited`` event, so "what the worker saw"
  rides the same bus as the verdict about it.

Entries are plain dicts ``{'t': clock(), 'kind': ..., **payload}`` with
already-materialized host values — the bus discipline, applied to the
black box.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import pathlib
import threading
import time
from typing import Any, Callable

logger = logging.getLogger('tpusystem.observe.flight')

__all__ = ['FlightRecorder', 'dump_installed']

ENV_FLIGHT = 'TPUSYSTEM_FLIGHT'

# recorders armed for the restart-contract dump (exit_for_restart calls
# dump_installed); module-level on purpose — the exit path cannot thread
# a recorder handle through every raise site
_installed: list['FlightRecorder'] = []


class FlightRecorder:
    """Bounded ring of recent events/spans with write-ahead dumps.

    Args:
        path: the post-mortem file. None records in RAM only (dump
            explicitly with :meth:`dump`).
        capacity: ring size — older entries fall off.
        cadence: dump every N entries (1 = write-ahead on every entry,
            the SIGKILL-proof setting; larger trades durability window
            for fewer writes, exactly the journal's cadence contract).
        process: label stamped into the file.
        clock: injectable wall-time source (the usual discipline).
    """

    def __init__(self, path: str | os.PathLike | None = None, *,
                 capacity: int = 256, cadence: int = 1,
                 process: str = 'worker',
                 clock: Callable[[], float] = time.monotonic) -> None:
        if capacity < 1 or cadence < 1:
            raise ValueError(
                f'capacity and cadence must be >= 1, got {capacity}/{cadence}')
        self.path = pathlib.Path(path) if path is not None else None
        self.capacity = capacity
        self.cadence = cadence
        self.process = process
        self.clock = clock
        self.ring: collections.deque = collections.deque(maxlen=capacity)
        self.notes = 0
        self._write_failed = False
        # entries arrive from scheduler loops, supervisor threads and bus
        # dispatch at once; the lock covers ring mutation AND the dump's
        # snapshot so a mid-iteration append can't crash the black box
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, env: dict | None = None,
                 **kwargs: Any) -> 'FlightRecorder | None':
        """The worker-side constructor: a recorder at the path the
        supervisor exported (``TPUSYSTEM_FLIGHT``), or None when
        unsupervised / recording is off."""
        path = (env if env is not None else os.environ).get(ENV_FLIGHT)
        return None if not path else cls(path, **kwargs)

    # ------------------------------------------------------------- intake

    def note(self, kind: str, **payload: Any) -> None:
        """Append one entry and persist at the cadence. Entries are
        sanitized at intake: one non-JSON-able breadcrumb must not
        poison every later dump of the whole ring (which would silently
        void the write-ahead SIGKILL guarantee for up to ``capacity``
        entries) — it degrades to its repr, alone."""
        entry = {'t': round(self.clock(), 6), 'kind': kind, **payload}
        try:
            json.dumps(entry)
        except (TypeError, ValueError):
            entry = {'t': entry['t'], 'kind': kind,
                     'unserializable': repr(payload)[:200]}
        with self._lock:
            self.ring.append(entry)
            self.notes += 1
            due = self.path is not None and self.notes % self.cadence == 0
        if due:
            self.dump()

    def record(self, message: Any) -> None:
        """Producer tap: fold a bus event into the ring, keeping only
        its stable host-value fields (the ledger's rule — ints, strings,
        bools, floats, None; payload objects like model aggregates stay
        out of the black box)."""
        import dataclasses
        payload = {}
        if dataclasses.is_dataclass(message):
            for field in dataclasses.fields(message):
                value = getattr(message, field.name, None)
                if isinstance(value, (int, float, str, bool, type(None))):
                    payload[field.name] = value
        self.note(type(message).__name__, **payload)

    def tap(self, producer: Any) -> 'FlightRecorder':
        """Observe every dispatch on a producer (the ledger's hook)."""
        producer.taps.append(self.record)
        return self

    def watch(self, tracer: Any) -> 'FlightRecorder':
        """Fold every span the tracer finishes into the ring. An
        existing sink is chained, not replaced — watching must not
        silently disconnect another consumer."""
        previous = tracer.sink

        def on_span(span: Any) -> None:
            self.note('span', name=span.name, cat=span.cat,
                      trace_id=span.trace_id, span_id=span.span_id,
                      seconds=(None if span.end is None
                               else round(span.end - span.start, 6)))
            if previous is not None:
                previous(span)
        tracer.sink = on_span
        return self

    # -------------------------------------------------------------- dump

    def dump(self, path: str | os.PathLike | None = None,
             **stamp: Any) -> pathlib.Path | None:
        """Write the ring as JSON (atomic rename — a reader, e.g. the
        supervisor picking up a post-mortem, never sees a torn file).
        ``stamp`` adds verdict fields (``reason='preempted'``...).
        Write failures degrade and log once: the black box must never
        take the process down."""
        target = pathlib.Path(path) if path is not None else self.path
        if target is None:
            return None
        with self._lock:                 # snapshot: a concurrent note()
            entries = list(self.ring)    # must not mutate mid-iteration
        payload = {'process': self.process,
                   'dumped_at': round(self.clock(), 6),
                   'entries': entries, **stamp}
        # OSError: filesystem trouble; TypeError/ValueError: a caller's
        # non-JSON-able breadcrumb — either way degrade and log once, the
        # black box must never take the process down
        try:
            serialized = json.dumps(payload)
            target.parent.mkdir(parents=True, exist_ok=True)
            tmp = target.with_name(target.name + '.tmp')
            tmp.write_text(serialized)
            tmp.replace(target)
        except (OSError, TypeError, ValueError) as error:
            if not self._write_failed:
                logger.warning('flight-recorder dump to %s failed (%s); '
                               'recording continues in RAM', target, error)
            self._write_failed = True
            return None
        self._write_failed = False
        return target

    @staticmethod
    def read(path: str | os.PathLike) -> dict | None:
        """A post-mortem back from disk, or None (missing/torn — a
        worker that died before its first dump left nothing)."""
        try:
            return json.loads(pathlib.Path(path).read_text())
        except (OSError, ValueError):
            return None

    # ----------------------------------------------------- exit contract

    def install(self) -> 'FlightRecorder':
        """Arm this recorder for the restart-contract dump:
        :func:`tpusystem.parallel.recovery.exit_for_restart` calls
        :func:`dump_installed` with the verdict before returning its
        ``SystemExit``, so a typed 42/43/44 exit always flushes the
        black box (a SIGKILL relies on the write-ahead cadence
        instead)."""
        if self not in _installed:
            _installed.append(self)
        return self

    def uninstall(self) -> None:
        if self in _installed:
            _installed.remove(self)


def dump_installed(**stamp: Any) -> None:
    """Flush every installed recorder (the exit-contract hook); never
    raises — the process is already on its way out."""
    for recorder in list(_installed):
        try:
            recorder.dump(**stamp)
        except Exception:                        # pragma: no cover
            logger.exception('flight-recorder exit dump failed')
