"""Experiment-tracking consumer: metrics, metadata, and weights persistence.

Reference parity: ``examples/tinysys/tinysys/services/storage.py`` — fully
event-driven tracking. ``Trained``/``Validated`` persist the metric values;
``Iterated`` advances the model row's epoch and records registry metadata
for the aggregate's constituent modules and the phase loaders. Weight
snapshots live in the separate :func:`checkpoint_consumer` (collective
sharded saves must run on every host; the metadata stores here are
``primary_only``).

Conventions:
* the aggregate's ``id`` is its registry hash (string);
* an aggregate may expose ``modules() -> dict[kind, object]`` returning its
  registered parts (network, criterion, optimizer); kinds are free-form;
* ``Iterated.loaders`` may be a ``dict[phase, loader]`` of registered
  loaders.

All dependencies are DI seams overridden at the composition root — tests
inject fakes exactly like the reference's
``examples/tinysys/tests/test_storage.py:33-66``.
"""

from __future__ import annotations

from typing import Any

from tpusystem.observe.events import Iterated, Trained, Validated
from tpusystem.registry import getarguments, gethash, getname
from tpusystem.services.prodcon import Consumer, Depends
from tpusystem.storage import ports


def experiment() -> str:
    """Name of the current experiment (override at composition root)."""
    return 'default'


def metrics_store() -> ports.Metrics:
    raise NotImplementedError('override the metrics store dependency')


def models_store() -> ports.Models:
    raise NotImplementedError('override the models store dependency')


def modules_store() -> ports.Modules:
    raise NotImplementedError('override the modules store dependency')


def iterations_store() -> ports.Iterations:
    raise NotImplementedError('override the iterations store dependency')


def repository() -> Any:
    """Weight repository (:class:`tpusystem.checkpoint.Repository`)."""
    raise NotImplementedError('override the repository dependency')


def _metadata(obj: Any) -> tuple[str | None, str, dict]:
    """(hash, name, arguments) for a registered object; unregistered objects
    degrade to their class name (the reference raises — degrading keeps
    tracking usable for ad-hoc parts)."""
    try:
        return gethash(obj), getname(obj), getarguments(obj)
    except AttributeError:
        return None, obj.__class__.__name__, {}


def tracking_consumer() -> Consumer:
    consumer = Consumer('tracking')

    @consumer.handler
    def handle_metrics(event: Trained | Validated,
                       metrics: ports.Metrics = Depends(metrics_store)) -> None:
        phase = 'train' if isinstance(event, Trained) else 'evaluation'
        for name, value in event.metrics.items():
            metrics.add(ports.Metric(
                model=str(event.model.id), name=name, value=float(value),
                epoch=getattr(event.model, 'epoch', 0), phase=phase))

    @consumer.handler
    def handle_epoch(event: Iterated,
                     models: ports.Models = Depends(models_store),
                     name: str = Depends(experiment)) -> None:
        models.update(ports.Model(
            hash=str(event.model.id), experiment=name,
            epoch=getattr(event.model, 'epoch', 0)))

    @consumer.handler
    def handle_modules(event: Iterated,
                       modules: ports.Modules = Depends(modules_store)) -> None:
        parts = getattr(event.model, 'modules', None)
        if not callable(parts):
            return
        epoch = getattr(event.model, 'epoch', 0)
        for kind, part in parts().items():
            digest, alias, arguments = _metadata(part)
            modules.put(ports.Module(
                model=str(event.model.id), kind=kind, hash=digest,
                name=alias, arguments=arguments, epoch=epoch))

    @consumer.handler
    def handle_iterations(event: Iterated,
                          iterations: ports.Iterations = Depends(iterations_store)) -> None:
        if not isinstance(event.loaders, dict):
            return
        epoch = getattr(event.model, 'epoch', 0)
        for phase, loader in event.loaders.items():
            digest, alias, arguments = _metadata(loader)
            iterations.put(ports.Iteration(
                model=str(event.model.id), phase=str(phase), hash=digest,
                name=alias, arguments=arguments, epoch=epoch))

    return consumer


def checkpoint_consumer() -> Consumer:
    """Weight snapshots on every ``Iterated`` edge.

    Deliberately separate from :func:`tracking_consumer`: sharded checkpoint
    saves are *collective* (each host writes the array shards it owns), so
    this consumer must register on **every** host, while the metadata stores
    above are ``primary_only``. Registering it primary-only on a pod would
    deadlock rank 0 on the save barrier."""
    consumer = Consumer('checkpoint')

    @consumer.handler
    def save_weights(event: Iterated,
                     weights: Any = Depends(repository)) -> None:
        weights.store(event.model)

    return consumer
