"""Tracing/profiling subsystem.

The reference has none — its nearest artifacts are TensorBoard scalars and
per-100-batch loss logs (SURVEY.md §5 "tracing/profiling: absent"). Here
profiling is a first-class citizen with two faces:

- **device plane** — :func:`trace` / :func:`annotate` / :func:`step_span`
  wrap ``jax.profiler`` so XLA traces (HLO timelines, memory, TPU util)
  land in a TensorBoard-readable logdir. Annotations are zero-cost when no
  trace is active, so they stay in production code.
- **bus plane** — :class:`StepTimer` measures host wall-clock around jitted
  step spans and emits :class:`~tpusystem.observe.events.StepTimed` events;
  any consumer (logging, storage, TensorBoard) observes throughput without
  the trainer knowing its observers — the reference's architecture point,
  applied to profiling.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Iterator

import jax

from tpusystem.observe.events import StepTimed
from tpusystem.services.prodcon import Producer


class ProfilerBusy(RuntimeError):
    """``jax.profiler.start_trace`` refused — almost always because a
    trace is already active (nested :func:`trace`, or a leftover from a
    span that never stopped). Typed so callers can skip-or-queue instead
    of crashing, and so the ORIGINAL error is what surfaces — the old
    code ran ``stop_trace`` in its ``finally`` even when the start had
    failed, masking the real problem with a second 'no trace running'
    error."""


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a device trace (XLA timeline, memory viewer) for the enclosed
    span into ``logdir``; open with TensorBoard's profile plugin.

    Only a trace this context actually *started* is stopped on exit: a
    failed start (e.g. a trace already active) raises the typed
    :exc:`ProfilerBusy` and leaves the pre-existing trace untouched."""
    try:
        jax.profiler.start_trace(logdir)
    except RuntimeError as error:
        raise ProfilerBusy(
            f'jax.profiler.start_trace({logdir!r}) refused: {error} — a '
            f'device trace is already active; stop it (or nest '
            f'annotate()/step_span() instead, which compose)') from error
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str) -> Any:
    """Named span on the host timeline of an active trace (no-op otherwise)."""
    return jax.profiler.TraceAnnotation(name)


def step_span(name: str, step: int) -> Any:
    """Step-correlated span: lets the profiler group device ops per training
    step (``jax.profiler.StepTraceAnnotation``)."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


class StepTimer:
    """Wall-clock throughput measurement around step spans.

    The timer brackets a *span* of steps — never a single one; timing a
    single step would force a device sync per step and destroy MFU
    (SURVEY.md §7.3 "keeping the bus off the hot path"). ``stop`` blocks on
    ``result`` (any device value from the last step) so the measurement
    covers real device work rather than async dispatch.

    Example::

        timer = StepTimer(producer)
        timer.start()
        for batch in loader:
            state, out = step(state, batch)
        timer.stop(model, 'train', steps=len(loader), result=out)
    """

    def __init__(self, producer: Producer | None = None) -> None:
        self.producer = producer
        self._started: float | None = None

    def start(self) -> 'StepTimer':
        self._started = time.perf_counter()
        return self

    def stop(self, model: Any, phase: str, steps: int,
             result: Any = None) -> StepTimed:
        if self._started is None:
            raise RuntimeError('StepTimer.stop() without start()')
        if result is not None:
            jax.block_until_ready(result)
        timed = StepTimed(model=model, phase=phase, steps=steps,
                          seconds=time.perf_counter() - self._started)
        self._started = None
        if self.producer is not None:
            self.producer.dispatch(timed)
        return timed
